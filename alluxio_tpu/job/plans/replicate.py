"""Block-level replication adjustment plans.

Re-design of ``job/server/src/main/java/alluxio/job/plan/replicate/
{ReplicateDefinition,EvictDefinition,MoveDefinition}.java``: each plan
targets ONE block and adjusts where its cached copies live — replicate
fans a copy out to N more workers, evict drops it from N workers, move
relocates it between workers/tiers. Driven by the master's
ReplicationChecker (reference: ``ReplicationChecker.java:57``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from alluxio_tpu.job.plan import (
    PlanDefinition, RegisteredJobWorker, RunTaskContext, SelectContext,
)
from alluxio_tpu.utils.exceptions import (
    InvalidArgumentError, NotFoundError, UnavailableError,
)


def _local_block_worker(ctx: RunTaskContext):
    # include_quarantined: this resolves the co-located worker to talk
    # TO, not a placement choice — an evict task must still find a
    # quarantined holder, and a replicate target quarantined between
    # select and run is still alive to receive
    for w in ctx.fs.block_master.get_worker_infos(
            include_quarantined=True):
        if w.address.tiered_identity.value("host") == ctx.hostname:
            return w
    raise UnavailableError(
        f"no block worker co-located with job worker {ctx.hostname}")


class ReplicateDefinition(PlanDefinition):
    name = "replicate"
    # receiving a new copy is valid on any non-holder; a holder re-run
    # is a no-op the checker cleans up next tick
    relocatable = True

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext) -> List[Tuple[int, Any]]:
        block_id = config.get("block_id")
        replicas = int(config.get("replicas", 1))
        if block_id is None:
            raise InvalidArgumentError("replicate job requires 'block_id'")
        info = ctx.block_master.get_block_info(block_id)
        if not info.locations and not config.get("ufs"):
            raise NotFoundError(
                f"block {block_id} has no cached copy to replicate from")
        have = {loc.address.tiered_identity.value("host")
                for loc in info.locations}
        live = ctx.live_hosts()
        missing = [w for w in sorted(workers, key=lambda w: w.worker_id)
                   if w.hostname not in have and w.hostname in live]
        chosen = missing[:replicas]
        if not chosen:
            return []
        args = {"block_id": block_id, "length": info.length,
                "ufs": config.get("ufs")}
        return [(w.worker_id, args) for w in chosen]

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        block_id = task_args["block_id"]
        local = _local_block_worker(ctx)
        client = ctx.fs.store.worker_client(local.address)
        ufs = task_args.get("ufs")
        if ufs:
            client.async_cache(block_id, ufs["ufs_path"], ufs["offset"],
                               ufs["length"], ufs.get("mount_id", 0))
            from alluxio_tpu.job.plans.load import LoadDefinition

            LoadDefinition._await_commit(ctx.fs.block_master, block_id,
                                         ctx.hostname)
        else:
            info = ctx.fs.block_master.get_block_info(block_id)
            if not info.locations:
                raise NotFoundError(f"block {block_id} evaporated")
            src = info.locations[0].address
            data = ctx.fs.store.worker_client(src).read_block_bytes(block_id)
            client.write_block(block_id, ctx.fs.store.session_id, data)
        return {"replicated": block_id, "to": ctx.hostname}


class EvictDefinition(PlanDefinition):
    name = "evict"

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext) -> List[Tuple[int, Any]]:
        block_id = config.get("block_id")
        replicas = int(config.get("replicas", 1))  # how many copies to drop
        if block_id is None:
            raise InvalidArgumentError("evict job requires 'block_id'")
        info = ctx.block_master.get_block_info(block_id)
        have = {loc.address.tiered_identity.value("host")
                for loc in info.locations}
        holders = [w for w in sorted(workers, key=lambda w: w.worker_id)
                   if w.hostname in have]
        args = {"block_id": block_id}
        return [(w.worker_id, args) for w in holders[:replicas]]

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        block_id = task_args["block_id"]
        local = _local_block_worker(ctx)
        ctx.fs.store.worker_client(local.address).remove_block(block_id)
        return {"evicted": block_id, "from": ctx.hostname}


class MoveDefinition(PlanDefinition):
    name = "move"

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext) -> List[Tuple[int, Any]]:
        block_id = config.get("block_id")
        dst_host = config.get("destination_host")
        if block_id is None or not dst_host:
            raise InvalidArgumentError(
                "move job requires 'block_id' and 'destination_host'")
        targets = [w for w in workers if w.hostname == dst_host]
        if not targets:
            raise UnavailableError(f"no job worker on host {dst_host}")
        return [(targets[0].worker_id, {"block_id": block_id})]

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        block_id = task_args["block_id"]
        info = ctx.fs.block_master.get_block_info(block_id)
        sources = [loc.address for loc in info.locations
                   if loc.address.tiered_identity.value("host")
                   != ctx.hostname]
        if not sources:
            return {"moved": block_id, "to": ctx.hostname, "noop": True}
        local = _local_block_worker(ctx)
        client = ctx.fs.store.worker_client(local.address)
        already = any(loc.address.tiered_identity.value("host")
                      == ctx.hostname for loc in info.locations)
        if not already:
            data = ctx.fs.store.worker_client(sources[0]).read_block_bytes(
                block_id)
            client.write_block(block_id, ctx.fs.store.session_id, data)
        for src in sources:
            ctx.fs.store.worker_client(src).remove_block(block_id)
        return {"moved": block_id, "to": ctx.hostname}
