"""StressBench job: fan a stress benchmark out over the job workers.

Re-design of ``job/server/src/main/java/alluxio/job/plan/stress/
StressBenchDefinition.java`` + the ``--cluster`` mode of
``stress/shell/.../cli/Benchmark.java:133``: the job master assigns the
same bench spec to every job worker; each runs it against the LIVE
cluster through its own client and returns its JSON summary; join
aggregates throughput (sum) and latency (worst percentiles) — the
distributed counterpart of running a stress CLI on N client hosts.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from alluxio_tpu.job.plan import (
    PlanDefinition, RegisteredJobWorker, RunTaskContext, SelectContext,
)
from alluxio_tpu.utils.exceptions import (
    InvalidArgumentError, UnavailableError,
)

#: bench name -> runner; each runs against an EXISTING cluster via the
#: job worker's own FileSystem client
_BENCHES = ("worker", "master")


class StressBenchDefinition(PlanDefinition):
    name = "stressbench"

    def select_executors(self, config: Dict[str, Any],
                         workers: List[RegisteredJobWorker],
                         ctx: SelectContext) -> List[Tuple[int, Any]]:
        bench = config.get("bench")
        if bench not in _BENCHES:
            raise InvalidArgumentError(
                f"stressbench requires 'bench' in {_BENCHES}")
        if not workers:
            raise UnavailableError("no job workers registered")
        n = int(config.get("cluster_limit", 0)) or len(workers)
        chosen = sorted(workers, key=lambda w: w.worker_id)[:n]
        return [(w.worker_id, {"task_index": i, "n_tasks": len(chosen)})
                for i, w in enumerate(chosen)]

    def run_task(self, config: Dict[str, Any], task_args: Any,
                 ctx: RunTaskContext) -> Any:
        import json

        bench = config["bench"]
        opts = dict(config.get("options", {}))
        # each task works under its own namespace dir so N workers
        # don't contend on one parent inode
        idx = task_args["task_index"]
        base = opts.pop("base_path", "/stress-dist")
        if bench == "worker":
            from alluxio_tpu.stress import worker_bench

            result = worker_bench.run(
                mode=opts.pop("mode", "random"), master=None,
                _reuse_fs=ctx.fs, base_path=f"{base}/t{idx}", **opts)
        else:
            from alluxio_tpu.stress import master_bench

            result = master_bench.run(
                op=opts.pop("op", "CreateFile"),
                base_path=f"{base}/t{idx}", _reuse_fs=ctx.fs, **opts)
        return json.loads(result.json_line())

    def join(self, config: Dict[str, Any],
             task_results: List[Any]) -> Any:
        results = [r for r in task_results if r]
        if not results:
            return {}
        agg: Dict[str, Any] = {
            "bench": results[0]["bench"],
            "tasks": len(results),
            "errors": sum(r.get("errors", 0) for r in results),
            "metrics": {},
        }
        m0 = results[0].get("metrics", {})
        for k in m0:
            vals = [r["metrics"].get(k, 0) for r in results
                    if isinstance(r["metrics"].get(k), (int, float))]
            if not vals:
                continue
            if k.endswith(("_us",)):  # latency: worst across tasks
                agg["metrics"][k] = max(vals)
            elif k in ("ops_per_s", "mb_per_s", "gb_per_s"):
                agg["metrics"][k] = round(sum(vals), 2)  # throughput
            else:
                agg["metrics"][k] = vals[0]
        return agg
