"""Job-service wire types.

Re-design of ``job/common/src/main/java/alluxio/job/wire/{JobInfo,TaskInfo,
Status,JobWorkerHealth}.java``: statuses form the same small lattice
(CREATED -> RUNNING -> COMPLETED | FAILED | CANCELED) and everything
serializes to msgpack-friendly dicts.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from alluxio_tpu.utils.wire import _NESTED, _wire_dataclass


class Status:
    CREATED = "CREATED"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"
    CANCELED = "CANCELED"

    FINISHED = (COMPLETED, FAILED, CANCELED)

    @staticmethod
    def is_finished(s: str) -> bool:
        return s in Status.FINISHED


@_wire_dataclass
@dataclass
class TaskInfo:
    """One task of a plan, bound to one job worker
    (reference: ``job/wire/TaskInfo.java``)."""

    job_id: int = 0
    task_id: int = 0
    worker_id: int = 0
    status: str = Status.CREATED
    error_message: str = ""
    result: Any = None
    args: Any = None


@_wire_dataclass
@dataclass
class JobInfo:
    """Plan or workflow status snapshot (reference: ``job/wire/
    {PlanInfo,WorkflowInfo}.java``)."""

    job_id: int = 0
    name: str = ""
    status: str = Status.CREATED
    error_message: str = ""
    result: Any = None
    tasks: List[TaskInfo] = field(default_factory=list)
    children: List[int] = field(default_factory=list)
    last_updated_ms: int = 0


_NESTED[("JobInfo", "tasks")] = TaskInfo


@_wire_dataclass
@dataclass
class JobWorkerHealth:
    """Job-worker load report shipped on each heartbeat
    (reference: ``job/wire/JobWorkerHealth.java``)."""

    worker_id: int = 0
    hostname: str = ""
    load_avg: float = 0.0
    task_pool_size: int = 0
    num_active_tasks: int = 0
    unfinished_tasks: int = 0


@dataclass
class JobCommand:
    """Command piggybacked on the heartbeat response (reference:
    ``grpc/job_master.proto`` RunTaskCommand/CancelTaskCommand/
    RegisterCommand)."""

    kind: str = ""  # run | cancel | register | set_throttle
    job_id: int = 0
    task_id: int = 0
    job_config: Optional[Dict[str, Any]] = None
    task_args: Any = None

    def to_wire(self) -> Dict[str, Any]:
        return {"kind": self.kind, "job_id": self.job_id,
                "task_id": self.task_id, "job_config": self.job_config,
                "task_args": self.task_args}

    @classmethod
    def from_wire(cls, d: Dict[str, Any]) -> "JobCommand":
        return cls(**d)
