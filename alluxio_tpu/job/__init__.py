"""Job service: background data-movement jobs (reference: ``job/``).

A job master accepts job configs, plans them into per-worker tasks via
``PlanDefinition.select_executors``, and job workers execute
``PlanDefinition.run_task`` — the two-phase SPI of
``job/server/src/main/java/alluxio/job/plan/PlanDefinition.java``.
"""

from alluxio_tpu.job.wire import JobInfo, Status, TaskInfo  # noqa: F401
