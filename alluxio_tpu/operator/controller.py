"""Dataset reconcile loop.

Re-design of the reference K8s operator's dataset controller
(``integration/kubernetes/operator/alluxio/api/v1alpha1/
dataset_types.go`` CRD + its reconcilers): a level-triggered loop that
makes the cluster match each ``Dataset`` CR —

  create  -> mount every ``spec.mounts`` entry under
             ``/datasets/<name>/``, set ``replication_min`` from
             ``spec.replicas``, and (``spec.prefetchStrategy: Eager``)
             submit ONE distributedLoad per spec generation
  scale   -> ``spec.replicas`` change re-sets ``replication_min``; the
             master's ReplicationChecker re-balances copies
  delete  -> free + unmount + drop our finalizer (the CR carries
             ``alluxio-tpu.io/dataset-protection`` so data detaches
             before the object vanishes)

Status is written back (phase, ufsTotal, cachedPercent,
observedGeneration) via the CRD status subresource, level-triggered
like the reference's requeue-on-diff loops.

CRD (install via ``deploy/kubernetes/dataset-crd.yaml``):
  group ``data.alluxio-tpu.io``, version ``v1alpha1``, kind ``Dataset``.

The API client is stdlib urllib against the API server (in-cluster:
service-account token + CA; tests: a fake HTTP API server) — no
kubernetes-python dependency, per the no-new-deps rule.
"""

from __future__ import annotations

import json
import logging
import os
import ssl
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from alluxio_tpu.utils.httperr import error_body

LOG = logging.getLogger(__name__)


class ConflictError(IOError):
    """409 from the API server: someone else wrote first. Benign —
    the next level-triggered pass re-reads and retries."""


GROUP = "data.alluxio-tpu.io"
VERSION = "v1alpha1"
PLURAL = "datasets"
FINALIZER = "alluxio-tpu.io/dataset-protection"

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class K8sApi:
    """Minimal typed access to the Dataset CRD (list / patch spec-level
    metadata / patch status subresource)."""

    def __init__(self, base_url: str = "", namespace: str = "",
                 token: str = "", ca_file: str = "",
                 timeout_s: float = 30.0) -> None:
        if not base_url:
            host = os.environ.get("KUBERNETES_SERVICE_HOST", "")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if host:
                base_url = f"https://{host}:{port}"
        if not base_url:
            raise ValueError("no API server: pass base_url or run "
                             "in-cluster")
        self.base = base_url.rstrip("/")
        self.namespace = namespace or self._default_namespace()
        self._token = token or self._sa_token()
        self._timeout = timeout_s
        ctx: Optional[ssl.SSLContext] = None
        if self.base.startswith("https://"):
            ctx = ssl.create_default_context(
                cafile=ca_file or (os.path.join(_SA_DIR, "ca.crt")
                                   if os.path.exists(
                                       os.path.join(_SA_DIR, "ca.crt"))
                                   else None))
        self._ctx = ctx

    @staticmethod
    def _default_namespace() -> str:
        ns_file = os.path.join(_SA_DIR, "namespace")
        if os.path.exists(ns_file):
            with open(ns_file) as f:
                return f.read().strip()
        return "default"

    @staticmethod
    def _sa_token() -> str:
        tok_file = os.path.join(_SA_DIR, "token")
        if os.path.exists(tok_file):
            with open(tok_file) as f:
                return f.read().strip()
        return ""

    # -- plumbing ------------------------------------------------------------
    def _call(self, method: str, path: str,
              body: Optional[dict] = None,
              content_type: str = "application/merge-patch+json") -> dict:
        url = self.base + path
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        if data is not None:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(req, timeout=self._timeout,
                                        context=self._ctx) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            detail = error_body(e, limit=300)
            if e.code == 409:
                raise ConflictError(
                    f"k8s {method} {path}: conflict {detail}") from None
            raise IOError(
                f"k8s {method} {path}: HTTP {e.code} {detail}") from None

    def _crd_path(self, name: str = "", sub: str = "") -> str:
        p = (f"/apis/{GROUP}/{VERSION}/namespaces/{self.namespace}"
             f"/{PLURAL}")
        if name:
            p += f"/{name}"
        if sub:
            p += f"/{sub}"
        return p

    # -- typed surface -------------------------------------------------------
    def list_datasets(self) -> List[dict]:
        return self._call("GET", self._crd_path()).get("items", [])

    def patch_metadata(self, name: str, metadata: dict) -> dict:
        return self._call("PATCH", self._crd_path(name),
                          {"metadata": metadata})

    def patch_status(self, name: str, status: dict) -> dict:
        return self._call("PATCH", self._crd_path(name, "status"),
                          {"status": status})


class DatasetController:
    """One reconcile pass = observe every Dataset CR, converge the
    cluster, write status. Level-triggered: safe to run as often as you
    like; every action is idempotent."""

    def __init__(self, api: K8sApi, fs, job_client=None,
                 mount_root: str = "/datasets") -> None:
        self._api = api
        self._fs = fs
        self._job = job_client
        self._root = mount_root.rstrip("/")
        #: dataset name -> generation whose prefetch was submitted
        self._prefetched: Dict[str, int] = {}

    # -- helpers -------------------------------------------------------------
    def _dataset_path(self, name: str) -> str:
        return f"{self._root}/{name}"

    def _mount_point(self, ds_name: str, mount: dict, idx: int) -> str:
        sub = mount.get("name") or mount.get("mountPoint", "").rstrip(
            "/").rsplit("/", 1)[-1] or f"mount{idx}"
        return f"{self._dataset_path(ds_name)}/{sub}"

    def _existing_mounts(self) -> Dict[str, dict]:
        return {m.alluxio_path: m for m in self._fs.get_mount_points()}

    # -- reconcile -----------------------------------------------------------
    def reconcile_once(self) -> int:
        """Returns the number of datasets acted on (for tests/metrics)."""
        acted = 0
        for ds in self._api.list_datasets():
            name = ds["metadata"]["name"]
            try:
                if self._reconcile_one(ds):
                    acted += 1
            except ConflictError as e:
                # another writer won; the next pass re-reads
                LOG.info("dataset %s: %s (will retry)", name, e)
            except Exception as e:  # noqa: BLE001 keep the loop alive
                LOG.exception("reconcile of dataset %s failed", name)
                try:
                    self._api.patch_status(name, {
                        "phase": "Failed",
                        "message": f"{type(e).__name__}: {e}"})
                except IOError:
                    pass
        return acted

    def _reconcile_one(self, ds: dict) -> bool:
        meta, spec = ds["metadata"], ds.get("spec", {})
        name = meta["name"]
        if meta.get("deletionTimestamp"):
            return self._teardown(ds)
        changed = self._ensure_finalizer(ds)
        # one observation per pass: the recursive listing and the mount
        # table feed replication, status AND mount pruning — re-reading
        # them per step would double the master load every tick
        mounts = self._existing_mounts()
        changed |= self._ensure_mounts(name, spec, mounts)
        files = self._walk_files(self._dataset_path(name))
        changed |= self._ensure_replication(name, spec, files)
        changed |= self._ensure_prefetch(name, meta, spec)
        self._write_status(name, meta, spec, files, mounts)
        return changed

    def _ensure_finalizer(self, ds: dict) -> bool:
        meta = ds["metadata"]
        fins = meta.get("finalizers") or []
        if FINALIZER in fins:
            return False
        # resourceVersion precondition: merge-patch replaces the array
        # wholesale, so a concurrent finalizer writer must 409 us (we
        # retry from a fresh read next pass) rather than be clobbered
        self._api.patch_metadata(meta["name"],
                                 {"finalizers": fins + [FINALIZER],
                                  "resourceVersion":
                                      meta.get("resourceVersion")})
        return True

    def _ensure_mounts(self, name: str, spec: dict,
                       existing: Dict[str, dict]) -> bool:
        changed = False
        desired = {}
        for i, m in enumerate(spec.get("mounts", [])):
            desired[self._mount_point(name, m, i)] = m
        for at, m in desired.items():
            if at in existing:
                continue
            parent = at.rsplit("/", 1)[0]
            self._fs.create_directory(parent, recursive=True,
                                      allow_exists=True)
            self._fs.mount(at, m["mountPoint"],
                           read_only=bool(m.get("readOnly")),
                           shared=bool(m.get("shared")),
                           properties=dict(m.get("options") or {}))
            LOG.info("dataset %s: mounted %s at %s", name,
                     m["mountPoint"], at)
            existing[at] = m
            changed = True
        # level-triggered both ways: a mount dropped from the spec is
        # freed + unmounted (stale creds/data must not stay exposed)
        prefix = self._dataset_path(name) + "/"
        for at in sorted(existing):
            if at.startswith(prefix) and at not in desired:
                try:
                    self._fs.free(at, recursive=True)
                except Exception:  # noqa: BLE001 best-effort
                    pass
                self._fs.unmount(at)
                existing.pop(at, None)
                LOG.info("dataset %s: unmounted %s (left the spec)",
                         name, at)
                changed = True
        return changed

    def _ensure_replication(self, name: str, spec: dict,
                            files: list) -> bool:
        replicas = spec.get("replicas")
        if replicas is None:
            return False
        # 0 is an explicit "release the copies": replication_min resets
        # so the checker stops re-creating them
        changed = False
        for info in files:
            if info.replication_min != int(replicas):
                self._fs.set_attribute(info.path,
                                       replication_min=int(replicas))
                changed = True
        return changed

    def _ensure_prefetch(self, name: str, meta: dict, spec: dict) -> bool:
        strategy = (spec.get("prefetchStrategy") or "Lazy").lower()
        if strategy not in ("eager", "always") or self._job is None:
            return False
        gen = int(meta.get("generation", 1))
        if self._prefetched.get(name) == gen:
            return False
        job_id = self._job.run({
            "type": "load", "path": self._dataset_path(name),
            "replication": int(spec.get("replicas") or 1),
            "recursive": True})
        self._prefetched[name] = gen
        LOG.info("dataset %s: submitted distributedLoad job %s "
                 "(generation %d)", name, job_id, gen)
        return True

    def _teardown(self, ds: dict) -> bool:
        meta = ds["metadata"]
        name = meta["name"]
        root = self._dataset_path(name)
        existing = self._existing_mounts()
        for at in sorted(existing):
            if at == root or at.startswith(root + "/"):
                try:
                    self._fs.free(at, recursive=True)
                except Exception:  # noqa: BLE001 freeing is best-effort
                    LOG.warning("dataset %s: free of %s failed",
                                name, at)
                self._fs.unmount(at)
                LOG.info("dataset %s: unmounted %s", name, at)
        try:
            self._fs.delete(root, recursive=True)
        except Exception:  # noqa: BLE001 already gone / never created
            pass
        fins = [f for f in (meta.get("finalizers") or [])
                if f != FINALIZER]
        self._api.patch_metadata(name, {
            "finalizers": fins,
            "resourceVersion": meta.get("resourceVersion")})
        self._prefetched.pop(name, None)
        return True

    # -- status --------------------------------------------------------------
    def _walk_files(self, path: str):
        try:
            infos = self._fs.list_status(path, recursive=True)
        except Exception:  # noqa: BLE001 nothing mounted yet
            return []
        return [i for i in infos if not i.folder]

    def _write_status(self, name: str, meta: dict, spec: dict,
                      files: list, mounts: Dict[str, dict]) -> None:
        total = sum(f.length for f in files)
        cached = sum(f.length * f.in_memory_percentage // 100
                     for f in files)
        n_mounts = len([
            at for at in mounts
            if at.startswith(self._dataset_path(name) + "/")
            or at == self._dataset_path(name)])
        phase = "Bound" if n_mounts >= len(spec.get("mounts", [])) \
            and spec.get("mounts") else "NotBound"
        self._api.patch_status(name, {
            "phase": phase,
            "ufsTotal": str(total),
            "cachedPercent": (100 * cached // total) if total else 0,
            "fileCount": len(files),
            "observedGeneration": int(meta.get("generation", 1)),
        })

    # -- loop ----------------------------------------------------------------
    def run_forever(self, interval_s: float = 10.0,
                    stop=None) -> None:
        while stop is None or not stop.is_set():
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 API server hiccup
                LOG.exception("reconcile pass failed")
            if stop is not None:
                stop.wait(interval_s)
            else:
                time.sleep(interval_s)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    from alluxio_tpu.client.file_system import FileSystem
    from alluxio_tpu.conf import Configuration

    p = argparse.ArgumentParser(
        prog="alluxio-tpu-operator",
        description="Dataset lifecycle controller (mount/prefetch/"
                    "replicate/teardown per Dataset CR)")
    p.add_argument("--master", required=True,
                   help="master host:port")
    p.add_argument("--job-master", default="",
                   help="job master host:port (default: the master's "
                        "host with the configured job-master port)")
    p.add_argument("--api-server", default="",
                   help="K8s API base URL (default: in-cluster)")
    p.add_argument("--namespace", default="")
    p.add_argument("--interval", type=float, default=10.0)
    p.add_argument("--once", action="store_true",
                   help="single reconcile pass (cron-style)")
    args = p.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    api = K8sApi(args.api_server, namespace=args.namespace)
    conf = Configuration()
    fs = FileSystem(args.master, conf=conf)
    from alluxio_tpu.conf import Keys
    from alluxio_tpu.rpc.job_service import JobMasterClient

    # job master co-deploys with the master: default to the SAME host
    # (not the conf default 'localhost' — the operator usually runs in
    # its own pod) with the configured job-master port
    job_addr = args.job_master
    if not job_addr:
        master_host = args.master.rsplit(":", 1)[0]
        job_addr = (f"{master_host}:"
                    f"{conf.get_int(Keys.JOB_MASTER_RPC_PORT)}")
    job = JobMasterClient(job_addr)
    ctl = DatasetController(api, fs, job)
    if args.once:
        ctl.reconcile_once()
        return 0
    ctl.run_forever(args.interval)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
