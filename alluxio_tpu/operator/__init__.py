"""Kubernetes dataset-lifecycle operator (reference:
``integration/kubernetes/operator/alluxio/`` — the 7.8k-LoC Go
controller-runtime operator with ``Dataset``/``AlluxioRuntime`` CRDs).

Env-adapted design: the Helm chart (``deploy/helm/alluxio-tpu``) owns
RUNTIME deployment (masters/workers as StatefulSet/DaemonSet), so the
operator here reconciles only the DATASET lifecycle — mount, prefetch,
replication, teardown — as a small Python control loop speaking the
Kubernetes REST API with the stdlib. See ``controller.py``.
"""

from alluxio_tpu.operator.controller import (  # noqa: F401
    DatasetController, K8sApi,
)
