"""UFS factory registry + per-process UFS manager.

Re-designs of ``underfs/UnderFileSystemFactoryRegistry.java`` (ServiceLoader
discovery -> here a plain scheme-keyed registry with entry-point-style
``register`` calls) and the UFS managers
(``core/server/common/.../underfs/{UfsManager,AbstractUfsManager}.java``):
mount-id-keyed cached instances shared by master/worker/job processes,
with per-UFS maintenance mode (reference: ``MasterUfsManager``).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from alluxio_tpu.underfs.base import UfsMode, UnderFileSystem
from alluxio_tpu.underfs.local import LocalUnderFileSystem
from alluxio_tpu.underfs.object_base import MemUnderFileSystem
from alluxio_tpu.utils.exceptions import NotFoundError, NotSupportedError

_FACTORIES: Dict[str, Callable[..., UnderFileSystem]] = {}
_LOCK = threading.Lock()


def register_factory(scheme: str, factory: Callable[..., UnderFileSystem]) -> None:
    with _LOCK:
        _FACTORIES[scheme] = factory


def _scheme_of(uri: str) -> str:
    if "://" in uri:
        return uri.split("://", 1)[0]
    return ""  # bare path -> local


def create_ufs(uri: str, properties: Optional[Dict[str, str]] = None) -> UnderFileSystem:
    scheme = _scheme_of(uri)
    with _LOCK:
        factory = _FACTORIES.get(scheme)
    if factory is None:
        raise NotSupportedError(f"no UFS factory for scheme {scheme!r} ({uri})")
    return factory(uri, properties)


def supported_schemes() -> Tuple[str, ...]:
    with _LOCK:
        return tuple(sorted(_FACTORIES))


# built-ins (reference: ServiceLoader META-INF/services entries per connector)
register_factory("", LocalUnderFileSystem)
register_factory("file", LocalUnderFileSystem)
register_factory("mem", MemUnderFileSystem)


#: optional connectors: (module, class name, schemes). ``schemes=None``
#: uses the class's own ``schemes`` attribute. hdfs needs a working
#: libhdfs (HADOOP_HOME) and probes at registration time.
_OPTIONAL_CONNECTORS = (
    ("alluxio_tpu.underfs.web", "WebUnderFileSystem", ("http", "https")),
    ("alluxio_tpu.underfs.s3", "S3UnderFileSystem", ("s3", "s3a")),
    ("alluxio_tpu.underfs.gcs", "GcsUnderFileSystem", ("gs", "gcs")),
    # oss/cos/kodo dispatch by dialect: the vendor's NATIVE auth when
    # <vendor>.dialect=native, the S3-compatible gateway otherwise
    ("alluxio_tpu.underfs.s3_compat", "create_oss_ufs", None),
    ("alluxio_tpu.underfs.s3_compat", "create_cos_ufs", None),
    ("alluxio_tpu.underfs.s3_compat", "create_kodo_ufs", None),
    # swift dispatches by dialect: Keystone-native when swift.auth.url
    # is set, S3-middleware gateway otherwise (underfs/swift.py)
    ("alluxio_tpu.underfs.swift", "create_swift_ufs", ("swift",)),
    ("alluxio_tpu.underfs.s3_compat", "ObsUnderFileSystem", None),
    ("alluxio_tpu.underfs.azure", "WasbUnderFileSystem", None),
    ("alluxio_tpu.underfs.azure", "AdlsUnderFileSystem", None),
    ("alluxio_tpu.underfs.ozone", "OzoneUnderFileSystem", None),
    ("alluxio_tpu.underfs.hdfs", "HdfsUnderFileSystem", ("hdfs",)),
    # REST dialect of the hdfs family: stdlib-only, always registers
    ("alluxio_tpu.underfs.webhdfs", "WebHdfsUnderFileSystem",
     ("webhdfs",)),
)


def _register_optional() -> None:
    """Connectors with extra deps register lazily and tolerate absence."""
    import importlib

    for module, cls_name, schemes in _OPTIONAL_CONNECTORS:
        try:
            cls = getattr(importlib.import_module(module), cls_name)
            for scheme in (schemes or cls.schemes):
                register_factory(scheme, cls)
        except Exception:  # noqa: BLE001 - dep absent: skip connector
            pass


_register_optional()


class UfsManager:
    """Mount-id-keyed cache of UFS instances (reference: AbstractUfsManager)."""

    def __init__(self) -> None:
        self._by_mount: Dict[int, UnderFileSystem] = {}
        self._roots: Dict[int, str] = {}
        self._modes: Dict[str, UfsMode] = {}  # ufs root -> mode
        self._lock = threading.RLock()

    def add_mount(self, mount_id: int, ufs_uri: str,
                  properties: Optional[Dict[str, str]] = None) -> UnderFileSystem:
        with self._lock:
            if mount_id in self._by_mount:
                return self._by_mount[mount_id]
            ufs = create_ufs(ufs_uri, properties)
            self._by_mount[mount_id] = ufs
            self._roots[mount_id] = ufs_uri
            return ufs

    def remove_mount(self, mount_id: int) -> None:
        with self._lock:
            ufs = self._by_mount.pop(mount_id, None)
            self._roots.pop(mount_id, None)
        if ufs is not None:
            ufs.close()

    def get(self, mount_id: int) -> UnderFileSystem:
        with self._lock:
            ufs = self._by_mount.get(mount_id)
        if ufs is None:
            raise NotFoundError(f"no UFS for mount id {mount_id}")
        return ufs

    def has(self, mount_id: int) -> bool:
        with self._lock:
            return mount_id in self._by_mount

    # -- maintenance mode (reference: MasterUfsManager ufs modes) ----------
    def set_ufs_mode(self, ufs_root: str, mode: UfsMode) -> None:
        with self._lock:
            self._modes[ufs_root] = mode

    def get_ufs_mode(self, ufs_root: str) -> UfsMode:
        with self._lock:
            return self._modes.get(ufs_root, UfsMode.READ_WRITE)

    def close(self) -> None:
        with self._lock:
            for ufs in self._by_mount.values():
                ufs.close()
            self._by_mount.clear()
            self._roots.clear()
