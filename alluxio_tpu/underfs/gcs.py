"""GCS UFS connector over the JSON API.

Re-design of ``underfs/gcs/src/main/java/alluxio/underfs/gcs/
GCSUnderFileSystem.java`` (jets3t-based in the reference): the TPU build
speaks the GCS JSON API directly (``storage/v1``), which is what TPU-VM
metadata-server tokens authorize. Endpoint-overridable for the in-process
fake server in tests.

Properties:
  gcs.endpoint  override (default https://storage.googleapis.com)
  gcs.token     static bearer token; when absent, tries the GCE metadata
                server (TPU VMs), then falls back to anonymous
"""

from __future__ import annotations

import os
import urllib.parse
from typing import Dict, List, Optional, Tuple

import requests

from alluxio_tpu.underfs.object_base import (
    ObjectStoreClient, ObjectUnderFileSystem,
)

_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")


class GcsJsonClient(ObjectStoreClient):
    def __init__(self, bucket: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        props = properties or {}
        self._bucket = bucket
        self._base = props.get(
            "gcs.endpoint", os.environ.get("ATPU_GCS_ENDPOINT",
                                           "https://storage.googleapis.com")
        ).rstrip("/")
        self._static_token = props.get("gcs.token", "")
        self._session = requests.Session()
        self._cached_token = ""
        self._token_expiry = 0.0

    def _headers(self) -> Dict[str, str]:
        import time

        tok = self._static_token
        if not tok and "googleapis.com" in self._base:
            if self._cached_token and time.monotonic() < self._token_expiry:
                tok = self._cached_token
            else:
                try:  # TPU-VM / GCE metadata token, cached until expiry
                    r = self._session.get(
                        _METADATA_TOKEN_URL,
                        headers={"Metadata-Flavor": "Google"}, timeout=2)
                    if r.ok:
                        body = r.json()
                        tok = body.get("access_token", "")
                        self._cached_token = tok
                        self._token_expiry = time.monotonic() + max(
                            30.0, float(body.get("expires_in", 300)) - 60.0)
                except requests.RequestException:
                    pass
        return {"Authorization": f"Bearer {tok}"} if tok else {}

    def _obj_url(self, key: str, alt_media: bool = False) -> str:
        u = (f"{self._base}/storage/v1/b/{self._bucket}/o/"
             f"{urllib.parse.quote(key, safe='')}")
        return u + "?alt=media" if alt_media else u

    def put(self, key: str, data: bytes) -> None:
        r = self._session.post(
            f"{self._base}/upload/storage/v1/b/{self._bucket}/o",
            params={"uploadType": "media", "name": key}, data=data,
            headers=self._headers(), timeout=60)
        r.raise_for_status()

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> Optional[bytes]:
        headers = self._headers()
        if offset or length is not None:
            end = "" if length is None else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._session.get(self._obj_url(key, alt_media=True),
                              headers=headers, timeout=60)
        if r.status_code == 404:
            return None
        if r.status_code == 416:
            return b""
        r.raise_for_status()
        return r.content

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        r = self._session.get(self._obj_url(key), headers=self._headers(),
                              timeout=30)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        meta = r.json()
        import datetime

        mtime = 0
        if meta.get("updated"):
            try:
                mtime = int(datetime.datetime.fromisoformat(
                    meta["updated"].replace("Z", "+00:00")
                ).timestamp() * 1000)
            except ValueError:
                pass
        return (int(meta.get("size", 0)), mtime, meta.get("etag", ""))

    def delete(self, key: str) -> bool:
        r = self._session.delete(self._obj_url(key),
                                 headers=self._headers(), timeout=30)
        return r.status_code in (200, 204)

    def copy(self, src_key: str, dst_key: str) -> bool:
        # rewriteTo may return done=false + rewriteToken for large objects;
        # loop until the rewrite completes or deletion of the source after a
        # half-finished copy would lose data
        url = (f"{self._base}/storage/v1/b/{self._bucket}/o/"
               f"{urllib.parse.quote(src_key, safe='')}/rewriteTo/b/"
               f"{self._bucket}/o/{urllib.parse.quote(dst_key, safe='')}")
        token = None
        for _ in range(64):
            params = {"rewriteToken": token} if token else {}
            r = self._session.post(url, params=params,
                                   headers=self._headers(), timeout=60)
            if not r.ok:
                return False
            body = r.json()
            if body.get("done", True):
                return True
            token = body.get("rewriteToken")
            if not token:
                return False
        return False

    def list_prefix(self, prefix: str) -> List[str]:
        keys: List[str] = []
        page_token = None
        while True:
            params = {"prefix": prefix, "maxResults": "1000"}
            if page_token:
                params["pageToken"] = page_token
            r = self._session.get(
                f"{self._base}/storage/v1/b/{self._bucket}/o",
                params=params, headers=self._headers(), timeout=30)
            r.raise_for_status()
            body = r.json()
            keys.extend(item["name"] for item in body.get("items", []))
            page_token = body.get("nextPageToken")
            if not page_token:
                break
        return keys


class GcsUnderFileSystem(ObjectUnderFileSystem):
    """``gs://bucket/...`` (reference: GCSUnderFileSystem)."""

    schemes = ("gs", "gcs")

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        rest = root_uri.split("://", 1)[1] if "://" in root_uri else root_uri
        bucket = rest.partition("/")[0]
        super().__init__(root_uri, GcsJsonClient(bucket, properties),
                         properties)
