"""Object-store UFS base: flat key space presented as a filesystem.

Re-design of ``core/common/src/main/java/alluxio/underfs/ObjectUnderFileSystem.java``:
directories are emulated with zero-byte breadcrumb markers (``dir/`` keys),
listing uses delimiter-style prefix scans, renames are copy+delete, and
multipart-style uploads stream through a buffer. Concrete stores implement
the small ``ObjectStoreClient`` protocol; ``MemObjectStore`` is the in-memory
test double (reference analogue: the mock object UFS used across tests),
and S3/GCS adapters layer HTTP clients over the same protocol.
"""

from __future__ import annotations

import io
import threading
import time
from typing import BinaryIO, Dict, Iterator, List, Optional, Tuple

from alluxio_tpu.underfs.base import (
    CreateOptions, DeleteOptions, UfsStatus, UnderFileSystem,
)

SEP = "/"
FOLDER_SUFFIX = "/"  # breadcrumb marker key suffix


class ObjectStoreClient:
    #: True when the client implements the multipart quartet
    #: (initiate_multipart / upload_part / complete_multipart /
    #: abort_multipart) + ``multipart_size`` — ``create()`` then
    #: streams large writes via :class:`MultipartWriter`. An explicit
    #: capability flag, not hasattr duck-guessing: a stray attribute
    #: must not route writes to a half-implemented surface.
    supports_multipart = False
    """Minimal blob-store protocol concrete stores implement."""

    def put(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> Optional[bytes]:
        raise NotImplementedError

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        """(length, last_modified_ms, etag) or None."""
        raise NotImplementedError

    def delete(self, key: str) -> bool:
        raise NotImplementedError

    def copy(self, src_key: str, dst_key: str) -> bool:
        raise NotImplementedError

    def list_prefix(self, prefix: str) -> List[str]:
        """All keys with the prefix (recursive)."""
        raise NotImplementedError


class MemObjectStore(ObjectStoreClient):
    """In-memory blob store; process-wide buckets so master and workers in
    one test process see the same data."""

    _BUCKETS: Dict[str, "MemObjectStore"] = {}
    _GLOBAL_LOCK = threading.Lock()

    @classmethod
    def bucket(cls, name: str) -> "MemObjectStore":
        with cls._GLOBAL_LOCK:
            if name not in cls._BUCKETS:
                cls._BUCKETS[name] = MemObjectStore()
            return cls._BUCKETS[name]

    @classmethod
    def reset_all(cls) -> None:
        with cls._GLOBAL_LOCK:
            cls._BUCKETS.clear()

    def __init__(self) -> None:
        self._objs: Dict[str, Tuple[bytes, int]] = {}
        self._lock = threading.RLock()

    def put(self, key: str, data: bytes) -> None:
        with self._lock:
            self._objs[key] = (bytes(data), int(time.time() * 1000))

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> Optional[bytes]:
        with self._lock:
            entry = self._objs.get(key)
        if entry is None:
            return None
        data = entry[0]
        end = len(data) if length is None else min(len(data), offset + length)
        return data[offset:end]

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        with self._lock:
            entry = self._objs.get(key)
        if entry is None:
            return None
        data, mtime = entry
        return (len(data), mtime, f"etag-{hash(data) & 0xFFFFFFFF:x}")

    def delete(self, key: str) -> bool:
        with self._lock:
            return self._objs.pop(key, None) is not None

    def copy(self, src_key: str, dst_key: str) -> bool:
        with self._lock:
            entry = self._objs.get(src_key)
            if entry is None:
                return False
            self._objs[dst_key] = (entry[0], int(time.time() * 1000))
            return True

    def list_prefix(self, prefix: str) -> List[str]:
        with self._lock:
            return sorted(k for k in self._objs if k.startswith(prefix))


class _ObjectWriter(io.BytesIO):
    def __init__(self, client: ObjectStoreClient, key: str) -> None:
        super().__init__()
        self._client = client
        self._key = key
        self.closed_ok = False

    def close(self) -> None:
        if not self.closed_ok:
            self._client.put(self._key, self.getvalue())
            self.closed_ok = True
        super().close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        return False


class MultipartWriter(io.RawIOBase):
    """Streaming writer over any client exposing the multipart quartet
    (``initiate_multipart``/``upload_part``/``complete_multipart``/
    ``abort_multipart`` + ``multipart_size``): buffers one part then
    ships; small files fall back to a single PUT (reference:
    S3ALowLevelOutputStream's short-circuit). Shared by the s3 client
    and the native OSS/COS dialects — their multipart wire protocols
    are S3-shaped."""

    def __init__(self, client, key: str) -> None:
        super().__init__()
        self._client = client
        self._key = key
        self._buf = bytearray()
        self._upload_id = None
        self._etags: List[tuple] = []
        self._part = 0
        self._closed = False

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self._buf.extend(b)
        while len(self._buf) >= self._client.multipart_size:
            self._ship(self._client.multipart_size)
        return len(b)

    def _ship(self, n: int) -> None:
        if self._upload_id is None:
            self._upload_id = self._client.initiate_multipart(self._key)
        self._part += 1
        chunk = bytes(self._buf[:n])
        del self._buf[:n]
        self._etags.append(
            (self._part,
             self._client.upload_part(self._key, self._upload_id,
                                      self._part, chunk)))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._upload_id is None:
                self._client.put(self._key, bytes(self._buf))
            else:
                if self._buf:
                    self._ship(len(self._buf))
                self._client.complete_multipart(self._key,
                                                self._upload_id,
                                                self._etags)
        except Exception:
            if self._upload_id is not None:
                self._client.abort_multipart(self._key, self._upload_id)
            raise
        finally:
            super().close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            if self._upload_id is not None:
                self._client.abort_multipart(self._key, self._upload_id)
            self._closed = True
        return False


class ObjectUnderFileSystem(UnderFileSystem):
    """Filesystem semantics over an ObjectStoreClient."""

    def __init__(self, root_uri: str, client: ObjectStoreClient,
                 properties: Optional[Dict[str, str]] = None) -> None:
        super().__init__(root_uri, properties)
        self._client = client
        scheme_sep = root_uri.find("://")
        rest = root_uri[scheme_sep + 3:] if scheme_sep >= 0 else root_uri
        bucket, _, prefix = rest.partition(SEP)
        self._bucket = bucket
        self._scheme = root_uri[:scheme_sep] if scheme_sep >= 0 else "mem"

    def _key(self, path: str) -> str:
        """Full UFS uri -> object key (strip scheme+bucket)."""
        p = path
        if "://" in p:
            p = p.split("://", 1)[1]
            p = p.partition(SEP)[2]
        return p.strip(SEP)

    def get_underfs_type(self) -> str:
        return self._scheme

    # -- IO -----------------------------------------------------------------
    def create(self, path: str, options: Optional[CreateOptions] = None) -> BinaryIO:
        if getattr(self._client, "supports_multipart", False):
            # large writes stream in parts instead of buffering whole
            return MultipartWriter(self._client, self._key(path))
        return _ObjectWriter(self._client, self._key(path))

    def open(self, path: str, offset: int = 0) -> BinaryIO:
        data = self._client.get(self._key(path), offset)
        if data is None:
            raise FileNotFoundError(path)
        return io.BytesIO(data)

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        data = self._client.get(self._key(path), offset, length)
        if data is None:
            raise FileNotFoundError(path)
        return data

    # -- namespace ----------------------------------------------------------
    def delete_file(self, path: str) -> bool:
        return self._client.delete(self._key(path))

    def delete_directory(self, path: str,
                         options: Optional[DeleteOptions] = None) -> bool:
        opts = options or DeleteOptions()
        key = self._key(path)
        marker = key + FOLDER_SUFFIX if key else ""
        children = [k for k in self._client.list_prefix(marker)
                    if k != marker] if key else self._client.list_prefix("")
        if children and not opts.recursive:
            return False
        for k in children:
            self._client.delete(k)
        if key:
            return self._client.delete(marker) or not children
        return True

    def rename_file(self, src: str, dst: str) -> bool:
        s, d = self._key(src), self._key(dst)
        if not self._client.copy(s, d):
            return False
        self._client.delete(s)
        return True

    def rename_directory(self, src: str, dst: str) -> bool:
        s, d = self._key(src), self._key(dst)
        keys = self._client.list_prefix(s + FOLDER_SUFFIX)
        marker = s + FOLDER_SUFFIX
        ok = True
        for k in keys:
            nk = d + FOLDER_SUFFIX + k[len(marker):] if k != marker else d + FOLDER_SUFFIX
            ok = self._client.copy(k, nk) and ok
            self._client.delete(k)
        if self._client.head(marker) is not None:
            self._client.copy(marker, d + FOLDER_SUFFIX)
            self._client.delete(marker)
        else:
            self._client.put(d + FOLDER_SUFFIX, b"")
        return ok

    def mkdirs(self, path: str, create_parent: bool = True) -> bool:
        key = self._key(path)
        if not key:
            return False
        if self._client.head(key + FOLDER_SUFFIX) is not None:
            return False
        parts = key.split(SEP)
        if create_parent:
            for i in range(1, len(parts)):
                self._client.put(SEP.join(parts[:i]) + FOLDER_SUFFIX, b"")
        self._client.put(key + FOLDER_SUFFIX, b"")
        return True

    # -- status -------------------------------------------------------------
    def get_status(self, path: str) -> Optional[UfsStatus]:
        key = self._key(path)
        if not key:
            return UfsStatus(name=path, is_directory=True)
        head = self._client.head(key)
        if head is not None:
            length, mtime, etag = head
            return UfsStatus(name=path, is_directory=False, length=length,
                             last_modified_ms=mtime, content_hash=etag)
        # directory: breadcrumb or implicit (any key under prefix)
        if self._client.head(key + FOLDER_SUFFIX) is not None or \
                self._client.list_prefix(key + SEP):
            return UfsStatus(name=path, is_directory=True)
        return None

    def list_status(self, path: str) -> Optional[List[UfsStatus]]:
        key = self._key(path)
        prefix = key + SEP if key else ""
        status = self.get_status(path)
        if status is None or not status.is_directory:
            return None
        names: Dict[str, UfsStatus] = {}
        for k in self._client.list_prefix(prefix):
            rest = k[len(prefix):]
            if not rest:
                continue  # the breadcrumb itself
            first, sep, _ = rest.partition(SEP)
            if sep:  # nested -> show the directory
                if first not in names:
                    names[first] = UfsStatus(name=first, is_directory=True)
            elif rest.endswith(FOLDER_SUFFIX):
                d = rest.rstrip(SEP)
                if d and d not in names:
                    names[d] = UfsStatus(name=d, is_directory=True)
            else:
                head = self._client.head(k)
                if head:
                    length, mtime, etag = head
                    names[rest] = UfsStatus(name=rest, length=length,
                                            last_modified_ms=mtime,
                                            content_hash=etag)
        return [names[n] for n in sorted(names)]


class MemUnderFileSystem(ObjectUnderFileSystem):
    """``mem://bucket/...`` — in-process object store for tests and the
    SleepingUFS-style fault injection wrapper."""

    schemes = ("mem",)

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        rest = root_uri.split("://", 1)[1] if "://" in root_uri else root_uri
        bucket = rest.partition(SEP)[0]
        super().__init__(root_uri, MemObjectStore.bucket(bucket), properties)
