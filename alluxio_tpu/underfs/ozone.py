"""Apache Ozone UFS connector.

Re-design of ``underfs/ozone/src/main/java/alluxio/underfs/ozone/
OzoneUnderFileSystem.java`` (the reference wraps the ``o3fs`` Hadoop
client over the OM RPC): the TPU build addresses Ozone through its S3
Gateway — part of every Ozone deployment — so the hardened SigV4 client
serves it with an endpoint remap instead of a Hadoop-RPC dependency.

URI forms (mirroring the reference's):
  ``o3fs://bucket.volume[.om-host[:port]]/path``  (bucket-rooted)
  ``ofs://om-host[:port]/volume/bucket/path``     (namespace-rooted;
                                                   the mount root must
                                                   be at/below a bucket)

The S3 gateway exposes each ``volume/bucket`` as the S3 bucket named
``bucket`` (the gateway is per-volume, configured by
``ozone.s3g.volume``), so both forms resolve to the bucket component.

Properties: ``ozone.endpoint`` (the S3 Gateway, e.g.
``http://s3g.host:9878``), ``ozone.access.key`` / ``ozone.secret.key``
(gateway credentials), falling back to the ``s3.*`` names.
"""

from __future__ import annotations

from typing import Dict, Optional

from alluxio_tpu.underfs.s3 import S3Client, S3UnderFileSystem
from alluxio_tpu.underfs.s3_compat import _remap


def _bucket_of(root_uri: str) -> str:
    scheme, _, rest = root_uri.partition("://")
    authority, _, path = rest.partition("/")
    if scheme == "ofs":
        # ofs://om/volume/bucket/... -> second path component
        parts = [p for p in path.split("/") if p]
        if len(parts) < 2:
            raise ValueError(
                f"ofs mount must reach a bucket: ofs://om/volume/bucket "
                f"(got {root_uri!r})")
        return parts[1]
    # o3fs://bucket.volume.om:9862/... -> first authority component
    return authority.split(".")[0]


class OzoneUnderFileSystem(S3UnderFileSystem):
    """Ozone via the S3 Gateway."""

    schemes = ("o3fs", "ofs")

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        # bypass S3UnderFileSystem.__init__'s bucket parse (the Ozone
        # authority embeds volume/OM components)
        bucket = _bucket_of(root_uri)
        from alluxio_tpu.underfs.object_base import ObjectUnderFileSystem

        ObjectUnderFileSystem.__init__(
            self, root_uri, self._make_client(bucket, properties),
            properties)
        self._bucket = bucket

    def _make_client(self, bucket: str,
                     properties: Optional[Dict[str, str]]) -> S3Client:
        props = _remap("ozone", properties)
        if "s3.path.style" not in props:
            props["s3.path.style"] = "true"  # the gateway is path-style
        return S3Client(bucket, props)

    def get_underfs_type(self) -> str:
        return "ozone"

    def _key(self, path: str) -> str:
        """Strip scheme+authority, plus the volume component for ofs."""
        p = path
        if "://" in p:
            scheme, _, rest = p.partition("://")
            p = rest.partition("/")[2]
            if scheme == "ofs":
                # drop volume/bucket prefix components
                parts = p.split("/", 2)
                p = parts[2] if len(parts) > 2 else ""
        return p.strip("/")
