"""Native auth dialects for the Chinese-cloud object stores.

The reference ships SDK connectors per vendor —
``underfs/oss/.../OSSUnderFileSystem.java`` (Alibaba SDK, "OSS ak:sig"
header auth), ``underfs/cos/.../COSUnderFileSystem.java`` (Tencent SDK,
``q-sign-algorithm`` auth string), ``underfs/kodo/.../
KodoUnderFileSystem.java`` (Qiniu SDK, QBox tokens + private download
URLs). The TPU build already serves all three through their
S3-compatible gateways (``s3_compat.py``); these clients add the
vendors' NATIVE wire auth for deployments where the gateway is
unavailable or feature-gapped, selected with ``<vendor>.dialect =
native`` (the gateway remains the default, so existing configs keep
working).

Auth schemes implemented from the public API docs:
  OSS   Authorization: ``OSS <ak>:<b64(hmac-sha1(sk, VERB\\n MD5\\n
        Type\\n Date\\n CanonicalizedOSSHeaders CanonicalizedResource))>``
  COS   Authorization: ``q-sign-algorithm=sha1&q-ak=..&q-sign-time=a;b&
        q-key-time=a;b&q-header-list=..&q-url-param-list=..&
        q-signature=<hmac-sha1 chain>``
  Kodo  management (rs/rsf): ``QBox <ak>:<urlsafe-b64(hmac-sha1(sk,
        path?query\\n body))>``; uploads: form upload with a signed
        PutPolicy uptoken; downloads: private-URL ``e=<deadline>&
        token=<ak>:<sig>`` against the bucket's download host.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate
from typing import Dict, List, Optional, Tuple

import requests

from alluxio_tpu.underfs.object_base import ObjectStoreClient


def _hmac_sha1(key: bytes, msg: bytes) -> bytes:
    return hmac.new(key, msg, hashlib.sha1).digest()


def _parse_http_date(value: Optional[str]) -> int:
    from alluxio_tpu.underfs.web import _parse_http_date as p

    return p(value) or 0


def _xml_keys(content: bytes) -> Tuple[List[str], bool, str]:
    """V1-style bucket listing XML -> (keys, truncated, next_marker)."""
    root = ET.fromstring(content)
    ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
    keys = [k.text for el in root.iter(f"{ns}Contents")
            for k in [el.find(f"{ns}Key")]
            if k is not None and k.text]
    trunc = root.find(f"{ns}IsTruncated")
    truncated = trunc is not None and trunc.text == "true"
    nm = root.find(f"{ns}NextMarker")
    next_marker = nm.text if nm is not None and nm.text else \
        (keys[-1] if truncated and keys else "")
    return keys, truncated, next_marker


class _XmlVendorClient(ObjectStoreClient):
    """Shared REST surface for the XML-API vendors (OSS, COS): the ops
    match S3's shapes; only auth and the copy header differ."""

    copy_header = ""
    supports_multipart = True

    def __init__(self, bucket: str, endpoint: str, ak: str, sk: str,
                 path_style: bool, multipart_size: int = 8 << 20) -> None:
        self._bucket = bucket
        self._ak, self._sk = ak, sk
        self._path_style = path_style
        self.multipart_size = multipart_size
        endpoint = endpoint.rstrip("/")
        self._base = (f"{endpoint}/{bucket}" if path_style else
                      endpoint.replace("://", f"://{bucket}."))
        self._host = urllib.parse.urlsplit(self._base).netloc
        self._session = requests.Session()

    def _uri_path(self, key: str) -> str:
        """The path as it appears ON THE WIRE — what signatures must
        cover (path-style requests carry the bucket segment)."""
        return (f"/{self._bucket}/{key}" if self._path_style
                else f"/{key}")

    # subclasses implement --------------------------------------------------
    def _auth(self, method: str, key: str, params: Dict[str, str],
              headers: Dict[str, str], data: bytes) -> None:
        raise NotImplementedError

    # -- plumbing ------------------------------------------------------------
    def _request(self, method: str, key: str = "", *, params=None,
                 data: bytes = b"", headers=None) -> requests.Response:
        params = dict(params or {})
        headers = dict(headers or {})
        self._auth(method, key, params, headers, data)
        url = self._base + "/" + urllib.parse.quote(key)
        if params:
            url += "?" + urllib.parse.urlencode(sorted(params.items()))
        return self._session.request(method, url, data=data or None,
                                     headers=headers, timeout=60)

    # -- ObjectStoreClient ---------------------------------------------------
    def put(self, key: str, data: bytes) -> None:
        self._request("PUT", key, data=data).raise_for_status()

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> Optional[bytes]:
        headers = {}
        if offset or length is not None:
            end = "" if length is None else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._request("GET", key, headers=headers)
        if r.status_code == 404:
            return None
        if r.status_code == 416:
            return b""
        r.raise_for_status()
        return r.content

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        r = self._request("HEAD", key)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return (int(r.headers.get("Content-Length", 0)),
                _parse_http_date(r.headers.get("Last-Modified")),
                r.headers.get("ETag", "").strip('"'))

    def delete(self, key: str) -> bool:
        return self._request("DELETE", key).status_code in (200, 204)

    def copy(self, src_key: str, dst_key: str) -> bool:
        src = f"/{self._bucket}/{urllib.parse.quote(src_key)}"
        return self._request("PUT", dst_key,
                             headers={self.copy_header: src}).ok

    def list_prefix(self, prefix: str) -> List[str]:
        # V1 marker paging — the native XML APIs have no V2
        # continuation tokens
        keys: List[str] = []
        marker = ""
        while True:
            params = {"prefix": prefix, "max-keys": "1000"}
            if marker:
                params["marker"] = marker
            r = self._request("GET", "", params=params)
            r.raise_for_status()
            page, truncated, marker = _xml_keys(r.content)
            keys.extend(page)
            if not truncated or not marker:
                return keys

    # -- multipart (both vendors' native multipart APIs are S3-shaped;
    # feeds the shared object_base.MultipartWriter) ----------------------
    def initiate_multipart(self, key: str) -> str:
        r = self._request("POST", key, params={"uploads": ""})
        r.raise_for_status()
        root = ET.fromstring(r.content)
        ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
        upload_id = root.find(f"{ns}UploadId")
        if upload_id is None or not upload_id.text:
            # fail HERE, not with an opaque 404 on the first part (or a
            # nonsense abort with an empty id)
            raise IOError(f"multipart initiate for {key!r}: response "
                          "carried no UploadId")
        return upload_id.text

    def upload_part(self, key: str, upload_id: str, part_number: int,
                    data: bytes) -> str:
        r = self._request("PUT", key, params={
            "partNumber": str(part_number), "uploadId": upload_id},
            data=data)
        r.raise_for_status()
        return r.headers.get("ETag", "").strip('"')

    def complete_multipart(self, key: str, upload_id: str,
                           etags: List[Tuple[int, str]]) -> None:
        body = "<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
            for n, e in etags) + "</CompleteMultipartUpload>"
        r = self._request("POST", key, params={"uploadId": upload_id},
                          data=body.encode())
        r.raise_for_status()

    def abort_multipart(self, key: str, upload_id: str) -> None:
        self._request("DELETE", key, params={"uploadId": upload_id})


class OssNativeClient(_XmlVendorClient):
    """Alibaba OSS header signing (SDK analogue:
    ``OSSUnderFileSystem.java``)."""

    copy_header = "x-oss-copy-source"
    #: query params that are SIGNED subresources per the OSS spec
    #: (prefix/marker/max-keys are NOT — they stay out of the
    #: CanonicalizedResource)
    _SIGNED_SUBRESOURCES = ("partNumber", "uploadId", "uploads")

    def _auth(self, method, key, params, headers, data) -> None:
        date = formatdate(usegmt=True)
        headers["Date"] = date
        headers["Host"] = self._host
        if data:
            headers["Content-MD5"] = base64.b64encode(
                hashlib.md5(data).digest()).decode()
        oss_headers = "".join(
            f"{k.lower()}:{v}\n" for k, v in sorted(headers.items())
            if k.lower().startswith("x-oss-"))
        resource = f"/{self._bucket}/{key}"
        sub = sorted((k, v) for k, v in params.items()
                     if k in self._SIGNED_SUBRESOURCES)
        if sub:
            # OSS V1 canonicalization: valueless subresources render
            # BARE ("?uploads", no '='), values unencoded — urlencode
            # here would sign a string the server never sees
            resource += "?" + "&".join(
                k if v == "" else f"{k}={v}" for k, v in sub)
        canonical = "\n".join([
            method, headers.get("Content-MD5", ""),
            headers.get("Content-Type", ""), date,
            oss_headers + resource])
        sig = base64.b64encode(_hmac_sha1(
            self._sk.encode(), canonical.encode())).decode()
        headers["Authorization"] = f"OSS {self._ak}:{sig}"


class CosNativeClient(_XmlVendorClient):
    """Tencent COS request signing (SDK analogue:
    ``COSUnderFileSystem.java``)."""

    copy_header = "x-cos-copy-source"

    def _auth(self, method, key, params, headers, data) -> None:
        headers["Host"] = self._host
        now = int(time.time())
        key_time = f"{now - 60};{now + 3600}"
        sign_key = hmac.new(self._sk.encode(), key_time.encode(),
                            hashlib.sha1).hexdigest()
        # canonical params/headers: lowercased, url-encoded, sorted
        p_items = sorted((k.lower(), urllib.parse.quote(str(v), safe=""))
                         for k, v in params.items())
        h_items = sorted((k.lower(), urllib.parse.quote(str(v), safe=""))
                         for k, v in headers.items())
        url_param_list = ";".join(k for k, _ in p_items)
        header_list = ";".join(k for k, _ in h_items)
        http_string = "\n".join([
            method.lower(), self._uri_path(key),
            "&".join(f"{k}={v}" for k, v in p_items),
            "&".join(f"{k}={v}" for k, v in h_items), ""])
        string_to_sign = "\n".join([
            "sha1", key_time,
            hashlib.sha1(http_string.encode()).hexdigest(), ""])
        signature = hmac.new(sign_key.encode(),
                             string_to_sign.encode(),
                             hashlib.sha1).hexdigest()
        headers["Authorization"] = "&".join([
            "q-sign-algorithm=sha1",
            f"q-ak={self._ak}",
            f"q-sign-time={key_time}",
            f"q-key-time={key_time}",
            f"q-header-list={header_list}",
            f"q-url-param-list={url_param_list}",
            f"q-signature={signature}"])


class KodoNativeClient(ObjectStoreClient):
    """Qiniu Kodo native protocol (SDK analogue:
    ``KodoUnderFileSystem.java`` + ``KodoClient.java``): management ops
    against the rs/rsf hosts with QBox tokens, uploads via a signed
    PutPolicy uptoken, reads via private download URLs."""

    def __init__(self, bucket: str, ak: str, sk: str, *,
                 rs_host: str = "https://rs.qiniuapi.com",
                 rsf_host: str = "https://rsf.qiniuapi.com",
                 up_host: str = "https://upload.qiniup.com",
                 download_host: str = "") -> None:
        self._bucket = bucket
        self._ak, self._sk = ak, sk
        self._rs = rs_host.rstrip("/")
        self._rsf = rsf_host.rstrip("/")
        self._up = up_host.rstrip("/")
        if not download_host:
            raise ValueError(
                "kodo needs kodo.download.host (the bucket's bound "
                "domain — Kodo serves data via domains, not the API "
                "hosts; reference KodoUnderFileSystem.java)")
        self._dl = download_host.rstrip("/")
        if "://" not in self._dl:
            self._dl = "http://" + self._dl
        self._session = requests.Session()

    # -- tokens --------------------------------------------------------------
    def _qbox_token(self, path_and_query: str, body: bytes = b"") -> str:
        data = path_and_query.encode() + b"\n" + body
        sig = base64.urlsafe_b64encode(
            _hmac_sha1(self._sk.encode(), data)).decode()
        return f"QBox {self._ak}:{sig}"

    def _uptoken(self, key: str) -> str:
        policy = base64.urlsafe_b64encode(json.dumps({
            "scope": f"{self._bucket}:{key}",
            "deadline": int(time.time()) + 3600,
            "insertOnly": 0,
        }).encode()).decode()
        sig = base64.urlsafe_b64encode(_hmac_sha1(
            self._sk.encode(), policy.encode())).decode()
        return f"{self._ak}:{sig}:{policy}"

    def _entry(self, key: str) -> str:
        return base64.urlsafe_b64encode(
            f"{self._bucket}:{key}".encode()).decode()

    def _rs_post(self, path: str) -> requests.Response:
        return self._session.post(
            self._rs + path,
            headers={"Authorization": self._qbox_token(path),
                     "Content-Type":
                         "application/x-www-form-urlencoded"},
            timeout=60)

    # -- ObjectStoreClient ---------------------------------------------------
    def put(self, key: str, data: bytes) -> None:
        r = self._session.post(self._up + "/", files={
            "file": (key, data)}, data={
            "token": self._uptoken(key), "key": key}, timeout=60)
        r.raise_for_status()

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> Optional[bytes]:
        # private download URL: e=<deadline>&token=ak:sign(url)
        url = f"{self._dl}/{urllib.parse.quote(key)}" \
              f"?e={int(time.time()) + 3600}"
        sig = base64.urlsafe_b64encode(_hmac_sha1(
            self._sk.encode(), url.encode())).decode()
        url += f"&token={self._ak}:{sig}"
        headers = {}
        if offset or length is not None:
            end = "" if length is None else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._session.get(url, headers=headers, timeout=60)
        if r.status_code == 404:
            return None
        if r.status_code == 416:
            return b""
        r.raise_for_status()
        return r.content

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        path = f"/stat/{self._entry(key)}"
        r = self._rs_post(path)
        if r.status_code == 404 or (
                r.status_code == 612):  # 612: no such entry
            return None
        r.raise_for_status()
        st = r.json()
        # putTime is in 100ns units (Qiniu convention)
        return (int(st.get("fsize", 0)),
                int(st.get("putTime", 0)) // 10_000,
                st.get("hash", ""))

    def delete(self, key: str) -> bool:
        r = self._rs_post(f"/delete/{self._entry(key)}")
        return r.ok

    def copy(self, src_key: str, dst_key: str) -> bool:
        r = self._rs_post(
            f"/copy/{self._entry(src_key)}/{self._entry(dst_key)}"
            f"/force/true")
        return r.ok

    def list_prefix(self, prefix: str) -> List[str]:
        keys: List[str] = []
        marker = ""
        while True:
            q = {"bucket": self._bucket, "prefix": prefix,
                 "limit": "1000"}
            if marker:
                q["marker"] = marker
            path = "/list?" + urllib.parse.urlencode(sorted(q.items()))
            r = self._session.post(
                self._rsf + path,
                headers={"Authorization": self._qbox_token(path),
                         "Content-Type":
                             "application/x-www-form-urlencoded"},
                timeout=60)
            r.raise_for_status()
            body = r.json()
            keys.extend(it["key"] for it in body.get("items", []))
            marker = body.get("marker", "")
            if not marker:
                return keys
