"""Under-storage connectors (reference: ``underfs/`` + ``core/common/.../underfs``)."""

from alluxio_tpu.underfs.base import (  # noqa: F401
    CreateOptions, DeleteOptions, UfsMode, UfsStatus, UnderFileSystem,
)
from alluxio_tpu.underfs.local import LocalUnderFileSystem  # noqa: F401
from alluxio_tpu.underfs.object_base import (  # noqa: F401
    MemObjectStore, MemUnderFileSystem, ObjectStoreClient,
    ObjectUnderFileSystem,
)
from alluxio_tpu.underfs.registry import (  # noqa: F401
    UfsManager, create_ufs, register_factory, supported_schemes,
)
