"""Local-filesystem UFS.

Re-design of ``underfs/local/.../LocalUnderFileSystem.java`` — backs dev
deployments, tests, and the journal in single-host mode. Atomic creates go
through a temp file + rename, matching the reference's atomicity contract.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import BinaryIO, List, Optional

from alluxio_tpu.underfs.base import (
    CreateOptions, DeleteOptions, UfsStatus, UnderFileSystem,
)


def _strip_scheme(path: str) -> str:
    if path.startswith("file://"):
        return path[len("file://"):]
    return path


class _AtomicWriter:
    """Write to a temp file; rename into place on close."""

    def __init__(self, final_path: str, mode: int) -> None:
        d = os.path.dirname(final_path)
        os.makedirs(d, exist_ok=True)
        fd, self._tmp = tempfile.mkstemp(prefix=".atpu_tmp_", dir=d)
        self._f = os.fdopen(fd, "wb")
        self._final = final_path
        self._mode = mode
        self.closed = False

    def write(self, b: bytes) -> int:
        return self._f.write(b)

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if self.closed:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        os.chmod(self._tmp, self._mode)
        os.replace(self._tmp, self._final)
        self.closed = True

    def abort(self) -> None:
        if not self.closed:
            self._f.close()
            if os.path.exists(self._tmp):
                os.remove(self._tmp)
            self.closed = True

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            self.abort()
        return False


class LocalUnderFileSystem(UnderFileSystem):
    schemes = ("file", "")

    def get_underfs_type(self) -> str:
        return "local"

    def create(self, path: str, options: Optional[CreateOptions] = None) -> BinaryIO:
        opts = options or CreateOptions()
        p = _strip_scheme(path)
        if opts.ensure_atomic:
            return _AtomicWriter(p, opts.mode)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        return open(p, "wb")

    def open(self, path: str, offset: int = 0) -> BinaryIO:
        f = open(_strip_scheme(path), "rb")
        if offset:
            f.seek(offset)
        return f

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        fd = os.open(_strip_scheme(path), os.O_RDONLY)
        try:
            return os.pread(fd, length, offset)
        finally:
            os.close(fd)

    def delete_file(self, path: str) -> bool:
        p = _strip_scheme(path)
        if not os.path.isfile(p):
            return False
        os.remove(p)
        return True

    def delete_directory(self, path: str,
                         options: Optional[DeleteOptions] = None) -> bool:
        p = _strip_scheme(path)
        opts = options or DeleteOptions()
        if not os.path.isdir(p):
            return False
        if opts.recursive:
            shutil.rmtree(p)
        else:
            if os.listdir(p):
                return False
            os.rmdir(p)
        return True

    def rename_file(self, src: str, dst: str) -> bool:
        s, d = _strip_scheme(src), _strip_scheme(dst)
        if not os.path.isfile(s):
            return False
        os.makedirs(os.path.dirname(d), exist_ok=True)
        os.replace(s, d)
        return True

    def rename_directory(self, src: str, dst: str) -> bool:
        s, d = _strip_scheme(src), _strip_scheme(dst)
        if not os.path.isdir(s):
            return False
        os.makedirs(os.path.dirname(d), exist_ok=True)
        os.rename(s, d)
        return True

    def mkdirs(self, path: str, create_parent: bool = True) -> bool:
        p = _strip_scheme(path)
        if os.path.exists(p):
            return False
        if create_parent:
            os.makedirs(p, exist_ok=True)
        else:
            os.mkdir(p)
        return True

    def get_status(self, path: str) -> Optional[UfsStatus]:
        p = _strip_scheme(path)
        try:
            st = os.stat(p)
        except FileNotFoundError:
            return None
        return UfsStatus(
            name=p, is_directory=os.path.isdir(p),
            length=st.st_size if not os.path.isdir(p) else 0,
            last_modified_ms=int(st.st_mtime * 1000),
            owner=str(st.st_uid), group=str(st.st_gid),
            mode=st.st_mode & 0o777,
            content_hash=f"{st.st_mtime_ns}_{st.st_size}")

    def list_status(self, path: str) -> Optional[List[UfsStatus]]:
        p = _strip_scheme(path)
        if not os.path.isdir(p):
            return None
        out = []
        for name in sorted(os.listdir(p)):
            child = self.get_status(os.path.join(p, name))
            if child is not None:
                child.name = name
                out.append(child)
        return out

    def get_space_total(self) -> int:
        st = os.statvfs(_strip_scheme(self._root) or "/")
        return st.f_blocks * st.f_frsize

    def get_space_used(self) -> int:
        st = os.statvfs(_strip_scheme(self._root) or "/")
        return (st.f_blocks - st.f_bfree) * st.f_frsize
