"""Read-only HTTP(S) UFS.

Re-design of ``underfs/web/src/main/java/alluxio/underfs/web/
WebUnderFileSystem.java``: files are served with GET/HEAD (Range-capable),
directories are HTML index pages whose ``<a href>`` entries become the
listing — same directory-page parsing approach as the reference's Jsoup
scraper, with a stdlib HTMLParser.
"""

from __future__ import annotations

import html.parser
import io
import urllib.parse
from typing import BinaryIO, Dict, List, Optional

import requests

from alluxio_tpu.underfs.base import (
    CreateOptions, DeleteOptions, UfsStatus, UnderFileSystem,
)


class _HrefParser(html.parser.HTMLParser):
    def __init__(self) -> None:
        super().__init__()
        self.hrefs: List[str] = []

    def handle_starttag(self, tag, attrs):
        if tag == "a":
            for k, v in attrs:
                if k == "href" and v:
                    self.hrefs.append(v)


def _parse_http_date(value: Optional[str]) -> Optional[int]:
    """RFC 7231 date -> epoch ms; locale-independent (unlike strptime %a/%b)."""
    if not value:
        return None
    try:
        import email.utils

        dt = email.utils.parsedate_to_datetime(value)
        return int(dt.timestamp() * 1000) if dt else None
    except (TypeError, ValueError):
        return None


class WebUnderFileSystem(UnderFileSystem):
    """``http(s)://host/...`` read-only UFS."""

    schemes = ("http", "https")

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        super().__init__(root_uri, properties)
        self._session = requests.Session()
        self._timeout = float((properties or {}).get("web.timeout", "30"))

    def get_underfs_type(self) -> str:
        return "web"

    # -- read path -----------------------------------------------------------
    def open(self, path: str, offset: int = 0) -> BinaryIO:
        headers = {"Range": f"bytes={offset}-"} if offset else {}
        r = self._session.get(path, headers=headers, timeout=self._timeout)
        if r.status_code == 404:
            raise FileNotFoundError(path)
        r.raise_for_status()
        data = r.content
        if offset and r.status_code == 200:  # server ignored Range
            data = data[offset:]
        return io.BytesIO(data)

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        r = self._session.get(
            path, headers={"Range": f"bytes={offset}-{offset + length - 1}"},
            timeout=self._timeout)
        if r.status_code == 404:
            raise FileNotFoundError(path)
        if r.status_code == 416:
            return b""
        r.raise_for_status()
        if r.status_code == 200:  # server ignored Range: slice locally
            return r.content[offset:offset + length]
        return r.content

    # -- status --------------------------------------------------------------
    def _head(self, path: str) -> Optional[requests.Response]:
        r = self._session.head(path, timeout=self._timeout,
                               allow_redirects=True)
        if r.status_code == 404:
            return None
        if not r.ok:  # some servers reject HEAD; retry tiny GET
            r = self._session.get(path, headers={"Range": "bytes=0-0"},
                                  timeout=self._timeout)
            if r.status_code == 404:
                return None
            # transient server errors must NOT read as "exists, empty" —
            # a fabricated zero-length status would poison sync fingerprints
            r.raise_for_status()
        return r

    def _looks_dir(self, path: str, resp: requests.Response) -> bool:
        # a directory is a path the server redirects to a trailing slash
        # (index servers 301 /a -> /a/); an .html FILE stays at its own URL
        # and must not be misclassified by its text/html content type
        final = getattr(resp, "url", path) or path
        return path.endswith("/") or final.endswith("/")

    def get_status(self, path: str) -> Optional[UfsStatus]:
        r = self._head(path)
        if r is None:
            return None
        if self._looks_dir(path, r):
            return UfsStatus(name=path, is_directory=True)
        length = int(r.headers.get("Content-Length", 0) or 0)
        if r.headers.get("Content-Range"):  # ranged fallback GET
            total = r.headers["Content-Range"].rpartition("/")[2]
            if total.isdigit():
                length = int(total)
        return UfsStatus(
            name=path, is_directory=False, length=length,
            last_modified_ms=_parse_http_date(r.headers.get("Last-Modified")),
            content_hash=r.headers.get("ETag", "").strip('"'))

    def list_status(self, path: str) -> Optional[List[UfsStatus]]:
        url = path if path.endswith("/") else path + "/"
        r = self._session.get(url, timeout=self._timeout)
        if r.status_code == 404 or "text/html" not in \
                r.headers.get("Content-Type", ""):
            return None
        parser = _HrefParser()
        parser.feed(r.text)
        out: List[UfsStatus] = []
        seen = set()
        for href in parser.hrefs:
            if href.startswith(("?", "#", "..", "/")) or "://" in href:
                continue
            name = urllib.parse.unquote(href)
            is_dir = name.endswith("/")
            name = name.rstrip("/")
            if not name or "/" in name or name in seen:
                continue
            seen.add(name)
            if is_dir:
                out.append(UfsStatus(name=name, is_directory=True))
            else:
                child = self.get_status(url + href)
                out.append(UfsStatus(
                    name=name, is_directory=False,
                    length=child.length if child else 0,
                    last_modified_ms=(child.last_modified_ms
                                      if child else None),
                    content_hash=child.content_hash if child else ""))
        return out

    # -- writes are unsupported (read-only UFS) ------------------------------
    def create(self, path: str, options: Optional[CreateOptions] = None):
        raise OSError("WebUnderFileSystem is read-only")

    def delete_file(self, path: str) -> bool:
        raise OSError("WebUnderFileSystem is read-only")

    def delete_directory(self, path: str,
                         options: Optional[DeleteOptions] = None) -> bool:
        raise OSError("WebUnderFileSystem is read-only")

    def rename_file(self, src: str, dst: str) -> bool:
        raise OSError("WebUnderFileSystem is read-only")

    def rename_directory(self, src: str, dst: str) -> bool:
        raise OSError("WebUnderFileSystem is read-only")

    def mkdirs(self, path: str, create_parent: bool = True) -> bool:
        raise OSError("WebUnderFileSystem is read-only")
