"""Azure storage UFS connectors: wasb (Blob REST) and abfs/adl (ADLS Gen2).

Re-designs of ``underfs/wasb/src/main/java/alluxio/underfs/wasb/
WasbUnderFileSystem.java`` and ``underfs/adl`` / ``underfs/abfs`` (the
reference delegates to hadoop-azure's SDK clients): the TPU build speaks
the two Azure REST dialects directly —

* **wasb** — the Blob service REST API (``PUT Blob`` / ``Get Blob`` with
  Range / ``List Blobs``), SharedKey- or SAS-authenticated.
* **abfs / adl** — the ADLS Gen2 "DFS" paths API (create + append +
  flush, JSON listings).

URI forms (matching hadoop-azure):
  ``wasb://container@account.blob.core.windows.net/path``
  ``abfs://filesystem@account.dfs.core.windows.net/path``

Properties (also accepted without the vendor prefix via ``azure.*``):
  azure.endpoint     endpoint override (tests / azurite / private clouds)
  azure.account.key  base64 SharedKey; absent + no SAS -> anonymous
  azure.sas.token    SAS query string (``sv=...&sig=...``)
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import time
import xml.etree.ElementTree as ET
from email.utils import formatdate, parsedate_to_datetime
from typing import Dict, List, Optional, Tuple
from urllib.parse import quote, unquote_plus, urlsplit

import requests

from alluxio_tpu.underfs.object_base import (
    ObjectStoreClient, ObjectUnderFileSystem,
)

_API_VERSION = "2021-08-06"


def _parse_authority(root_uri: str) -> Tuple[str, str, str]:
    """``scheme://container@account.suffix/...`` ->
    (container, account, default_endpoint)."""
    rest = root_uri.split("://", 1)[1] if "://" in root_uri else root_uri
    authority = rest.partition("/")[0]
    if "@" in authority:
        container, _, host = authority.partition("@")
        account = host.partition(".")[0]
        return container, account, f"https://{host}"
    # bare ``scheme://container/...`` (endpoint must come from properties)
    return authority, "", ""


def _http_date_ms(value: str) -> int:
    try:
        return int(parsedate_to_datetime(value).timestamp() * 1000)
    except Exception:  # noqa: BLE001
        return int(time.time() * 1000)


class _SharedKey:
    """SharedKey request signer (Blob/DFS string-to-sign, 2021 dialect)."""

    def __init__(self, account: str, key_b64: str) -> None:
        self.account = account
        self._key = base64.b64decode(key_b64)

    def sign(self, method: str, url: str,
             headers: Dict[str, str]) -> str:
        parts = urlsplit(url)
        canon_headers = "".join(
            f"{k}:{v}\n" for k, v in sorted(headers.items())
            if k.startswith("x-ms-"))
        canon_res = f"/{self.account}{parts.path}"
        if parts.query:
            # Azure computes the string-to-sign over URL-DECODED query
            # names/values (SharedKey spec "Constructing the canonicalized
            # resource string"): a prefix containing %2F or a continuation
            # token with '+'/'=' must be decoded here or the service
            # rejects the signature with 403 AuthenticationFailed.
            q: Dict[str, List[str]] = {}
            for kv in parts.query.split("&"):
                k, _, v = kv.partition("=")
                q.setdefault(unquote_plus(k).lower(), []).append(
                    unquote_plus(v))
            for k in sorted(q):
                canon_res += f"\n{k}:{','.join(sorted(q[k]))}"
        to_sign = "\n".join([
            method,
            headers.get("Content-Encoding", ""),
            headers.get("Content-Language", ""),
            headers.get("Content-Length", "") or "",
            headers.get("Content-MD5", ""),
            headers.get("Content-Type", ""),
            "",  # Date: always sent via x-ms-date instead
            headers.get("If-Modified-Since", ""),
            headers.get("If-Match", ""),
            headers.get("If-None-Match", ""),
            headers.get("If-Unmodified-Since", ""),
            headers.get("Range", ""),
            canon_headers + canon_res,
        ])
        sig = base64.b64encode(
            hmac.new(self._key, to_sign.encode(), hashlib.sha256).digest()
        ).decode()
        return f"SharedKey {self.account}:{sig}"


class _AzureRestBase(ObjectStoreClient):
    """Shared endpoint/auth plumbing for the two dialects."""

    def __init__(self, container: str, account: str,
                 default_endpoint: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        props = properties or {}
        self._container = container
        self._endpoint = (props.get("azure.endpoint") or default_endpoint
                          or "").rstrip("/")
        if not self._endpoint:
            raise ValueError(
                "no Azure endpoint: use the "
                "container@account.host URI form or set azure.endpoint")
        account = props.get("azure.account", account) or "devaccount"
        key = props.get("azure.account.key", "")
        self._sas = props.get("azure.sas.token", "").lstrip("?")
        self._signer = _SharedKey(account, key) if key else None
        self._session = requests.Session()

    def _url(self, key: str, query: str = "") -> str:
        url = f"{self._endpoint}/{self._container}"
        if key:
            url += "/" + quote(key, safe="/")
        qs = [q for q in (query, self._sas) if q]
        if qs:
            url += "?" + "&".join(qs)
        return url

    def _request(self, method: str, url: str, *, data: bytes = b"",
                 headers: Optional[Dict[str, str]] = None):
        hdrs = dict(headers or {})
        hdrs["x-ms-version"] = _API_VERSION
        hdrs["x-ms-date"] = formatdate(usegmt=True)
        if self._signer is not None:
            # Content-Length participates in the string-to-sign but the
            # transport sets the actual header from the body
            sign_hdrs = dict(hdrs)
            if data:
                sign_hdrs["Content-Length"] = str(len(data))
            hdrs["Authorization"] = self._signer.sign(
                method, url, sign_hdrs)
        return self._session.request(method, url, data=data,
                                     headers=hdrs, timeout=60)

    # shared across both dialects: ranged read and delete are identical
    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> Optional[bytes]:
        headers = {}
        if offset or length is not None:
            end = "" if length is None else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._request("GET", self._url(key), headers=headers)
        if r.status_code == 404:
            return None
        if r.status_code == 416:
            return b""
        r.raise_for_status()
        return r.content

    def delete(self, key: str) -> bool:
        r = self._request("DELETE", self._url(key))
        return r.status_code in (200, 202, 204)


class AzureBlobClient(_AzureRestBase):
    """Blob service dialect (wasb)."""

    def put(self, key: str, data: bytes) -> None:
        r = self._request("PUT", self._url(key), data=data,
                          headers={"x-ms-blob-type": "BlockBlob"})
        r.raise_for_status()

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        r = self._request("HEAD", self._url(key))
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return (int(r.headers.get("Content-Length", 0)),
                _http_date_ms(r.headers.get("Last-Modified", "")),
                r.headers.get("ETag", ""))

    def copy(self, src_key: str, dst_key: str) -> bool:
        r = self._request(
            "PUT", self._url(dst_key),
            headers={"x-ms-copy-source": self._url(src_key)})
        if r.status_code not in (200, 201, 202):
            return False
        # poll async copies to completion (tests/azurite complete sync)
        for _ in range(60):
            status = r.headers.get("x-ms-copy-status", "success")
            if status == "success":
                return True
            if status in ("failed", "aborted"):
                return False
            time.sleep(0.5)
            h = self._request("HEAD", self._url(dst_key))
            r = h
        return False

    def list_prefix(self, prefix: str) -> List[str]:
        keys: List[str] = []
        marker = ""
        while True:
            q = (f"restype=container&comp=list"
                 f"&prefix={quote(prefix, safe='')}")
            if marker:
                q += f"&marker={quote(marker, safe='')}"
            r = self._request("GET", self._url("", q))
            r.raise_for_status()
            root = ET.fromstring(r.content)
            for b in root.iter("Blob"):
                name = b.findtext("Name")
                if name:
                    keys.append(name)
            marker = root.findtext("NextMarker") or ""
            if not marker:
                return keys


class AdlsGen2Client(_AzureRestBase):
    """ADLS Gen2 "DFS" paths dialect (abfs/adl): writes are
    create + append + flush; listings are JSON."""

    def put(self, key: str, data: bytes) -> None:
        r = self._request("PUT", self._url(key, "resource=file"))
        r.raise_for_status()
        if data:
            r = self._request(
                "PATCH", self._url(key, "action=append&position=0"),
                data=data)
            r.raise_for_status()
        r = self._request(
            "PATCH", self._url(key, f"action=flush&position={len(data)}"))
        r.raise_for_status()

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        r = self._request("HEAD", self._url(key))
        if r.status_code == 404:
            return None
        r.raise_for_status()
        if r.headers.get("x-ms-resource-type") == "directory":
            return None  # object contract: directories are not blobs
        return (int(r.headers.get("Content-Length", 0)),
                _http_date_ms(r.headers.get("Last-Modified", "")),
                r.headers.get("ETag", ""))

    def copy(self, src_key: str, dst_key: str) -> bool:
        # the DFS dialect has rename but no server-side copy: stream
        data = self.get(src_key)
        if data is None:
            return False
        self.put(dst_key, data)
        return True

    def rename(self, src_key: str, dst_key: str) -> bool:
        """Native HNS rename (atomic server-side; no copy+delete)."""
        r = self._request(
            "PUT", self._url(dst_key),
            headers={"x-ms-rename-source":
                     f"/{self._container}/{quote(src_key, safe='/')}"})
        return r.status_code in (200, 201)

    def list_prefix(self, prefix: str) -> List[str]:
        keys: List[str] = []
        token = ""
        while True:
            q = "resource=filesystem&recursive=true"
            if prefix:
                q += f"&directory={quote(prefix, safe='')}"
            if token:
                q += f"&continuation={quote(token, safe='')}"
            r = self._request("GET", self._url("", q))
            if r.status_code == 404:
                return keys
            r.raise_for_status()
            for p in r.json().get("paths", []):
                if not p.get("isDirectory") in (True, "true"):
                    keys.append(p["name"])
            token = r.headers.get("x-ms-continuation", "")
            if not token:
                return keys


class WasbUnderFileSystem(ObjectUnderFileSystem):
    """``wasb://container@account.blob.core.windows.net/...``."""

    schemes = ("wasb", "wasbs")

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        container, account, endpoint = _parse_authority(root_uri)
        client = AzureBlobClient(container, account, endpoint, properties)
        super().__init__(root_uri, client, properties)
        self._bucket = container

    def get_underfs_type(self) -> str:
        return "wasb"


class AdlsUnderFileSystem(ObjectUnderFileSystem):
    """``abfs://filesystem@account.dfs.core.windows.net/...`` (also
    registered for the legacy ``adl`` scheme)."""

    schemes = ("abfs", "abfss", "adl")

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        container, account, endpoint = _parse_authority(root_uri)
        client = AdlsGen2Client(container, account, endpoint, properties)
        super().__init__(root_uri, client, properties)
        self._bucket = container

    def get_underfs_type(self) -> str:
        return "abfs"

    def rename_file(self, src: str, dst: str) -> bool:
        # HNS gives real rename: one call, atomic, no copy+delete
        return self._client.rename(self._key(src), self._key(dst))
