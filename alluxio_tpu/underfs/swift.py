"""OpenStack Swift UFS connector — NATIVE dialect.

Re-design of ``underfs/swift/src/main/java/alluxio/underfs/swift/
SwiftUnderFileSystem.java:59`` (which delegates auth to JOSS): the TPU
build speaks Keystone v3 and the Swift object API directly —

* **auth**: ``POST {auth_url}/auth/tokens`` with password credentials
  scoped to a project; the ``X-Subject-Token`` header carries the token
  and the response catalog carries the object-store endpoint. Tokens
  refresh automatically on expiry/401 (JOSS does the same re-auth).
* **objects**: ``PUT/GET(+Range)/HEAD/DELETE {storage}/{container}/
  {key}``; listings are ``?format=json&prefix=&marker=`` pages; server-
  side copy via the ``X-Copy-From`` header.

Properties:
  swift.auth.url        Keystone v3 base (``https://ks:5000/v3``).
                        ABSENT -> the connector falls back to the S3-
                        middleware gateway dialect (s3_compat), keeping
                        old configs working.
  swift.user / swift.password / swift.project
  swift.domain          user+project domain name (default "Default")
  swift.region          pick this region's endpoint from the catalog
"""

from __future__ import annotations

import json
import threading
import time
from email.utils import parsedate_to_datetime
from typing import Dict, List, Optional, Tuple
from urllib.parse import quote

import requests

from alluxio_tpu.underfs.object_base import (
    ObjectStoreClient, ObjectUnderFileSystem,
)


class KeystoneSession:
    """Keystone v3 password auth + catalog endpoint resolution, with
    lazy (re)authentication shared by all requests of one connector."""

    def __init__(self, auth_url: str, user: str, password: str,
                 project: str, domain: str = "Default",
                 region: str = "") -> None:
        self._auth_url = auth_url.rstrip("/")
        self._user = user
        self._password = password
        self._project = project
        self._domain = domain or "Default"
        self._region = region
        self._lock = threading.Lock()
        self._token: Optional[str] = None
        self._storage_url: Optional[str] = None
        self.http = requests.Session()

    def _authenticate_locked(self) -> None:
        body = {"auth": {
            "identity": {"methods": ["password"], "password": {"user": {
                "name": self._user,
                "domain": {"name": self._domain},
                "password": self._password}}},
            "scope": {"project": {"name": self._project,
                                  "domain": {"name": self._domain}}},
        }}
        r = self.http.post(f"{self._auth_url}/auth/tokens", json=body,
                           timeout=30)
        r.raise_for_status()
        self._token = r.headers["X-Subject-Token"]
        catalog = (r.json().get("token") or {}).get("catalog") or []
        url = None
        for svc in catalog:
            if svc.get("type") != "object-store":
                continue
            for ep in svc.get("endpoints", []):
                if ep.get("interface") != "public":
                    continue
                if self._region and ep.get("region") != self._region:
                    continue
                url = ep.get("url")
                break
        if url is None:
            raise IOError(
                "keystone catalog has no public object-store endpoint"
                + (f" in region {self._region!r}" if self._region else ""))
        self._storage_url = url.rstrip("/")

    def credentials(self) -> Tuple[str, str]:
        with self._lock:
            if self._token is None:
                self._authenticate_locked()
            return self._token, self._storage_url

    def invalidate(self) -> None:
        with self._lock:
            self._token = None


class SwiftClient(ObjectStoreClient):
    """Swift object API over a KeystoneSession."""

    def __init__(self, container: str, session: KeystoneSession) -> None:
        self._container = container
        self._ks = session

    def _request(self, method: str, key: str = "", *, params=None,
                 data=None, headers=None, retry_auth: bool = True):
        token, storage = self._ks.credentials()
        url = f"{storage}/{quote(self._container)}"
        if key:
            url += "/" + quote(key, safe="/")
        hdrs = dict(headers or {})
        hdrs["X-Auth-Token"] = token
        r = self._ks.http.request(method, url, params=params, data=data,
                                  headers=hdrs, timeout=60)
        if r.status_code == 401 and retry_auth:
            # expired token: re-auth once (JOSS re-auth behavior)
            self._ks.invalidate()
            return self._request(method, key, params=params, data=data,
                                 headers=headers, retry_auth=False)
        return r

    # -- ObjectStoreClient ---------------------------------------------------
    def put(self, key: str, data: bytes) -> None:
        r = self._request("PUT", key, data=data)
        r.raise_for_status()

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> Optional[bytes]:
        headers = {}
        if offset or length is not None:
            end = "" if length is None else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._request("GET", key, headers=headers)
        if r.status_code == 404:
            return None
        if r.status_code == 416:
            return b""
        r.raise_for_status()
        return r.content

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        r = self._request("HEAD", key)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        mtime = 0
        lm = r.headers.get("Last-Modified") or r.headers.get(
            "X-Timestamp")
        if lm:
            try:
                mtime = int(float(lm) * 1000)
            except ValueError:
                try:
                    mtime = int(
                        parsedate_to_datetime(lm).timestamp() * 1000)
                except Exception:  # noqa: BLE001
                    mtime = int(time.time() * 1000)
        return (int(r.headers.get("Content-Length", 0)), mtime,
                r.headers.get("Etag", ""))

    def delete(self, key: str) -> bool:
        r = self._request("DELETE", key)
        return r.status_code in (200, 204)

    def copy(self, src_key: str, dst_key: str) -> bool:
        r = self._request(
            "PUT", dst_key,
            headers={"X-Copy-From":
                     f"/{self._container}/{quote(src_key, safe='/')}"})
        return r.status_code in (200, 201, 202)

    def list_prefix(self, prefix: str) -> List[str]:
        keys: List[str] = []
        marker = ""
        while True:
            params = {"format": "json", "prefix": prefix}
            if marker:
                params["marker"] = marker
            r = self._request("GET", params=params)
            if r.status_code == 404:
                return keys
            r.raise_for_status()
            page = json.loads(r.content or b"[]")
            if not page:
                return keys
            for obj in page:
                name = obj.get("name")
                if name:
                    keys.append(name)
            marker = page[-1].get("name", "")
            if not marker:
                return keys


class SwiftNativeUnderFileSystem(ObjectUnderFileSystem):
    """``swift://container/...`` over Keystone v3 + the Swift API."""

    schemes = ("swift",)

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        props = properties or {}
        rest = root_uri.split("://", 1)[1] if "://" in root_uri else root_uri
        container = rest.partition("/")[0]
        session = KeystoneSession(
            props["swift.auth.url"],
            props.get("swift.user", ""),
            props.get("swift.password", ""),
            props.get("swift.project", ""),
            domain=props.get("swift.domain", "Default"),
            region=props.get("swift.region", ""))
        super().__init__(root_uri, SwiftClient(container, session),
                         properties=props)

    def get_underfs_type(self) -> str:
        return "swift"


def create_swift_ufs(root_uri: str,
                     properties: Optional[Dict[str, str]] = None):
    """Dialect dispatch: Keystone native when ``swift.auth.url`` is
    configured, S3-middleware gateway otherwise (old configs keep
    working; reference ``SwiftUnderFileSystem`` likewise speaks either
    Keystone v2/v3 via JOSS or tempauth)."""
    props = properties or {}
    if props.get("swift.auth.url"):
        return SwiftNativeUnderFileSystem(root_uri, props)
    from alluxio_tpu.underfs.s3_compat import SwiftUnderFileSystem

    return SwiftUnderFileSystem(root_uri, props)
