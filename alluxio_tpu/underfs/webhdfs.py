"""HDFS UFS connector over the WebHDFS REST protocol.

Second dialect of the HDFS family (reference:
``underfs/hdfs/src/main/java/alluxio/underfs/hdfs/
HdfsUnderFileSystem.java:80``): where ``hdfs://`` rides libhdfs JNI
(``underfs/hdfs.py``, needs a Hadoop native install), ``webhdfs://``
speaks the NameNode's REST API (``hdfs-site: dfs.webhdfs.enabled``) with
nothing but the standard library — which also makes the HDFS wire
contract testable against an in-process fake NameNode
(``tests/testutils/fake_webhdfs.py``).

Protocol notes (Hadoop WebHDFS, stable since 1.x):
  GET    ?op=GETFILESTATUS | LISTSTATUS | OPEN[&offset=&length=]
  PUT    ?op=MKDIRS | RENAME&destination= | CREATE (two-step: the
         namenode answers 307 with the datanode Location; the data goes
         in a second PUT — urllib does not follow redirects for PUT, so
         the dance is explicit here)
  DELETE ?op=DELETE[&recursive=]
Errors arrive as ``{"RemoteException": {"exception", "message"}}``.
"""

from __future__ import annotations

import io
import json
import urllib.error
import urllib.parse
import urllib.request
from typing import BinaryIO, Dict, List, Optional

from alluxio_tpu.utils import httperr

from alluxio_tpu.underfs.base import (
    CreateOptions, DeleteOptions, UfsStatus, UnderFileSystem,
)


class _RemoteError(IOError):
    def __init__(self, exception: str, message: str) -> None:
        super().__init__(f"{exception}: {message}")
        self.exception = exception


class WebHdfsUnderFileSystem(UnderFileSystem):
    """``webhdfs://namenode:9870/...``."""

    schemes = ("webhdfs",)

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        super().__init__(root_uri, properties)
        parsed = urllib.parse.urlsplit(root_uri)
        props = properties or {}
        host = parsed.hostname or "localhost"
        port = parsed.port or 9870
        self._base = f"http://{host}:{port}/webhdfs/v1"
        self._user = props.get("hdfs.user", "")
        self._timeout = float(props.get("hdfs.timeout.s", 30))

    # -- wire ---------------------------------------------------------------
    def _url(self, path: str, op: str, **params) -> str:
        if "://" in path:
            path = urllib.parse.urlsplit(path).path or "/"
        if not path.startswith("/"):
            path = "/" + path
        q = {"op": op, **{k: str(v) for k, v in params.items()}}
        if self._user:
            q["user.name"] = self._user
        return (self._base + urllib.parse.quote(path) + "?"
                + urllib.parse.urlencode(q))

    def _request(self, method: str, url: str,
                 data: Optional[bytes] = None,
                 redirect_body: Optional[bytes] = None) -> bytes:
        """``redirect_body``: enables the two-step CREATE/APPEND dance —
        step 1 goes WITHOUT a body (the protocol's shape; a real
        NameNode may hang up before draining one) and the payload rides
        only the redirected request to the datanode Location."""
        req = urllib.request.Request(url, data=data, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if redirect_body is not None and e.code == 307:
                loc = e.headers.get("Location", "")
                httperr.drain(e)
                return self._request(method, loc, data=redirect_body)
            # parse-sensitive: the RemoteException mapping needs the
            # FULL body (truncation breaks absence detection)
            detail = httperr.error_body(e, limit=1 << 20)
            try:
                remote = json.loads(detail)["RemoteException"]
                raise _RemoteError(remote.get("exception", ""),
                                   remote.get("message", "")) from None
            except (ValueError, KeyError):
                raise IOError(
                    f"webhdfs {method} {url}: HTTP {e.code}") from None

    def _json(self, method: str, url: str, **kw) -> dict:
        body = self._request(method, url, **kw)
        return json.loads(body) if body else {}

    # -- SPI ----------------------------------------------------------------
    def get_underfs_type(self) -> str:
        return "hdfs"

    def create(self, path: str,
               options: Optional[CreateOptions] = None) -> BinaryIO:
        ufs = self

        class _Writer(io.BytesIO):
            def __init__(self) -> None:
                super().__init__()
                self._done = False

            def close(inner) -> None:  # noqa: N805
                if not inner._done:
                    inner._done = True
                    ufs._create_upload(path, inner.getvalue())
                super(_Writer, inner).close()

            def __enter__(inner):  # noqa: N805
                return inner

            def __exit__(inner, exc_type, exc, tb):  # noqa: N805
                if exc_type is None:
                    inner.close()
                else:
                    # abort: a GC-time IOBase.__del__ -> close() must
                    # NOT upload the partial buffer
                    inner._done = True
                return False

        return _Writer()

    @staticmethod
    def _absent(e: _RemoteError) -> bool:
        """Only a server-confirmed FileNotFoundException means absent;
        StandbyException / AccessControlException / safe mode must NOT
        read as 'file deleted' — metadata sync would wipe live state."""
        return e.exception == "FileNotFoundException"

    def _create_upload(self, path: str, payload: bytes) -> None:
        self._request("PUT", self._url(path, "CREATE", overwrite="true"),
                      data=None, redirect_body=payload)

    def open(self, path: str, offset: int = 0) -> BinaryIO:
        # STREAMING read: the HTTP response body is the file — hand it
        # to the caller as-is (sequential read(n)); materializing
        # multi-GB objects in RAM per open() would OOM a worker under
        # concurrent cold read-through. read_range covers positioned
        # one-shot reads.
        params = {"offset": offset} if offset else {}
        url = self._url(path, "OPEN", **params)
        req = urllib.request.Request(url, method="GET")
        try:
            return urllib.request.urlopen(req, timeout=self._timeout)
        except urllib.error.HTTPError as e:
            detail = httperr.error_body(e, limit=1 << 20)
            try:
                remote = json.loads(detail)["RemoteException"]
            except (ValueError, KeyError):
                raise IOError(f"webhdfs OPEN {path}: "
                              f"HTTP {e.code}") from None
            if remote.get("exception") == "FileNotFoundException":
                raise FileNotFoundError(path) from None
            raise _RemoteError(remote.get("exception", ""),
                               remote.get("message", "")) from None

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        try:
            return self._request("GET", self._url(
                path, "OPEN", offset=offset, length=length))
        except _RemoteError as e:
            if self._absent(e):
                raise FileNotFoundError(path) from e
            raise

    def delete_file(self, path: str) -> bool:
        st = self.get_status(path)
        if st is None or st.is_directory:  # SPI: type mismatch -> False
            return False
        return bool(self._json("DELETE", self._url(
            path, "DELETE", recursive="false")).get("boolean"))

    def delete_directory(self, path: str,
                         options: Optional[DeleteOptions] = None) -> bool:
        opts = options or DeleteOptions()
        st = self.get_status(path)
        if st is None or not st.is_directory:
            return False
        try:
            return bool(self._json("DELETE", self._url(
                path, "DELETE",
                recursive="true" if opts.recursive else "false")).get(
                    "boolean"))
        except _RemoteError as e:
            # the server enforces non-empty protection race-free; map
            # its refusal to the contractual False
            if e.exception == "PathIsNotEmptyDirectoryException":
                return False
            raise

    def _rename(self, src: str, dst: str) -> bool:
        if "://" in dst:
            dst = urllib.parse.urlsplit(dst).path or "/"
        return bool(self._json("PUT", self._url(
            src, "RENAME", destination=dst)).get("boolean"))

    rename_file = _rename
    rename_directory = _rename

    def mkdirs(self, path: str, create_parent: bool = True) -> bool:
        # WebHDFS MKDIRS always creates parents; enforce the SPI
        # contract (siblings return False on pre-existing paths and on
        # missing parents when create_parent=False) client-side
        if self.get_status(path) is not None:
            return False
        if not create_parent:
            parent = path.rstrip("/").rsplit("/", 1)[0] or "/"
            pst = self.get_status(parent)
            if pst is None or not pst.is_directory:
                return False
        return bool(self._json("PUT", self._url(
            path, "MKDIRS")).get("boolean"))

    def _to_status(self, st: dict, name: str) -> UfsStatus:
        return UfsStatus(
            name=name,
            is_directory=st.get("type") == "DIRECTORY",
            length=int(st.get("length", 0)),
            last_modified_ms=int(st.get("modificationTime", 0)) or None,
            owner=st.get("owner", ""),
            group=st.get("group", ""),
            mode=int(st.get("permission", "755"), 8))

    def get_status(self, path: str) -> Optional[UfsStatus]:
        try:
            st = self._json("GET", self._url(
                path, "GETFILESTATUS"))["FileStatus"]
        except _RemoteError as e:
            if self._absent(e):
                return None
            raise
        return self._to_status(st, path)

    def list_status(self, path: str) -> Optional[List[UfsStatus]]:
        # ONE round trip: LISTSTATUS on a file returns a single entry
        # with an empty pathSuffix — that distinguishes file (-> None)
        # from directory without a GETFILESTATUS probe. Listing is the
        # hot path of recursive active sync.
        try:
            listing = self._json("GET", self._url(path, "LISTSTATUS"))
        except _RemoteError as e:
            if self._absent(e):
                return None
            raise
        entries = listing.get("FileStatuses", {}).get("FileStatus", [])
        if len(entries) == 1 and not entries[0].get("pathSuffix") \
                and entries[0].get("type") == "FILE":
            return None  # the path itself is a file
        return [self._to_status(e, e.get("pathSuffix", ""))
                for e in entries]

    def supports_active_sync(self) -> bool:
        # poll-based: the master's ActiveSyncManager re-syncs sync
        # points on its heartbeat (first step toward the reference's
        # iNotify push, SupportedHdfsActiveSyncProvider.java:28)
        return True
