"""HDFS UFS connector via pyarrow's libhdfs binding.

Re-design of ``underfs/hdfs/src/main/java/alluxio/underfs/hdfs/
HdfsUnderFileSystem.java:80``: the TPU build rides ``pyarrow.fs.
HadoopFileSystem`` (JNI libhdfs) instead of the Hadoop Java client.
Requires a Hadoop native installation (``HADOOP_HOME``/``CLASSPATH``) at
runtime; the factory registers only when pyarrow can load it. Active sync
(the reference's iNotify path, ``UnderFileSystem.java:713-742``) is
exposed as poll-based change detection via content fingerprints — see
``master/sync.py``.
"""

from __future__ import annotations

import urllib.parse
from typing import BinaryIO, Dict, List, Optional

from pyarrow import fs as pafs  # gates factory registration when absent

from alluxio_tpu.underfs.base import (
    CreateOptions, DeleteOptions, UfsStatus, UnderFileSystem,
)


class HdfsUnderFileSystem(UnderFileSystem):
    """``hdfs://namenode:port/...``."""

    schemes = ("hdfs",)

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        super().__init__(root_uri, properties)
        parsed = urllib.parse.urlsplit(root_uri)
        props = properties or {}
        self._fs = pafs.HadoopFileSystem(  # raises if libhdfs missing
            host=parsed.hostname or "default",
            port=parsed.port or 8020,
            user=props.get("hdfs.user") or None,
            replication=int(props.get("hdfs.replication", 3)))

    def _p(self, path: str) -> str:
        if "://" in path:
            return urllib.parse.urlsplit(path).path or "/"
        return path

    def get_underfs_type(self) -> str:
        return "hdfs"

    def create(self, path: str,
               options: Optional[CreateOptions] = None) -> BinaryIO:
        return self._fs.open_output_stream(self._p(path))

    def open(self, path: str, offset: int = 0) -> BinaryIO:
        f = self._fs.open_input_file(self._p(path))
        if offset:
            f.seek(offset)
        return f

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        with self._fs.open_input_file(self._p(path)) as f:
            return f.read_at(length, offset)

    def delete_file(self, path: str) -> bool:
        self._fs.delete_file(self._p(path))
        return True

    def delete_directory(self, path: str,
                         options: Optional[DeleteOptions] = None) -> bool:
        opts = options or DeleteOptions()
        if not opts.recursive and (self.list_status(path) or []):
            return False
        self._fs.delete_dir(self._p(path))
        return True

    def rename_file(self, src: str, dst: str) -> bool:
        self._fs.move(self._p(src), self._p(dst))
        return True

    rename_directory = rename_file

    def mkdirs(self, path: str, create_parent: bool = True) -> bool:
        self._fs.create_dir(self._p(path), recursive=create_parent)
        return True

    def _to_status(self, info, name: str) -> UfsStatus:
        return UfsStatus(
            name=name,
            is_directory=info.type == pafs.FileType.Directory,
            length=info.size or 0,
            last_modified_ms=int(info.mtime.timestamp() * 1000)
            if info.mtime else None)

    def get_status(self, path: str) -> Optional[UfsStatus]:
        info = self._fs.get_file_info(self._p(path))
        if info.type == pafs.FileType.NotFound:
            return None
        return self._to_status(info, path)

    def list_status(self, path: str) -> Optional[List[UfsStatus]]:
        base = self._p(path)
        info = self._fs.get_file_info(base)
        if info.type != pafs.FileType.Directory:
            return None
        sel = pafs.FileSelector(base, recursive=False)
        return [self._to_status(i, i.base_name)
                for i in self._fs.get_file_info(sel)]
