"""S3-compatible connectors for the other cloud object stores.

The reference ships per-vendor SDK connectors (``underfs/{oss,cos,kodo,
swift}`` — Alibaba OSS, Tencent COS, Qiniu Kodo, OpenStack Swift). All four
services expose S3-compatible REST gateways, so the TPU build serves them
through the SigV4 client with vendor-specific endpoint defaults instead of
four SDK dependencies. Properties mirror the s3 connector with a vendor
prefix (e.g. ``oss.endpoint``, ``cos.access.key``) and fall back to the
``s3.*`` names.
"""

from __future__ import annotations

from typing import Dict, Optional

from alluxio_tpu.underfs.s3 import S3Client, S3UnderFileSystem


def _remap(prefix: str, properties: Optional[Dict[str, str]],
           default_endpoint: str = "") -> Dict[str, str]:
    props = dict(properties or {})
    for suffix in ("endpoint", "access.key", "secret.key", "region",
                   "path.style", "multipart.size"):
        v = props.get(f"{prefix}.{suffix}", props.get(f"s3.{suffix}"))
        if v is not None:
            props[f"s3.{suffix}"] = v
    if "s3.endpoint" not in props and default_endpoint:
        props["s3.endpoint"] = default_endpoint
    return props


class _CompatUfs(S3UnderFileSystem):
    vendor_prefix = "s3"
    default_endpoint = ""

    def _make_client(self, bucket: str,
                     properties: Optional[Dict[str, str]]) -> S3Client:
        return S3Client(bucket, _remap(self.vendor_prefix, properties,
                                       self.default_endpoint))


class OssUnderFileSystem(_CompatUfs):
    """``oss://bucket/...`` via Alibaba OSS's S3-compatible API
    (reference: ``underfs/oss``); ``oss.dialect=native`` switches to
    the vendor's own header signing — see :func:`create_oss_ufs`."""

    schemes = ("oss",)
    vendor_prefix = "oss"
    default_endpoint = "https://oss-cn-hangzhou.aliyuncs.com"


class CosUnderFileSystem(_CompatUfs):
    """``cos://bucket/...`` via Tencent COS's S3-compatible API
    (reference: ``underfs/cos``); ``cos.dialect=native`` switches to
    q-signature auth — see :func:`create_cos_ufs`."""

    schemes = ("cos", "cosn")
    vendor_prefix = "cos"
    default_endpoint = "https://cos.ap-guangzhou.myqcloud.com"


class KodoUnderFileSystem(_CompatUfs):
    """``kodo://bucket/...`` via Qiniu Kodo's S3-compatible API
    (reference: ``underfs/kodo``); ``kodo.dialect=native`` switches to
    QBox tokens + private download URLs — see :func:`create_kodo_ufs`."""

    schemes = ("kodo",)
    vendor_prefix = "kodo"
    default_endpoint = "https://s3-cn-east-1.qiniucs.com"


def _native_requested(prefix: str,
                      properties: Optional[Dict[str, str]]) -> bool:
    return (properties or {}).get(f"{prefix}.dialect", "").lower() == \
        "native"


def _bucket_of(uri: str) -> str:
    rest = uri.split("://", 1)[1] if "://" in uri else uri
    return rest.partition("/")[0]


def _vendor_prop(props: Dict[str, str], prefix: str, suffix: str,
                 default: str = "") -> str:
    """Same fallback contract as the gateway path's ``_remap``: the
    vendor-prefixed name wins, the documented ``s3.*`` name backs it."""
    return props.get(f"{prefix}.{suffix}",
                     props.get(f"s3.{suffix}", default))


def _native_creds(props: Dict[str, str],
                  prefix: str) -> "tuple[str, str]":
    ak = _vendor_prop(props, prefix, "access.key")
    sk = _vendor_prop(props, prefix, "secret.key")
    if not ak or not sk:
        raise ValueError(
            f"{prefix}.dialect=native needs {prefix}.access.key + "
            f"{prefix}.secret.key (or the s3.* fallbacks) — refusing "
            f"to sign with empty credentials")
    return ak, sk


def create_oss_ufs(root_uri: str,
                   properties: Optional[Dict[str, str]] = None):
    """Dialect dispatch (the swift-connector pattern): the S3 gateway
    by default; ``oss.dialect=native`` signs with Alibaba's own
    "OSS ak:sig" scheme (reference ``OSSUnderFileSystem.java``)."""
    if not _native_requested("oss", properties):
        return OssUnderFileSystem(root_uri, properties)
    from alluxio_tpu.underfs.object_base import ObjectUnderFileSystem
    from alluxio_tpu.underfs.vendor_native import OssNativeClient

    p = properties or {}
    ak, sk = _native_creds(p, "oss")
    client = OssNativeClient(
        _bucket_of(root_uri),
        _vendor_prop(p, "oss", "endpoint",
                     OssUnderFileSystem.default_endpoint),
        ak, sk,
        _vendor_prop(p, "oss", "path.style", "false") == "true",
        multipart_size=int(
            _vendor_prop(p, "oss", "multipart.size", str(8 << 20))))
    return ObjectUnderFileSystem(root_uri, client, properties)


create_oss_ufs.schemes = OssUnderFileSystem.schemes


def create_cos_ufs(root_uri: str,
                   properties: Optional[Dict[str, str]] = None):
    """``cos.dialect=native`` -> Tencent q-signature auth (reference
    ``COSUnderFileSystem.java``); default stays the S3 gateway."""
    if not _native_requested("cos", properties):
        return CosUnderFileSystem(root_uri, properties)
    from alluxio_tpu.underfs.object_base import ObjectUnderFileSystem
    from alluxio_tpu.underfs.vendor_native import CosNativeClient

    p = properties or {}
    ak, sk = _native_creds(p, "cos")
    client = CosNativeClient(
        _bucket_of(root_uri),
        _vendor_prop(p, "cos", "endpoint",
                     CosUnderFileSystem.default_endpoint),
        ak, sk,
        _vendor_prop(p, "cos", "path.style", "false") == "true",
        multipart_size=int(
            _vendor_prop(p, "cos", "multipart.size", str(8 << 20))))
    return ObjectUnderFileSystem(root_uri, client, properties)


create_cos_ufs.schemes = CosUnderFileSystem.schemes


def create_kodo_ufs(root_uri: str,
                    properties: Optional[Dict[str, str]] = None):
    """``kodo.dialect=native`` -> Qiniu QBox tokens + private download
    URLs (reference ``KodoUnderFileSystem.java``); default stays the
    S3 gateway."""
    if not _native_requested("kodo", properties):
        return KodoUnderFileSystem(root_uri, properties)
    from alluxio_tpu.underfs.object_base import ObjectUnderFileSystem
    from alluxio_tpu.underfs.vendor_native import KodoNativeClient

    p = properties or {}
    ak, sk = _native_creds(p, "kodo")
    client = KodoNativeClient(
        _bucket_of(root_uri), ak, sk,
        rs_host=p.get("kodo.rs.host", "https://rs.qiniuapi.com"),
        rsf_host=p.get("kodo.rsf.host", "https://rsf.qiniuapi.com"),
        up_host=p.get("kodo.up.host", "https://upload.qiniup.com"),
        download_host=p.get("kodo.download.host", ""))
    return ObjectUnderFileSystem(root_uri, client, properties)


create_kodo_ufs.schemes = KodoUnderFileSystem.schemes


class SwiftUnderFileSystem(_CompatUfs):
    """``swift://container/...`` via an OpenStack Swift S3-middleware
    endpoint (reference: ``underfs/swift``)."""

    schemes = ("swift",)
    vendor_prefix = "swift"


class ObsUnderFileSystem(_CompatUfs):
    """``obs://bucket/...`` via Huawei OBS's S3-compatible API."""

    schemes = ("obs",)
    vendor_prefix = "obs"
    default_endpoint = "https://obs.cn-north-1.myhuaweicloud.com"
