"""S3-compatible connectors for the other cloud object stores.

The reference ships per-vendor SDK connectors (``underfs/{oss,cos,kodo,
swift}`` — Alibaba OSS, Tencent COS, Qiniu Kodo, OpenStack Swift). All four
services expose S3-compatible REST gateways, so the TPU build serves them
through the SigV4 client with vendor-specific endpoint defaults instead of
four SDK dependencies. Properties mirror the s3 connector with a vendor
prefix (e.g. ``oss.endpoint``, ``cos.access.key``) and fall back to the
``s3.*`` names.
"""

from __future__ import annotations

from typing import Dict, Optional

from alluxio_tpu.underfs.s3 import S3Client, S3UnderFileSystem


def _remap(prefix: str, properties: Optional[Dict[str, str]],
           default_endpoint: str = "") -> Dict[str, str]:
    props = dict(properties or {})
    for suffix in ("endpoint", "access.key", "secret.key", "region",
                   "path.style", "multipart.size"):
        v = props.get(f"{prefix}.{suffix}", props.get(f"s3.{suffix}"))
        if v is not None:
            props[f"s3.{suffix}"] = v
    if "s3.endpoint" not in props and default_endpoint:
        props["s3.endpoint"] = default_endpoint
    return props


class _CompatUfs(S3UnderFileSystem):
    vendor_prefix = "s3"
    default_endpoint = ""

    def _make_client(self, bucket: str,
                     properties: Optional[Dict[str, str]]) -> S3Client:
        return S3Client(bucket, _remap(self.vendor_prefix, properties,
                                       self.default_endpoint))


class OssUnderFileSystem(_CompatUfs):
    """``oss://bucket/...`` via Alibaba OSS's S3-compatible API
    (reference: ``underfs/oss``)."""

    schemes = ("oss",)
    vendor_prefix = "oss"
    default_endpoint = "https://oss-cn-hangzhou.aliyuncs.com"


class CosUnderFileSystem(_CompatUfs):
    """``cos://bucket/...`` via Tencent COS's S3-compatible API
    (reference: ``underfs/cos``)."""

    schemes = ("cos", "cosn")
    vendor_prefix = "cos"
    default_endpoint = "https://cos.ap-guangzhou.myqcloud.com"


class KodoUnderFileSystem(_CompatUfs):
    """``kodo://bucket/...`` via Qiniu Kodo's S3-compatible API
    (reference: ``underfs/kodo``)."""

    schemes = ("kodo",)
    vendor_prefix = "kodo"
    default_endpoint = "https://s3-cn-east-1.qiniucs.com"


class SwiftUnderFileSystem(_CompatUfs):
    """``swift://container/...`` via an OpenStack Swift S3-middleware
    endpoint (reference: ``underfs/swift``)."""

    schemes = ("swift",)
    vendor_prefix = "swift"


class ObsUnderFileSystem(_CompatUfs):
    """``obs://bucket/...`` via Huawei OBS's S3-compatible API."""

    schemes = ("obs",)
    vendor_prefix = "obs"
    default_endpoint = "https://obs.cn-north-1.myhuaweicloud.com"
