"""Delegating UFS wrapper + sleep-injecting subclass.

Re-designs of the reference's test doubles, shipped in-package because
operators use them for fault drills too:
``tests/src/test/java/alluxio/testutils/underfs/delegating/
DelegatingUnderFileSystem.java`` (intercept any UFS op) and
``.../underfs/sleeping/SleepingUnderFileSystem.java:38`` (per-op
configurable sleeps to simulate a slow object store).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from alluxio_tpu.underfs.base import UnderFileSystem


class DelegatingUnderFileSystem(UnderFileSystem):
    """Forwards every op to a wrapped UFS; subclass and override to
    intercept."""

    def __init__(self, delegate: UnderFileSystem) -> None:
        super().__init__(delegate.get_root(), {})
        self._ufs = delegate

    def get_underfs_type(self):
        return self._ufs.get_underfs_type()

    def create(self, path, options=None):
        return self._ufs.create(path, options)

    def open(self, path, offset=0):
        return self._ufs.open(path, offset)

    def read_range(self, path, offset, length):
        return self._ufs.read_range(path, offset, length)

    def delete_file(self, path):
        return self._ufs.delete_file(path)

    def delete_directory(self, path, options=None):
        return self._ufs.delete_directory(path, options)

    def rename_file(self, src, dst):
        return self._ufs.rename_file(src, dst)

    def rename_directory(self, src, dst):
        return self._ufs.rename_directory(src, dst)

    def mkdirs(self, path, create_parent=True):
        return self._ufs.mkdirs(path, create_parent)

    def get_status(self, path):
        return self._ufs.get_status(path)

    def list_status(self, path):
        return self._ufs.list_status(path)

    def get_fingerprint(self, path):
        return self._ufs.get_fingerprint(path)

    def get_space_total(self):
        return self._ufs.get_space_total()

    def get_space_used(self):
        return self._ufs.get_space_used()

    def supports_active_sync(self):
        return self._ufs.supports_active_sync()

    def close(self):
        self._ufs.close()


class SleepingUnderFileSystem(DelegatingUnderFileSystem):
    """Injects per-op sleeps (reference: SleepingUnderFileSystemOptions):
    ``sleeps={"open": 0.5, "list_status": 1.0}`` delays those ops."""

    def __init__(self, delegate: UnderFileSystem,
                 sleeps: Optional[Dict[str, float]] = None) -> None:
        super().__init__(delegate)
        self.sleeps = dict(sleeps or {})
        self.op_counts: Dict[str, int] = {}

    def _nap(self, op: str) -> None:
        self.op_counts[op] = self.op_counts.get(op, 0) + 1
        s = self.sleeps.get(op, 0.0)
        if s > 0:
            time.sleep(s)

    def create(self, path, options=None):
        self._nap("create")
        return super().create(path, options)

    def open(self, path, offset=0):
        self._nap("open")
        return super().open(path, offset)

    def read_range(self, path, offset, length):
        self._nap("read_range")
        return super().read_range(path, offset, length)

    def delete_file(self, path):
        self._nap("delete_file")
        return super().delete_file(path)

    def delete_directory(self, path, options=None):
        self._nap("delete_directory")
        return super().delete_directory(path, options)

    def rename_file(self, src, dst):
        self._nap("rename_file")
        return super().rename_file(src, dst)

    def rename_directory(self, src, dst):
        self._nap("rename_directory")
        return super().rename_directory(src, dst)

    def mkdirs(self, path, create_parent=True):
        self._nap("mkdirs")
        return super().mkdirs(path, create_parent)

    def get_status(self, path):
        self._nap("get_status")
        return super().get_status(path)

    def list_status(self, path):
        self._nap("list_status")
        return super().list_status(path)
