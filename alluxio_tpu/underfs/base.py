"""Under-filesystem (UFS) SPI.

Re-design of ``core/common/src/main/java/alluxio/underfs/UnderFileSystem.java:183-742``
(create/open/delete/rename/status/fingerprint contract) +
``BaseUnderFileSystem.java``: the pluggable contract between the framework
and persistent storage (local disk, object stores, HDFS, ...).

Differences from the reference, on purpose:
- streams are plain Python file-like objects (``read(n)``, ``write(b)``)
  plus ``open_positioned`` for stateless positioned reads — the shape the
  zero-copy TPU read path wants (pread into a staging buffer);
- the object-store base class lives in ``object_base.py`` and emulates
  directories with breadcrumb markers exactly like the reference's
  ``ObjectUnderFileSystem``.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import BinaryIO, Dict, Iterator, List, Optional

from alluxio_tpu.utils.fingerprint import Fingerprint


@dataclass
class UfsStatus:
    name: str  # path relative to the listed directory, or full path for status
    is_directory: bool = False
    length: int = 0
    last_modified_ms: Optional[int] = None
    owner: str = ""
    group: str = ""
    mode: Optional[int] = None
    content_hash: str = ""
    xattr: Dict[str, str] = field(default_factory=dict)

    def fingerprint(self) -> Fingerprint:
        return Fingerprint.from_status(self)


@dataclass
class CreateOptions:
    create_parent: bool = True
    ensure_atomic: bool = True  # write temp + rename, like reference's NonAtomicFileOutputStream wrapping
    owner: str = ""
    group: str = ""
    mode: int = 0o644


@dataclass
class DeleteOptions:
    recursive: bool = False


class UfsMode(enum.Enum):
    """Per-UFS maintenance mode (reference: ``UfsMode`` / master-tracked
    read-only/no-access maintenance)."""

    READ_WRITE = "READ_WRITE"
    READ_ONLY = "READ_ONLY"
    NO_ACCESS = "NO_ACCESS"


class UnderFileSystem:
    """Abstract UFS. Paths handed to these methods are full UFS URIs
    (e.g. ``/disk/path`` or ``mem://bucket/key``)."""

    #: scheme(s) this UFS serves, e.g. ("s3",) — used by the factory registry
    schemes: tuple = ()

    def __init__(self, root_uri: str, properties: Optional[Dict[str, str]] = None):
        self._root = root_uri
        self._properties = dict(properties or {})

    # -- identity -----------------------------------------------------------
    def get_underfs_type(self) -> str:
        raise NotImplementedError

    def get_root(self) -> str:
        return self._root

    # -- file IO ------------------------------------------------------------
    def create(self, path: str, options: Optional[CreateOptions] = None) -> BinaryIO:
        """Open a new file for writing; visible at ``path`` only on close."""
        raise NotImplementedError

    def open(self, path: str, offset: int = 0) -> BinaryIO:
        """Open for sequential reading starting at ``offset``."""
        raise NotImplementedError

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        """Positioned read (one-shot pread); default via open()."""
        with self.open(path, offset) as f:
            return f.read(length)

    # -- namespace ops ------------------------------------------------------
    def delete_file(self, path: str) -> bool:
        raise NotImplementedError

    def delete_directory(self, path: str,
                         options: Optional[DeleteOptions] = None) -> bool:
        raise NotImplementedError

    def rename_file(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def rename_directory(self, src: str, dst: str) -> bool:
        raise NotImplementedError

    def mkdirs(self, path: str, create_parent: bool = True) -> bool:
        raise NotImplementedError

    # -- status -------------------------------------------------------------
    def exists(self, path: str) -> bool:
        return self.get_status(path) is not None

    def is_file(self, path: str) -> bool:
        s = self.get_status(path)
        return s is not None and not s.is_directory

    def is_directory(self, path: str) -> bool:
        s = self.get_status(path)
        return s is not None and s.is_directory

    def get_status(self, path: str) -> Optional[UfsStatus]:
        raise NotImplementedError

    def list_status(self, path: str) -> Optional[List[UfsStatus]]:
        """Direct children (name = relative); None if path is not a dir."""
        raise NotImplementedError

    def get_fingerprint(self, path: str) -> Fingerprint:
        return Fingerprint.from_status(self.get_status(path))

    # -- capacity / mode ----------------------------------------------------
    def get_space_total(self) -> int:
        return -1

    def get_space_used(self) -> int:
        return -1

    # -- misc ---------------------------------------------------------------
    def supports_active_sync(self) -> bool:
        """Reference: HDFS iNotify active sync (``UnderFileSystem.java:713-742``)."""
        return False

    def connect_from_master(self, hostname: str) -> None:
        pass

    def connect_from_worker(self, hostname: str) -> None:
        pass

    def cleanup(self) -> None:
        pass

    def close(self) -> None:
        pass
