"""S3 UFS connector.

Re-design of ``underfs/s3a/src/main/java/alluxio/underfs/s3a/
S3AUnderFileSystem.java:79`` + ``S3ALowLevelOutputStream.java`` (low-level
multipart upload) without an SDK: hand-rolled SigV4 REST over ``requests``,
endpoint-overridable so it serves AWS S3, GCS-interop, MinIO and the
in-process fake used in tests. Also the S3-compatible face of the other
object-store connectors (OSS/COS/Kodo/Swift expose S3-compatible gateways;
see ``s3_compat.py``).

Properties (mount ``--option``):
  s3.endpoint        override endpoint url (default AWS virtual-host style)
  s3.access.key / s3.secret.key
  s3.region          default us-east-1
  s3.path.style      "true" to force path-style addressing (auto-on when an
                     endpoint override is set)
  s3.multipart.size  part size for streaming uploads (default 8MB)
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.parse
import xml.etree.ElementTree as ET
from typing import BinaryIO, Dict, List, Optional, Tuple

import requests

from alluxio_tpu.underfs.base import CreateOptions
from alluxio_tpu.underfs.object_base import (
    ObjectStoreClient, ObjectUnderFileSystem,
)

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "" if encode_slash else "/"
    return urllib.parse.quote(s, safe=safe + "-_.~")


class SigV4Signer:
    """AWS Signature Version 4 request signing."""

    def __init__(self, access_key: str, secret_key: str, region: str,
                 service: str = "s3") -> None:
        self._ak = access_key
        self._sk = secret_key
        self._region = region
        self._service = service

    def sign(self, method: str, url: str, headers: Dict[str, str],
             payload_sha256: str) -> Dict[str, str]:
        parsed = urllib.parse.urlsplit(url)
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        headers = {k.lower(): v for k, v in headers.items()}
        headers["host"] = parsed.netloc
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_sha256
        canonical_qs = "&".join(
            sorted(f"{_uri_encode(k)}={_uri_encode(v)}"
                   for k, v in urllib.parse.parse_qsl(
                       parsed.query, keep_blank_values=True)))
        signed_names = sorted(h.lower() for h in headers)
        canonical_headers = "".join(
            f"{h}:{str(headers[h]).strip()}\n" for h in signed_names)
        signed_headers = ";".join(signed_names)
        # the request path is already percent-encoded once by the caller;
        # re-encoding here would double-encode and break the signature for
        # keys with spaces/':'/non-ASCII
        canonical = "\n".join([
            method, parsed.path or "/",
            canonical_qs, canonical_headers, signed_headers, payload_sha256])
        scope = f"{datestamp}/{self._region}/{self._service}/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        def _hmac(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = _hmac(b"AWS4" + self._sk.encode(), datestamp)
        k = _hmac(k, self._region)
        k = _hmac(k, self._service)
        k = _hmac(k, "aws4_request")
        signature = hmac.new(k, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self._ak}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}")
        return headers


class S3Client(ObjectStoreClient):
    """REST client over one bucket (reference: the jets3t/AWS-SDK calls in
    ``S3AUnderFileSystem``); speaks SigV4 when keys are configured and
    anonymous otherwise (fake servers / public buckets)."""

    supports_multipart = True

    def __init__(self, bucket: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        props = properties or {}
        self._bucket = bucket
        endpoint = props.get("s3.endpoint",
                             os.environ.get("ATPU_S3_ENDPOINT", ""))
        self._region = props.get("s3.region", "us-east-1")
        path_style = props.get(
            "s3.path.style", "true" if endpoint else "false") == "true"
        if not endpoint:
            endpoint = f"https://s3.{self._region}.amazonaws.com"
        endpoint = endpoint.rstrip("/")
        self._base = (f"{endpoint}/{bucket}" if path_style else
                      endpoint.replace("://", f"://{bucket}."))
        ak = props.get("s3.access.key", os.environ.get("AWS_ACCESS_KEY_ID", ""))
        sk = props.get("s3.secret.key",
                       os.environ.get("AWS_SECRET_ACCESS_KEY", ""))
        self._signer = SigV4Signer(ak, sk, self._region) if ak else None
        self._session = requests.Session()
        self.multipart_size = int(props.get("s3.multipart.size", 8 << 20))

    # -- plumbing ------------------------------------------------------------
    def _request(self, method: str, key: str = "", *, params=None,
                 data: bytes = b"", headers=None,
                 stream: bool = False) -> requests.Response:
        url = f"{self._base}/{_uri_encode(key, encode_slash=False)}"
        if params:
            url += "?" + urllib.parse.urlencode(sorted(params.items()))
        headers = dict(headers or {})
        if self._signer is not None:
            sha = hashlib.sha256(data).hexdigest() if data else _EMPTY_SHA256
            headers = self._signer.sign(method, url, headers, sha)
        return self._session.request(method, url, data=data or None,
                                     headers=headers, stream=stream,
                                     timeout=60)

    # -- ObjectStoreClient ---------------------------------------------------
    def put(self, key: str, data: bytes) -> None:
        r = self._request("PUT", key, data=data)
        r.raise_for_status()

    def get(self, key: str, offset: int = 0,
            length: Optional[int] = None) -> Optional[bytes]:
        headers = {}
        if offset or length is not None:
            end = "" if length is None else str(offset + length - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        r = self._request("GET", key, headers=headers)
        if r.status_code == 404:
            return None
        if r.status_code == 416:  # zero-length range past EOF
            return b""
        r.raise_for_status()
        return r.content

    def head(self, key: str) -> Optional[Tuple[int, int, str]]:
        r = self._request("HEAD", key)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        from alluxio_tpu.underfs.web import _parse_http_date

        length = int(r.headers.get("Content-Length", 0))
        mtime = _parse_http_date(r.headers.get("Last-Modified")) or 0
        return (length, mtime, r.headers.get("ETag", "").strip('"'))

    def delete(self, key: str) -> bool:
        r = self._request("DELETE", key)
        return r.status_code in (200, 204)

    def copy(self, src_key: str, dst_key: str) -> bool:
        r = self._request(
            "PUT", dst_key,
            headers={"x-amz-copy-source":
                     f"/{self._bucket}/{_uri_encode(src_key, False)}"})
        return r.ok

    def list_prefix(self, prefix: str) -> List[str]:
        keys: List[str] = []
        token = None
        while True:
            params = {"list-type": "2", "prefix": prefix,
                      "max-keys": "1000"}
            if token:
                params["continuation-token"] = token
            r = self._request("GET", "", params=params)
            r.raise_for_status()
            root = ET.fromstring(r.content)
            ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
            for el in root.iter(f"{ns}Contents"):
                k = el.find(f"{ns}Key")
                if k is not None and k.text:
                    keys.append(k.text)
            truncated = root.find(f"{ns}IsTruncated")
            if truncated is None or truncated.text != "true":
                break
            tok = root.find(f"{ns}NextContinuationToken")
            token = tok.text if tok is not None else None
            if not token:
                break
        return keys

    # -- multipart (reference: S3ALowLevelOutputStream) ----------------------
    def initiate_multipart(self, key: str) -> str:
        r = self._request("POST", key, params={"uploads": ""})
        r.raise_for_status()
        root = ET.fromstring(r.content)
        ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
        upload_id = root.find(f"{ns}UploadId")
        if upload_id is None or not upload_id.text:
            raise IOError(f"multipart initiate for {key!r}: response "
                          "carried no UploadId")
        return upload_id.text

    def upload_part(self, key: str, upload_id: str, part_number: int,
                    data: bytes) -> str:
        r = self._request("PUT", key, params={
            "partNumber": str(part_number), "uploadId": upload_id},
            data=data)
        r.raise_for_status()
        return r.headers.get("ETag", "").strip('"')

    def complete_multipart(self, key: str, upload_id: str,
                           etags: List[Tuple[int, str]]) -> None:
        body = "<CompleteMultipartUpload>" + "".join(
            f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
            for n, e in etags) + "</CompleteMultipartUpload>"
        r = self._request("POST", key, params={"uploadId": upload_id},
                          data=body.encode())
        r.raise_for_status()

    def abort_multipart(self, key: str, upload_id: str) -> None:
        self._request("DELETE", key, params={"uploadId": upload_id})


class S3UnderFileSystem(ObjectUnderFileSystem):
    """``s3://bucket/...`` (reference: S3AUnderFileSystem)."""

    schemes = ("s3", "s3a")

    def __init__(self, root_uri: str,
                 properties: Optional[Dict[str, str]] = None) -> None:
        rest = root_uri.split("://", 1)[1] if "://" in root_uri else root_uri
        bucket = rest.partition("/")[0]
        super().__init__(root_uri, self._make_client(bucket, properties),
                         properties)

    def _make_client(self, bucket: str,
                     properties: Optional[Dict[str, str]]) -> S3Client:
        return S3Client(bucket, properties)

    # create() comes from ObjectUnderFileSystem: S3Client advertises
    # supports_multipart, so large writes stream via the shared
    # MultipartWriter
