"""Centralized log aggregation (reference: ``logserver/``)."""

from alluxio_tpu.logserver.process import (
    LogServerProcess, enable_remote_logging,
)

__all__ = ["LogServerProcess", "enable_remote_logging"]
