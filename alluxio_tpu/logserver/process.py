"""Log server: one process aggregating every role's logs.

Re-design of ``logserver/src/main/java/alluxio/logserver/
{AlluxioLogServer,AlluxioLogServerProcess}.java``: cluster processes
attach a socket handler that ships log records to this server, which
writes one file per source under its logs dir — the reference's
log4j SocketAppender -> per-client file layout, on Python's stdlib
``logging.handlers.SocketHandler`` wire format (4-byte length prefix +
pickled record dict).
"""

from __future__ import annotations

import io
import logging
import logging.handlers
import os
import pickle
import socketserver
import struct
import threading
from typing import Dict, Optional

LOG = logging.getLogger(__name__)


class _RestrictedUnpickler(pickle.Unpickler):
    """Log records are dicts of primitives: refuse EVERY global
    lookup, so a crafted __reduce__ payload cannot execute code
    (pickle over a network port is otherwise an RCE primitive)."""

    def find_class(self, module, name):
        raise pickle.UnpicklingError(
            f"global {module}.{name} is forbidden in log records")


def _safe_loads(payload: bytes):
    return _RestrictedUnpickler(io.BytesIO(payload)).load()


class _RecordHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        src = self.client_address[0]
        while True:
            head = self.rfile.read(4)
            if len(head) < 4:
                return
            (n,) = struct.unpack(">L", head)
            payload = self.rfile.read(n)
            if len(payload) < n:
                return
            try:
                rec = logging.makeLogRecord(_safe_loads(payload))
            except Exception:  # noqa: BLE001 corrupt frame: drop conn
                LOG.warning("bad log frame from %s", src)
                return
            self.server.owner.write_record(src, rec)


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    owner: "LogServerProcess" = None


class LogServerProcess:
    """Receives records, writes ``<dir>/<source-host>.log``."""

    def __init__(self, logs_dir: str, *, port: int = 0,
                 bind_host: str = "127.0.0.1") -> None:
        """Default bind is loopback: the record stream carries no
        authentication; bind wider only inside a trusted network
        (same stance as the S3 proxy)."""
        self._dir = logs_dir
        os.makedirs(logs_dir, exist_ok=True)
        self._server = _Server((bind_host, port), _RecordHandler)
        self._server.owner = self
        self.port = self._server.server_address[1]
        self._files: Dict[str, logging.Handler] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._fmt = logging.Formatter(
            "%(asctime)s %(levelname)-7s %(name)s [%(_src)s] %(message)s")

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="logserver",
            daemon=True)
        self._thread.start()
        LOG.info("log server on port %d -> %s", self.port, self._dir)
        return self.port

    def write_record(self, src: str, rec: logging.LogRecord) -> None:
        rec._src = src
        with self._lock:
            h = self._files.get(src)
            if h is None:
                h = logging.FileHandler(
                    os.path.join(self._dir, f"{src}.log"))
                h.setFormatter(self._fmt)
                self._files[src] = h
        h.handle(rec)  # handle() takes the handler's own I/O lock

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        with self._lock:
            for h in self._files.values():
                h.close()
            self._files.clear()


def enable_remote_logging(host: str, port: int, *,
                          level: int = logging.INFO,
                          logger_name: str = "") -> logging.Handler:
    """Attach a SocketHandler shipping this process's records to the log
    server (the reference's log4j RemoteAppender wiring). Returns the
    handler so callers can detach it."""
    handler = logging.handlers.SocketHandler(host, port)
    handler.setLevel(level)
    logging.getLogger(logger_name or None).addHandler(handler)
    return handler
