"""Ring attention: sequence/context parallelism over the ICI ring.

Long-context support is first-class in this framework: sequences too long
for one device's HBM are sharded across the mesh's ``data`` axis, and
attention runs blockwise with K/V shards rotating around the ring via
``ppermute`` while a running log-sum-exp keeps the softmax stable
(the standard ring-attention recipe; no reference analogue — the reference
has no compute plane, SURVEY.md 5.7).

Shapes (per device, inside ``shard_map``): q/k/v ``[B, T_local, H, D]``.
The full sequence is ``T_local * axis_size``. Causal masking uses global
block offsets so device i attends correctly to rotated shards.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _block_attention(q, k, v, *, bias=None, scale: float):
    """Plain attention scores for one (q-block, kv-block) pair; returns
    (unnormalized out, running max, running denom) pieces."""
    # [B, H, Tq, Tk]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        scores = scores + bias
    m = jnp.max(scores, axis=-1, keepdims=True)  # [B,H,Tq,1]
    p = jnp.exp(scores - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l


def _causal_bias(t_q: int, t_k: int, q_offset, k_offset, dtype):
    """Bias masking keys that are in the future of each query, with global
    offsets (shards are rotated, so local indices are not global)."""
    q_idx = q_offset + jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
    k_idx = k_offset + jax.lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
    mask = k_idx > q_idx
    return jnp.where(mask, jnp.asarray(-1e9, dtype=dtype), 0).astype(dtype)


def ring_attention_local(q, k, v, *, axis_name: str, causal: bool = True):
    """Per-device body (call inside shard_map over ``axis_name``)."""
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    q_offset = my_index * t_local

    def step(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        # which shard are we holding? (rotations move shard s to s-1)
        src_index = (my_index + i) % axis_size
        k_offset = src_index * t_local
        bias = None
        if causal:
            bias = _causal_bias(t_local, t_local, q_offset, k_offset,
                                jnp.float32)[None, None]
        o, m, l = _block_attention(q, k_cur, v_cur, bias=bias, scale=scale)
        # merge with running (log-sum-exp) accumulators
        m_new = jnp.maximum(m_acc, m)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m - m_new)
        o_acc = o_acc * alpha.transpose(0, 2, 1, 3) + o * beta.transpose(0, 2, 1, 3)
        l_acc = l_acc * alpha + l * beta
        # rotate K/V around the ring for the next step
        perm = [(j, (j - 1) % axis_size) for j in range(axis_size)]
        k_next = jax.lax.ppermute(k_cur, axis_name, perm)
        v_next = jax.lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, m_new, l_acc, k_next, v_next), None

    o0 = jnp.zeros((b, t_local, h, d), dtype=jnp.float32)
    m0 = jnp.full((b, h, t_local, 1), -jnp.inf, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, t_local, 1), dtype=jnp.float32)
    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k.astype(jnp.float32), v.astype(jnp.float32)),
        jnp.arange(axis_size))
    out = o / jnp.maximum(l, 1e-20).transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention(q, k, v, *, mesh, axis: str = "data",
                   causal: bool = True):
    """Sequence-parallel attention: q/k/v sharded on ``axis`` along T.

    Global shapes ``[B, T, H, D]``; per-device compute is blockwise with
    K/V rotating over ICI. XLA overlaps each ppermute with the next
    block's einsums.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    spec = P(None, axis, None, None)
    body = functools.partial(ring_attention_local, axis_name=axis,
                             causal=causal)
    try:
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    except TypeError:  # older jax spells it check_rep
        fn = shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_rep=False)
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal: bool = True):
    """Single-device attention for correctness checks."""
    b, t, h, d = q.shape
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        bias = _causal_bias(t, t, 0, 0, jnp.float32)
        scores = scores + bias[None, None]
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
