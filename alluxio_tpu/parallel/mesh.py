"""Device mesh management + TPU locality mapping.

The SPMD substrate for the framework's distribution strategies
(SURVEY.md 2.11): data-parallel block striping -> ``data`` axis shardings;
replication fan-out -> replicated shardings over ICI; locality scheduling ->
``TieredIdentity`` derived from mesh coordinates (host < slice < pod).

Axes convention: ``data`` (batch / sequence shards), ``model`` (tensor
parallel). Meshes come from ``jax.devices()`` reshaped; on multi-host
deployments the same code runs under ``jax.distributed`` with the global
device set.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from alluxio_tpu.utils.wire import LocalityTier, TieredIdentity

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(axis_sizes: Optional[Dict[str, int]] = None, *,
              devices=None):
    """Build a Mesh; default = all devices on the data axis."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    if not axis_sizes:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes.keys())
    shape = tuple(axis_sizes.values())
    if int(np.prod(shape)) != len(devices):
        raise ValueError(
            f"mesh shape {axis_sizes} needs {int(np.prod(shape))} devices, "
            f"have {len(devices)}")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, names)


def named_sharding(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))


def replicated(mesh):
    return named_sharding(mesh)


def identity_for_device(device) -> TieredIdentity:
    """Map a device's topology coordinates onto the locality tiers the
    placement policies understand (reference: ``TieredIdentityFactory``;
    here locality comes from the TPU topology instead of rack scripts)."""
    tiers = [LocalityTier("host", f"host-{getattr(device, 'process_index', 0)}")]
    coords = getattr(device, "coords", None)
    slice_index = getattr(device, "slice_index", None)
    if slice_index is not None:
        tiers.append(LocalityTier("slice", f"slice-{slice_index}"))
    elif coords is not None:
        tiers.append(LocalityTier("slice", f"slice-{coords[-1]}"))
    tiers.append(LocalityTier("pod", "pod-0"))
    return TieredIdentity(tiers)


def shard_host_batch(mesh, host_array, *, axis: str = DATA_AXIS):
    """Place one host array as a mesh-sharded jax.Array (batch dim split
    over ``axis``): the device-side entry of the data-parallel read path."""
    import jax

    return jax.device_put(host_array, named_sharding(mesh, axis))
