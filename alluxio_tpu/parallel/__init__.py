"""SPMD distribution over device meshes (TPU-native; SURVEY.md 2.11)."""

from alluxio_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS, MODEL_AXIS, make_mesh, named_sharding, replicated,
    shard_host_batch,
)
from alluxio_tpu.parallel.ring_attention import (  # noqa: F401
    reference_attention, ring_attention,
)
