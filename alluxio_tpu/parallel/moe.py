"""Expert-parallel mixture-of-experts FFN.

The EP strategy for the multichip story (SURVEY §2.11's SPMD checklist;
the reference has no model compute, so this is the TPU-native extension
the data plane feeds): experts are sharded over a mesh axis and tokens
are dispatched densely via one-hot combine — written as plain einsums
with sharding constraints so XLA inserts the all-to-alls itself (the
scaling-book recipe: annotate, don't hand-schedule).

Top-1 token-choice routing with capacity = tokens (dense dispatch): at
the sizes the dryrun exercises, correctness and sharding layout are the
point; capacity-dropping is an optimization layered on the same einsums.
"""

from __future__ import annotations

from typing import Any, Dict

EXPERT_AXIS = "model"  # experts ride the model axis (ep x tp fuse)


def init_moe_params(key, *, n_experts: int, d_model: int,
                    d_ff: int, dtype=None) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.float32
    k1, k2, k3 = jax.random.split(key, 3)
    scale = d_model ** -0.5
    return {
        "gate": (jax.random.normal(k1, (d_model, n_experts)) *
                 scale).astype(dtype),
        "w_in": (jax.random.normal(k2, (n_experts, d_model, d_ff)) *
                 scale).astype(dtype),
        "w_out": (jax.random.normal(k3, (n_experts, d_ff, d_model)) *
                  (d_ff ** -0.5)).astype(dtype),
    }


def moe_param_specs() -> Dict[str, Any]:
    """The ONE source of the expert layout (PartitionSpecs): experts
    sharded over the expert axis, gate replicated. The transformer's
    ``param_shardings`` and ``moe_param_shardings`` both derive from
    this so the layouts cannot drift."""
    from jax.sharding import PartitionSpec as P

    return {
        "gate": P(),
        "w_in": P(EXPERT_AXIS),
        "w_out": P(EXPERT_AXIS),
    }


def moe_param_shardings(mesh) -> Dict[str, Any]:
    from jax.sharding import NamedSharding

    return {k: NamedSharding(mesh, spec)
            for k, spec in moe_param_specs().items()}


def moe_ffn(params, x):
    """(B, T, d_model) -> (B, T, d_model), top-1 routed.

    Dense dispatch: ``probs`` one-hot selects the expert per token; the
    expert einsums contract over the sharded expert dim, so under pjit
    the dispatch/combine become all-to-all-style collectives over
    ``EXPERT_AXIS`` without any manual communication.
    """
    import jax
    import jax.numpy as jnp

    logits = jnp.einsum("btd,de->bte", x, params["gate"])
    top = jnp.argmax(logits, axis=-1)
    n_experts = params["gate"].shape[-1]
    onehot = jax.nn.one_hot(top, n_experts, dtype=x.dtype)
    # router gradient flows through the softmax prob of the taken expert
    gate = jnp.take_along_axis(
        jax.nn.softmax(logits, axis=-1), top[..., None], axis=-1)
    # dispatch: (e, B, T, d) views of tokens, zero where not routed
    dispatched = jnp.einsum("btd,bte->ebtd", x, onehot)
    hidden = jax.nn.gelu(
        jnp.einsum("ebtd,edf->ebtf", dispatched, params["w_in"]))
    expert_out = jnp.einsum("ebtf,efd->ebtd", hidden, params["w_out"])
    # combine: sum over experts (only the routed slot is nonzero)
    combined = jnp.einsum("ebtd,bte->btd", expert_out, onehot)
    return combined * gate


def load_balance_loss(params, x) -> "Any":
    """Auxiliary load-balancing loss (Switch-style): mean fraction per
    expert x mean router prob per expert, scaled by n_experts^2."""
    import jax
    import jax.numpy as jnp

    logits = jnp.einsum("btd,de->bte", x, params["gate"])
    probs = jax.nn.softmax(logits, axis=-1)
    n_experts = params["gate"].shape[-1]
    hard = jax.nn.one_hot(jnp.argmax(logits, -1), n_experts,
                          dtype=x.dtype)
    frac = hard.mean(axis=(0, 1))
    prob = probs.mean(axis=(0, 1))
    return (frac * prob).sum() * n_experts * n_experts
