"""Pipeline parallelism: GPipe-style microbatch rotation in shard_map.

The PP strategy for the multichip story (SURVEY §2.11 checklist): layer
stages are sharded over a ``pipe`` mesh axis; microbatches stream
through stages with ``lax.ppermute`` carrying activations to the next
stage each step (the scaling-book shard_map pipeline recipe — the
collectives ride ICI neighbors, exactly what ``ppermute`` lowers to).

The schedule is the classic GPipe fill-drain: with S stages and M
microbatches, the loop runs S-1+M steps; stage s computes on step t
when ``0 <= t - s < M``. Everything is static shapes inside one jit.
"""

from __future__ import annotations

PIPE_AXIS = "pipe"


def pipeline_apply(stage_fn, stage_params, x_microbatches, *, mesh,
                   axis: str = PIPE_AXIS):
    """Run microbatches through pipeline stages.

    - ``stage_fn(params, x) -> x``: one stage's compute (same shape in
      and out — e.g. a block of transformer layers).
    - ``stage_params``: pytree whose leaves have a leading stage dim of
      size S, sharded over ``axis`` (one slice per device).
    - ``x_microbatches``: (M, ...) microbatches, replicated.

    Returns (M, ...) outputs after all S stages.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map as _sm  # jax >= 0.8 (check_vma)

        def shard_map(f, *, mesh, in_specs, out_specs):
            return _sm(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
    except ImportError:  # pragma: no cover - older jax (check_rep)
        from jax.experimental.shard_map import shard_map as _sme

        def shard_map(f, *, mesh, in_specs, out_specs):
            return _sme(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, check_rep=False)

    n_stages = mesh.shape[axis]
    n_micro = x_microbatches.shape[0]
    steps = n_stages - 1 + n_micro

    param_spec = jax.tree_util.tree_map(lambda _: P(axis), stage_params)

    def stage_body(params, xs):
        # inside shard_map: leading stage dim is THIS device's slice
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = jax.lax.axis_index(axis)

        def step(carry, t):
            buf, outs = carry  # buf: activation entering this stage
            # stage 0 feeds itself from the microbatch stream
            feed = jnp.where(t < n_micro, t, 0)
            x_in = jnp.where(stage == 0,
                             xs[feed], buf)
            active = jnp.logical_and(t - stage >= 0,
                                     t - stage < n_micro)
            y = jnp.where(active, stage_fn(params, x_in), x_in)
            # the LAST stage writes its finished microbatch out
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            write = jnp.logical_and(stage == n_stages - 1, active)
            outs = jax.lax.cond(
                write,
                lambda o: o.at[out_idx].set(y),
                lambda o: o,
                outs)
            # rotate activations to the next stage over ICI neighbors
            nxt = jax.lax.ppermute(
                y, axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (nxt, outs), None

        buf0 = jnp.zeros_like(xs[0])
        outs0 = jnp.zeros_like(xs)
        (final_buf, outs), _ = jax.lax.scan(
            step, (buf0, outs0), jnp.arange(steps))
        # only the last stage wrote finished microbatches; psum over the
        # pipe axis replicates them to every stage (out_specs says the
        # result is replicated — without this, rank 0's zeros win)
        return jax.lax.psum(outs, axis)

    fn = shard_map(
        stage_body, mesh=mesh,
        in_specs=(param_spec, P()),
        out_specs=P())
    return fn(stage_params, x_microbatches)
