"""ICI data plane: warm cached blocks sharded across the device mesh,
served to peers by XLA collectives instead of per-block gRPC.

**The TPU-native transport the reference has no analogue for** (SURVEY
§5.8: "NEW: ICI collectives as the intra-slice 'remote read'"; §2.11
block-striping row: "block map keyed by device mesh position"). In the
reference, a client reading a block cached on another worker opens a gRPC
stream through both hosts' NICs
(``client/block/stream/GrpcDataReader.java:49``). On a TPU slice the warm
copy already sits in a peer chip's HBM one ICI hop away — so the "remote
read" becomes an ``all_gather``/``ppermute`` *inside jit*, riding ICI at
hundreds of GB/s with zero host traffic, zero gRPC, and zero
host<->device copies.

Design:

- ``MeshBlockCache.load_global`` builds ONE global ``jax.Array`` of shape
  ``(n_blocks, block_bytes)`` sharded ``P(axis)`` over the mesh: device
  ``d`` holds blocks ``[d*per_dev, (d+1)*per_dev)`` in its HBM. Placement
  IS the mesh position — the client-side block map for the warm set.
  Each host loads only ITS devices' blocks from the co-located worker
  (short-circuit mmap); assembly uses
  ``jax.make_array_from_single_device_arrays`` — the idiomatic multi-host
  pattern (no host ever materializes the global array).
- Warm "remote reads" are jitted collectives over the cached array:
  ``gather_all`` (every device sees every block; ICI all-gather),
  ``ring_shift`` (each device reads its neighbor's shard; ICI ppermute —
  the sequence-parallel access pattern), and ``global_batch`` (assemble a
  batch from blocks wherever they live, fused into the consumer's jit).
- ``replicate`` broadcasts a hot shard to every device
  (``device_put_replicated`` fan-out; reference analogue:
  ``ReplicationChecker`` + ``job/plan/replicate`` — but one collective,
  not N gRPC streams).

Cold loads still ride the worker data plane (UFS -> worker tier -> host
-> HBM); this module is the warm path on top of it.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from alluxio_tpu.parallel.mesh import DATA_AXIS, named_sharding


def _shard_map(*args, **kwargs):
    """shard_map across jax versions: >=0.8 top-level with ``check_vma``,
    older experimental with ``check_rep`` (the replication check cannot
    statically infer all_gather-produced replication either way)."""
    try:  # jax >= 0.8
        from jax import shard_map as sm

        kwargs.setdefault("check_vma", False)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as sm

        kwargs.setdefault("check_rep", False)
    return sm(*args, **kwargs)


class MeshBlockCache:
    """Warm block cache sharded over a mesh axis; collective reads.

    One instance manages one dataset (an ordered list of ``(path, block)``
    pairs padded to equal block size). The global order is striped so the
    sharding is contiguous per device: global index ``g = d*per_dev + k``
    is the ``k``-th block of device ``d``.
    """

    def __init__(self, mesh, *, axis: str = DATA_AXIS,
                 block_bytes: int, dtype=np.uint8,
                 client_host: str = "") -> None:
        import socket

        import jax

        self._jax = jax
        self.mesh = mesh
        self.axis = axis
        self.block_bytes = block_bytes
        self.dtype = np.dtype(dtype)
        self.n_devices = int(np.prod([
            mesh.shape[a] for a in ([axis] if isinstance(axis, str)
                                    else axis)]))
        #: (path, block_index) in global order, set by load_global
        self.plan: List[Tuple[str, int]] = []
        #: global block index -> master block id (for placement reports)
        self.block_ids: List[int] = []
        self.client_host = client_host or socket.gethostname()
        self._block_client = None
        #: path -> master block ids (filled from loaders; avoids a
        #: get_status RPC per path on every resolve)
        self._bids_by_path: Dict[str, List[int]] = {}
        #: per_dev -> jitted batch assembler (jit caches by fn object;
        #: rebuilding the closure per call would retrace every batch)
        self._batch_fns: Dict[int, object] = {}

    # -- placement -----------------------------------------------------------
    def placement(self, n_blocks: int) -> Dict[int, int]:
        """global block index -> mesh position (the warm-set block map)."""
        per_dev = -(-n_blocks // self.n_devices)
        return {g: g // per_dev for g in range(n_blocks)}

    # -- load (cold/host path; per-host locality) ----------------------------
    def load_global(self, fs, paths: Sequence[str], *,
                    loader=None, report: bool = True,
                    io_threads: int = 8):
        """Materialize the warm set: every addressable device's shard is
        loaded from the host-local worker tier (short-circuit mmap ->
        one device_put per device), then assembled into one global sharded
        array WITHOUT any host seeing the whole dataset. Per-device host
        reads run in an IO thread pool and the device_puts are issued
        as each shard completes, so transfer overlaps the next reads.

        ``report=True`` registers this host's device placement with the
        master block map (SURVEY §2.11 "block map keyed by device mesh
        position") so the control plane can steer consumers at warm
        copies one ICI hop away.

        ``loader``: an existing DeviceBlockLoader to reuse (tests); else
        one is built per call.
        """
        from concurrent.futures import ThreadPoolExecutor

        import jax

        from alluxio_tpu.client.jax_io import DeviceBlockLoader

        sharding = named_sharding(self.mesh, self.axis)
        own_loader = loader is None
        if own_loader:
            loader = DeviceBlockLoader(fs, paths, hbm_bytes=0,
                                       dtype=self.dtype)
        try:
            self.plan = list(loader.plan)
            self._resolve_block_ids(fs, loader)
            n = len(self.plan)
            per_dev = -(-n // self.n_devices)
            elems = self.block_bytes // self.dtype.itemsize
            # mesh-position-major device order along the sharded axis
            mesh_devs = self.mesh.devices.reshape(-1)
            addressable = {d.id for d in jax.local_devices()}
            my_positions = [p for p in range(self.n_devices)
                            if mesh_devs[p].id in addressable]

            def read_shard(d_pos: int):
                rows = []
                for k in range(per_dev):
                    g = d_pos * per_dev + k
                    rows.append(self._host_row(loader, g, n, elems))
                return d_pos, np.stack(rows)  # (per_dev, elems)

            shards = {}
            # host reads (mmap/stream) parallelize; device_put is issued
            # the moment a shard's rows are ready (async transfer)
            with ThreadPoolExecutor(max_workers=max(1, io_threads)) as ex:
                for d_pos, local in ex.map(read_shard, my_positions):
                    shards[d_pos] = jax.device_put(local, mesh_devs[d_pos])
            global_shape = (per_dev * self.n_devices, elems)
            cached = jax.make_array_from_single_device_arrays(
                global_shape, sharding,
                [shards[p] for p in my_positions])
            if report:
                self.report_placement(fs, my_positions, per_dev, n)
            return cached
        finally:
            if own_loader:
                loader.close()

    def _host_row(self, loader, g: int, n: int, elems: int):
        if g >= n:  # pad the ragged tail with zeros
            return np.zeros(elems, self.dtype)
        host = loader.host_block(*self.plan[g])
        if host.shape[0] != elems:
            padded = np.zeros(elems, self.dtype)
            padded[:host.shape[0]] = host
            host = padded
        return host

    def _resolve_block_ids(self, fs, loader=None) -> None:
        if loader is not None:  # loader already fetched every status
            self._bids_by_path.update(
                getattr(loader, "block_ids_by_path", {}))
        self.block_ids = []
        for path, idx in self.plan:
            bids = self._bids_by_path.get(path)
            if bids is None:
                bids = self._bids_by_path[path] = \
                    list(fs.get_status(path).block_ids)
            self.block_ids.append(bids[idx] if idx < len(bids) else -1)

    # -- control-plane placement reporting -----------------------------------
    def report_placement(self, fs, my_positions: Sequence[int],
                         per_dev: int, n: int) -> None:
        """Tell the master which blocks are HBM-resident at which mesh
        position (this host's shard of the warm set only — each host
        reports its own; the master merges)."""
        client = self._block_master_client(fs)
        if client is None:
            return
        mesh_blocks = {}
        for pos in my_positions:
            bids = [self.block_ids[g]
                    for g in range(pos * per_dev,
                                   min((pos + 1) * per_dev, n))
                    if self.block_ids[g] >= 0]
            if bids:
                mesh_blocks[pos] = bids
        try:
            client.report_device_blocks(self.client_host, mesh_blocks)
        except Exception:  # noqa: BLE001 placement is advisory cache state
            pass

    def drop_placement(self, fs) -> None:
        """Warm set released: clear this host's device block map entries
        (pairs with eviction/close)."""
        client = self._block_master_client(fs)
        if client is not None:
            try:
                client.clear_device_blocks(self.client_host)
            except Exception:  # noqa: BLE001 advisory
                pass

    def _block_master_client(self, fs):
        if self._block_client is None:
            store = getattr(fs, "store", None)
            self._block_client = getattr(store, "block_master", None)
            if self._block_client is None:
                import logging

                logging.getLogger(__name__).warning(
                    "no block-master client on %r: device placement "
                    "reporting disabled", type(fs).__name__)
        return self._block_client

    # -- warm collective reads (zero host traffic) ---------------------------
    def gather_all(self, cached):
        """Every device materializes ALL blocks: one ICI all-gather inside
        jit — the collective replacement for N remote gRPC block reads.
        Returns a fn suitable for fusion into a consumer step."""
        import jax
        from jax.sharding import PartitionSpec as P

        @jax.jit
        def _gather(x):
            def f(local):  # local: (per_dev, elems)
                return jax.lax.all_gather(
                    local, self.axis, axis=0, tiled=True)

            return _shard_map(
                f, mesh=self.mesh, in_specs=P(self.axis, None),
                out_specs=P())(x)

        return _gather(cached)

    def ring_shift(self, cached, shift: int = 1):
        """Each device receives its ``shift``-th neighbor's shard over the
        ICI ring (ppermute) — the sequence-parallel/ring-attention access
        pattern applied to cached data. Sharding is preserved."""
        import jax
        from jax.sharding import PartitionSpec as P

        n = self.n_devices

        @jax.jit
        def _shift(x):
            def f(local):
                # (source, dest): device d receives from (d + shift) % n
                perm = [((d + shift) % n, d) for d in range(n)]
                return jax.lax.ppermute(local, self.axis, perm)

            return _shard_map(
                f, mesh=self.mesh, in_specs=P(self.axis, None),
                out_specs=P(self.axis, None))(x)

        return _shift(cached)

    def global_batch(self, cached, indices):
        """Assemble a batch of blocks by GLOBAL index regardless of which
        device caches them, moving O(batch) bytes over ICI — NOT the
        whole warm set. Each device takes the requested rows it owns from
        its local shard (others contribute zeros), then ONE psum merges
        the batch: ICI traffic is the reduction of a (batch, elems)
        buffer, independent of warm-set size. ``indices``: 1-D array of
        global block ids. Output is replicated (every device gets the
        whole batch); compose into the consumer's jit so XLA overlaps
        the collective with compute."""
        import jax.numpy as jnp

        per_dev = cached.shape[0] // self.n_devices
        return self.batch_fn(per_dev)(cached, jnp.asarray(indices))

    def batch_fn(self, per_dev: int):
        """The jitted O(batch) assembler, cached per ``per_dev`` (exposed
        so consumers can fuse it into their step and tests can inspect
        the lowering)."""
        cached_fn = self._batch_fns.get(per_dev)
        if cached_fn is not None:
            return cached_fn
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        @jax.jit
        def _assemble(x, idx):
            def f(local, idx_rep):
                # local: (per_dev, elems); idx_rep: (B,) global indices
                pos = jax.lax.axis_index(self.axis)
                local_idx = idx_rep - pos * per_dev
                mine = (local_idx >= 0) & (local_idx < per_dev)
                rows = jnp.take(local,
                                jnp.clip(local_idx, 0, per_dev - 1),
                                axis=0)           # (B, elems)
                rows = jnp.where(mine[:, None], rows,
                                 jnp.zeros((), local.dtype))
                # O(batch) collective: merge owners' contributions
                return jax.lax.psum(rows, self.axis)

            return _shard_map(
                f, mesh=self.mesh, in_specs=(P(self.axis, None), P()),
                out_specs=P())(x, idx)

        self._batch_fns[per_dev] = _assemble
        return _assemble

    def replicate(self, cached, block_index: int):
        """Fan a hot block out to EVERY device (the
        ``device_put_replicated``/ICI-broadcast replication of SURVEY
        §2.11): one collective broadcast, not N point-to-point streams.
        Returns a fully-replicated (elems,) array."""
        import jax
        import jax.numpy as jnp

        out_sharding = named_sharding(self.mesh)  # replicated

        @jax.jit
        def _pick(x):
            row = jax.lax.dynamic_slice_in_dim(x, block_index, 1, axis=0)
            return jax.lax.with_sharding_constraint(
                jnp.squeeze(row, axis=0), out_sharding)

        return _pick(cached)

    # -- warm-set turnover (eviction/refresh) --------------------------------
    def turnover(self, cached, fs, replacements: Dict[int, Tuple[str, int]],
                 *, loader=None, report: bool = True):
        """Replace warm-set rows in place: ``replacements`` maps a global
        block index -> a new ``(path, block_index)`` source. Only hosts
        owning a replaced row do IO, and each touched device gets ONE
        donated in-place row update — O(changed blocks) host->device
        traffic, untouched shards are reused as-is. The refreshed
        placement is re-reported to the master block map.

        This is the warm-set eviction/refresh story: evict = replace a
        cold block with the next epoch's data; the HBM footprint never
        grows (the old shard buffer is donated into the update).
        """
        import jax

        from alluxio_tpu.client.jax_io import DeviceBlockLoader

        if not replacements:
            return cached
        n = len(self.plan)
        per_dev = cached.shape[0] // self.n_devices
        elems = cached.shape[1]
        sharding = named_sharding(self.mesh, self.axis)
        mesh_devs = self.mesh.devices.reshape(-1)
        addressable = {d.id for d in jax.local_devices()}
        my_positions = [p for p in range(self.n_devices)
                        if mesh_devs[p].id in addressable]
        # validate EVERY index before mutating the plan: a bad key must
        # not leave plan/device state describing different data
        for g in replacements:
            if not 0 <= g < n:
                raise IndexError(f"global block index {g} out of range")
        for g, src in replacements.items():
            self.plan[g] = tuple(src)
        self._resolve_block_ids(fs)

        new_paths = sorted({p for p, _i in replacements.values()})
        own_loader = loader is None
        if own_loader:
            loader = DeviceBlockLoader(fs, new_paths, hbm_bytes=0,
                                       dtype=self.dtype)
        try:
            @partial(jax.jit, donate_argnums=0)
            def _update(local, rows, data):
                return local.at[rows].set(data)

            shards = {s.device: s.data for s in cached.addressable_shards}
            for pos in my_positions:
                dev = mesh_devs[pos]
                touched = sorted(g for g in replacements
                                 if g // per_dev == pos)
                if not touched:
                    continue
                data = np.stack([self._host_row(loader, g, n, elems)
                                 for g in touched])
                rows = np.asarray([g - pos * per_dev for g in touched])
                shards[dev] = _update(shards[dev],
                                      jax.device_put(rows, dev),
                                      jax.device_put(data, dev))
            cached = jax.make_array_from_single_device_arrays(
                (per_dev * self.n_devices, elems), sharding,
                [shards[mesh_devs[p]] for p in my_positions])
            if report:
                self.report_placement(fs, my_positions, per_dev, n)
            return cached
        finally:
            if own_loader:
                loader.close()

    # -- introspection -------------------------------------------------------
    def describe_placement(self, cached) -> Dict[int, List[int]]:
        """mesh position -> global block ids resident there (from the
        REAL sharding of the cached array, not the nominal plan)."""
        out: Dict[int, List[int]] = {}
        per_dev = cached.shape[0] // self.n_devices
        mesh_devs = list(self.mesh.devices.reshape(-1))
        for shard in cached.addressable_shards:
            pos = mesh_devs.index(shard.device)
            start = shard.index[0].start or 0
            out[pos] = list(range(start, start + per_dev))
        return out
