"""ICI data plane: warm cached blocks sharded across the device mesh,
served to peers by XLA collectives instead of per-block gRPC.

**The TPU-native transport the reference has no analogue for** (SURVEY
§5.8: "NEW: ICI collectives as the intra-slice 'remote read'"; §2.11
block-striping row: "block map keyed by device mesh position"). In the
reference, a client reading a block cached on another worker opens a gRPC
stream through both hosts' NICs
(``client/block/stream/GrpcDataReader.java:49``). On a TPU slice the warm
copy already sits in a peer chip's HBM one ICI hop away — so the "remote
read" becomes an ``all_gather``/``ppermute`` *inside jit*, riding ICI at
hundreds of GB/s with zero host traffic, zero gRPC, and zero
host<->device copies.

Design:

- ``MeshBlockCache.load_global`` builds ONE global ``jax.Array`` of shape
  ``(n_blocks, block_bytes)`` sharded ``P(axis)`` over the mesh: device
  ``d`` holds blocks ``[d*per_dev, (d+1)*per_dev)`` in its HBM. Placement
  IS the mesh position — the client-side block map for the warm set.
  Each host loads only ITS devices' blocks from the co-located worker
  (short-circuit mmap); assembly uses
  ``jax.make_array_from_single_device_arrays`` — the idiomatic multi-host
  pattern (no host ever materializes the global array).
- Warm "remote reads" are jitted collectives over the cached array:
  ``gather_all`` (every device sees every block; ICI all-gather),
  ``ring_shift`` (each device reads its neighbor's shard; ICI ppermute —
  the sequence-parallel access pattern), and ``global_batch`` (assemble a
  batch from blocks wherever they live, fused into the consumer's jit).
- ``replicate`` broadcasts a hot shard to every device
  (``device_put_replicated`` fan-out; reference analogue:
  ``ReplicationChecker`` + ``job/plan/replicate`` — but one collective,
  not N gRPC streams).

Cold loads still ride the worker data plane (UFS -> worker tier -> host
-> HBM); this module is the warm path on top of it.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from alluxio_tpu.parallel.mesh import DATA_AXIS, named_sharding


def _shard_map(*args, **kwargs):
    """shard_map across jax versions: >=0.8 top-level with ``check_vma``,
    older experimental with ``check_rep`` (the replication check cannot
    statically infer all_gather-produced replication either way)."""
    try:  # jax >= 0.8
        from jax import shard_map as sm

        kwargs.setdefault("check_vma", False)
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map as sm

        kwargs.setdefault("check_rep", False)
    return sm(*args, **kwargs)


class MeshBlockCache:
    """Warm block cache sharded over a mesh axis; collective reads.

    One instance manages one dataset (an ordered list of ``(path, block)``
    pairs padded to equal block size). The global order is striped so the
    sharding is contiguous per device: global index ``g = d*per_dev + k``
    is the ``k``-th block of device ``d``.
    """

    def __init__(self, mesh, *, axis: str = DATA_AXIS,
                 block_bytes: int, dtype=np.uint8) -> None:
        import jax

        self._jax = jax
        self.mesh = mesh
        self.axis = axis
        self.block_bytes = block_bytes
        self.dtype = np.dtype(dtype)
        self.n_devices = int(np.prod([
            mesh.shape[a] for a in ([axis] if isinstance(axis, str)
                                    else axis)]))
        #: (path, block_index) in global order, set by load_global
        self.plan: List[Tuple[str, int]] = []

    # -- placement -----------------------------------------------------------
    def placement(self, n_blocks: int) -> Dict[int, int]:
        """global block index -> mesh position (the warm-set block map)."""
        per_dev = -(-n_blocks // self.n_devices)
        return {g: g // per_dev for g in range(n_blocks)}

    # -- load (cold/host path; per-host locality) ----------------------------
    def load_global(self, fs, paths: Sequence[str], *,
                    loader=None):
        """Materialize the warm set: every addressable device's shard is
        loaded from the host-local worker tier (short-circuit mmap ->
        one device_put per device), then assembled into one global sharded
        array WITHOUT any host seeing the whole dataset.

        ``loader``: an existing DeviceBlockLoader to reuse (tests); else
        one is built per call.
        """
        import jax

        from alluxio_tpu.client.jax_io import DeviceBlockLoader

        sharding = named_sharding(self.mesh, self.axis)
        own_loader = loader is None
        if own_loader:
            loader = DeviceBlockLoader(fs, paths, hbm_bytes=0,
                                       dtype=self.dtype)
        try:
            self.plan = list(loader.plan)
            n = len(self.plan)
            per_dev = -(-n // self.n_devices)
            elems = self.block_bytes // self.dtype.itemsize
            # mesh-position-major device order along the sharded axis
            mesh_devs = self.mesh.devices.reshape(-1)
            addressable = {d.id for d in jax.local_devices()}
            shards = []
            for d_pos in range(self.n_devices):
                dev = mesh_devs[d_pos]
                if dev.id not in addressable:
                    continue  # another host loads this shard
                rows = []
                for k in range(per_dev):
                    g = d_pos * per_dev + k
                    if g < n:
                        host = loader.host_block(*self.plan[g])
                    else:  # pad the ragged tail with zeros
                        host = np.zeros(elems, self.dtype)
                    if host.shape[0] != elems:
                        padded = np.zeros(elems, self.dtype)
                        padded[:host.shape[0]] = host
                        host = padded
                    rows.append(host)
                local = np.stack(rows)  # (per_dev, elems)
                shards.append(jax.device_put(local, dev))
            global_shape = (per_dev * self.n_devices, elems)
            return jax.make_array_from_single_device_arrays(
                global_shape, sharding, shards)
        finally:
            if own_loader:
                loader.close()

    # -- warm collective reads (zero host traffic) ---------------------------
    def gather_all(self, cached):
        """Every device materializes ALL blocks: one ICI all-gather inside
        jit — the collective replacement for N remote gRPC block reads.
        Returns a fn suitable for fusion into a consumer step."""
        import jax
        from jax.sharding import PartitionSpec as P

        @jax.jit
        def _gather(x):
            def f(local):  # local: (per_dev, elems)
                return jax.lax.all_gather(
                    local, self.axis, axis=0, tiled=True)

            return _shard_map(
                f, mesh=self.mesh, in_specs=P(self.axis, None),
                out_specs=P())(x)

        return _gather(cached)

    def ring_shift(self, cached, shift: int = 1):
        """Each device receives its ``shift``-th neighbor's shard over the
        ICI ring (ppermute) — the sequence-parallel/ring-attention access
        pattern applied to cached data. Sharding is preserved."""
        import jax
        from jax.sharding import PartitionSpec as P

        n = self.n_devices

        @jax.jit
        def _shift(x):
            def f(local):
                # (source, dest): device d receives from (d + shift) % n
                perm = [((d + shift) % n, d) for d in range(n)]
                return jax.lax.ppermute(local, self.axis, perm)

            return _shard_map(
                f, mesh=self.mesh, in_specs=P(self.axis, None),
                out_specs=P(self.axis, None))(x)

        return _shift(cached)

    def global_batch(self, cached, indices):
        """Assemble a batch of blocks by GLOBAL index regardless of which
        device caches them: all-gather + gather fused into one jit (the
        consumer composes this into its step so XLA overlaps the
        collective with compute). ``indices``: 1-D array of block ids.
        Output is replicated (each device gets the whole batch)."""
        import jax
        import jax.numpy as jnp

        gathered = self.gather_all(cached)

        @jax.jit
        def _take(g, idx):
            return jnp.take(g, idx, axis=0)

        return _take(gathered, jnp.asarray(indices))

    def replicate(self, cached, block_index: int):
        """Fan a hot block out to EVERY device (the
        ``device_put_replicated``/ICI-broadcast replication of SURVEY
        §2.11): one collective broadcast, not N point-to-point streams.
        Returns a fully-replicated (elems,) array."""
        import jax
        import jax.numpy as jnp

        out_sharding = named_sharding(self.mesh)  # replicated

        @jax.jit
        def _pick(x):
            row = jax.lax.dynamic_slice_in_dim(x, block_index, 1, axis=0)
            return jax.lax.with_sharding_constraint(
                jnp.squeeze(row, axis=0), out_sharding)

        return _pick(cached)

    # -- introspection -------------------------------------------------------
    def describe_placement(self, cached) -> Dict[int, List[int]]:
        """mesh position -> global block ids resident there (from the
        REAL sharding of the cached array, not the nominal plan)."""
        out: Dict[int, List[int]] = {}
        per_dev = cached.shape[0] // self.n_devices
        mesh_devs = list(self.mesh.devices.reshape(-1))
        for shard in cached.addressable_shards:
            pos = mesh_devs.index(shard.device)
            start = shard.index[0].start or 0
            out[pos] = list(range(start, start + per_dev))
        return out
