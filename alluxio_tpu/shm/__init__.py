"""Same-host zero-copy data plane: the SHM lease protocol.

The worker's MEM tier lives on ``/dev/shm`` (``atpu.worker.shm.dir``) —
a committed top-tier block file *is* a named shared-memory segment. This
package holds the protocol both sides of the zero-copy path speak:

- the **worker** (``worker/shm_store.py``) grants a co-located client a
  *lease* on a segment: ``shm_open`` returns the file path + a lease id,
  and pins the block in :class:`TieredBlockStore` so eviction cannot
  demote or unlink it while mapped. Leases are **TTL-bounded, not
  session-bound**: a SIGKILLed client's pins self-expire one TTL later
  (the crash-safe reclamation path — same shape as prefetch pins), while
  live clients renew lazily via ``shm_renew``.
- the **client** (``client/shm_transport.py``) mmaps the segment once
  and serves every subsequent read of the block as a ``memoryview``
  slice — no RPC, no serialization, no copy; ``np.frombuffer`` over the
  same pages feeds ``jax.device_put`` directly, so a same-host read
  costs exactly one host->device transfer.

Fallback contract: every failure in this plane (lease denied, segment
unavailable, worker restarted and forgot the lease, mmap error) is a
typed, *retryable-elsewhere* signal — the routing layer in
``client/remote_read.py`` / ``client/block_streams.py`` catches it and
transparently re-issues the read on the remote gRPC path. The SHM plane
can only ever make reads faster, never fail them.

Protocol summary (docs/small_reads.md has the full matrix):

======================  ================================================
RPC                     semantics
======================  ================================================
``shm_open``            grant lease: {lease_id, path, length, ttl_s};
                        raises ShmLeaseDeniedError (table full / fault)
                        or ShmSegmentUnavailableError (not cached in
                        the top tier)
``shm_renew``           extend lease TTL; {ok: False} for an unknown
                        lease (worker restarted) — client re-opens
``shm_release``         drop lease; last lease on a block unpins it
======================  ================================================
"""

from __future__ import annotations

from typing import NamedTuple

from alluxio_tpu.utils.exceptions import (
    AlluxioTpuError, register_wire_error,
)


@register_wire_error
class ShmLeaseDeniedError(AlluxioTpuError):
    """Worker declined to grant/keep an SHM lease (lease table at
    ``atpu.worker.shm.max.leases``, or an injected
    ``atpu.debug.fault.shm.lease.deny.rate`` fault). The client falls
    back to the remote read path; retry-later is implied, not required."""

    code = "RESOURCE_EXHAUSTED"


@register_wire_error
class ShmSegmentUnavailableError(AlluxioTpuError):
    """The block has no mappable top-tier segment on this worker (not
    cached, mid-eviction, or resident on a lower tier). Not an error for
    the read itself — the remote path serves it."""

    code = "NOT_FOUND"


class ShmLease(NamedTuple):
    """A granted lease, as the client tracks it."""

    lease_id: int
    block_id: int
    path: str
    length: int
    ttl_s: float
    #: monotonic deadline after which the worker may reclaim the pin
    expires_at: float
