"""Clairvoyant prefetch service: epoch-aware block scheduling into tiers.

With a seeded shuffle the exact per-epoch access order is known before
the first step runs (NoPFS, arxiv 2101.08734; Hoard, arxiv 1812.00669),
so the data plane can plan — not guess — which blocks must already be
resident in which tier when the consumer arrives:

- :mod:`~alluxio_tpu.prefetch.oracle` derives the exact future access
  sequence from (manifest, seed, epoch, cursor);
- :mod:`~alluxio_tpu.prefetch.scheduler` turns the lookahead window into
  tier-placement plans (HBM vs DRAM vs skip) under a byte budget, with
  deadline/lateness tracking and backpressure;
- :mod:`~alluxio_tpu.prefetch.agent` executes plans each heartbeat:
  async worker-tier loads + eviction pins, and HBM adoption through the
  consumer's :class:`~alluxio_tpu.client.jax_io.DeviceBlockLoader`;
- :mod:`~alluxio_tpu.prefetch.service` assembles the control loop from
  configuration and binds it to a loader.
"""

from alluxio_tpu.prefetch.oracle import (  # noqa: F401
    AccessOracle, BlockRef, DatasetManifest,
)
from alluxio_tpu.prefetch.scheduler import (  # noqa: F401
    PlacementAction, PrefetchScheduler, TIER_DRAM, TIER_HBM,
)
from alluxio_tpu.prefetch.agent import (  # noqa: F401
    JobServiceExecutor, PrefetchAgent, WorkerTierExecutor,
)
from alluxio_tpu.prefetch.service import PrefetchService  # noqa: F401
