"""Prefetch scheduler: lookahead window -> tier-placement plans.

Walks the oracle's exact future-access window in deadline order and
decides, per block, where it should be resident before the consumer
arrives: HBM (device tier, a ``hbm.fraction`` slice of the byte budget),
DRAM (worker tier), or skip (budget exhausted — backpressure). Issued
and ready-but-unconsumed bytes count against the budget, so the planner
can never run away from a slow consumer. Every consume is classified —
**hit** (resident before the read), **late** (planned and in flight, but
the consumer got there first), **miss** (never planned) — and late reads
record their block-ready stall so p50/p99 lateness is observable.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass
from typing import Dict, List, Optional

from alluxio_tpu.metrics import metrics
from alluxio_tpu.prefetch.oracle import AccessOracle, BlockRef

TIER_HBM = "HBM"
TIER_DRAM = "DRAM"

OUTCOME_HIT = "hit"
OUTCOME_LATE = "late"
OUTCOME_MISS = "miss"
#: consume from a superseded epoch generation: ignored by accounting
OUTCOME_STALE = "stale"


#: live schedulers in this process — the registry gauges below sum over
#: this set, so two services in one process both stay observable (a
#: per-instance closure would be silently overwritten by name)
_LIVE_SCHEDULERS: "weakref.WeakSet" = weakref.WeakSet()


def retune_budget(budget_bytes: "Optional[int]") -> int:
    """Remediation hook: apply a master-pushed byte budget to every
    live scheduler in this process (``None`` restores each scheduler's
    own configured budget).  Returns how many schedulers were retuned —
    the client's overlay application logs it.  The HBM/DRAM split
    ratio each scheduler was built with is preserved."""
    n = 0
    for s in list(_LIVE_SCHEDULERS):
        s.set_budget(budget_bytes)
        n += 1
    return n


def _register_gauges() -> None:
    """(Re-)register the process-wide prefetch gauges. Idempotent, and
    safe to call per scheduler: the registered functions read the live
    set, so re-registration after a metrics reset restores them."""
    m = metrics()
    m.register_gauge(
        "Client.PrefetchInflightBytes",
        lambda: float(sum(s.held_bytes(TIER_DRAM) + s.held_bytes(TIER_HBM)
                          for s in list(_LIVE_SCHEDULERS))))
    m.register_gauge(
        "Client.PrefetchReadyBlocks",
        lambda: float(sum(s.ready_count()
                          for s in list(_LIVE_SCHEDULERS))))


@dataclass
class PlacementAction:
    """One planned placement: make ``ref`` resident in ``tier`` before
    the consumer's cursor reaches global sequence ``deadline_seq``."""

    ref: BlockRef
    tier: str
    deadline_seq: int


class PrefetchScheduler:
    """Budgeted placement planning + outcome accounting for one consumer.

    Thread-safe: the agent heartbeat calls :meth:`plan` /
    :meth:`on_loaded` while the loader's producer thread calls
    :meth:`on_consume` / :meth:`advance`.
    """

    def __init__(self, oracle: AccessOracle, *, lookahead_blocks: int,
                 budget_bytes: int, hbm_fraction: float = 0.0,
                 retry_backoff_s: float = 0.5) -> None:
        if not 0.0 <= hbm_fraction <= 1.0:
            raise ValueError(f"hbm_fraction {hbm_fraction} not in [0, 1]")
        self._oracle = oracle
        self._lookahead = max(1, lookahead_blocks)
        self._budget = max(0, budget_bytes)
        self._hbm_budget = int(self._budget * hbm_fraction)
        #: what the service configured, kept so a withdrawn remediation
        #: overlay can restore it (set_budget(None))
        self._configured_budget = self._budget
        self._hbm_fraction = hbm_fraction
        self._retry_backoff_s = retry_backoff_s
        self._lock = threading.Lock()
        # consumer cursor (epoch, position within the host's sequence)
        self._epoch = 0
        self._pos = 0
        self._generation = 0
        #: issued, load not yet observed complete
        self._inflight: Dict[int, PlacementAction] = {}
        #: load complete, not yet consumed
        self._ready: Dict[int, PlacementAction] = {}
        #: bytes held against the budget per tier class
        self._held = {TIER_HBM: 0, TIER_DRAM: 0}
        #: failure cooldowns: block_id -> (consecutive fails, earliest
        #: replan time) — without this a permanently-failing placement
        #: (HBM store too small, worker down) is replanned every tick,
        #: a hot loop of full host reads / RPCs for zero placements
        self._retry: Dict[int, tuple] = {}
        # instance-local tallies: the registry counters below are
        # process-global (shared by name across schedulers, matching
        # the repo's metrics convention), so stats()/hit_rate must not
        # read them back — two services in one process would report
        # each other's outcomes
        self._n = {"hits": 0, "late": 0, "misses": 0,
                   "late_arrivals": 0}
        m = metrics()
        self._hits = m.counter("Client.PrefetchHits")
        self._late = m.counter("Client.PrefetchLate")
        self._miss = m.counter("Client.PrefetchMisses")
        self._late_arrivals = m.counter("Client.PrefetchLateArrivals")
        self._ready_timer = m.timer("Client.PrefetchBlockReady")
        # weak registration: the registry has no deregistration, so a
        # strong reference would leak every scheduler (and its
        # oracle+manifest) for process lifetime
        _LIVE_SCHEDULERS.add(self)
        _register_gauges()

    # -- retuning -----------------------------------------------------------
    def set_budget(self, budget_bytes: "Optional[int]") -> None:
        """Live byte-budget retune (remediation overlay; ``None``
        restores the configured value).  Held bytes are untouched — a
        shrink simply stops admitting new placements until consumes
        drain below the new ceiling."""
        with self._lock:
            self._budget = self._configured_budget \
                if budget_bytes is None else max(0, int(budget_bytes))
            self._hbm_budget = int(self._budget * self._hbm_fraction)

    # -- cursor -------------------------------------------------------------
    def begin_epoch(self, epoch: int) -> int:
        """Consumer starts (or restarts) an epoch; cursor rewinds to its
        head. Residency state survives — re-reads of still-resident
        blocks are the hits the HBM/DRAM tiers exist to serve. Returns
        a generation token: a superseded epoch's producer may still be
        mid-consume when a new epoch rewinds the cursor, and its last
        ``on_consume`` must not advance the NEW epoch's cursor — stale
        tokens are fenced off."""
        with self._lock:
            self._epoch = int(epoch)
            self._pos = 0
            self._generation += 1
            return self._generation

    def cursor(self) -> "tuple[int, int]":
        with self._lock:
            return self._epoch, self._pos

    # -- planning -----------------------------------------------------------
    def plan(self) -> List[PlacementAction]:
        """Next placements in deadline order, newest-deadline last, under
        the byte budget. Empty when the window is fully planned or the
        budget is saturated (backpressure)."""
        out: List[PlacementAction] = []
        now = time.monotonic()
        with self._lock:
            window = self._oracle.window(self._epoch, self._pos,
                                         self._lookahead)
            seen = set()
            for seq, ref in window:
                bid = ref.block_id
                if bid in seen or bid in self._inflight or \
                        bid in self._ready:
                    continue
                seen.add(bid)
                retry = self._retry.get(bid)
                if retry is not None and now < retry[1]:
                    continue  # failure cooldown: skip, plan the rest
                tier = self._admit(ref)
                if tier is None:
                    break  # budget saturated: nearer deadlines first
                action = PlacementAction(ref=ref, tier=tier,
                                         deadline_seq=seq)
                self._inflight[bid] = action
                self._held[tier] += ref.length
                out.append(action)
        return out

    def _admit(self, ref: BlockRef) -> Optional[str]:
        """Tier for ``ref`` under the split budget: HBM while its slice
        has room, then DRAM, else nothing (caller stops planning)."""
        if self._held[TIER_HBM] + ref.length <= self._hbm_budget:
            return TIER_HBM
        dram_budget = self._budget - self._hbm_budget
        if self._held[TIER_DRAM] + ref.length <= dram_budget:
            return TIER_DRAM
        return None

    # -- agent callbacks ----------------------------------------------------
    def on_loaded(self, block_id: int) -> None:
        """The agent observed the placement complete (block resident)."""
        with self._lock:
            self._retry.pop(block_id, None)
            action = self._inflight.pop(block_id, None)
            if action is None:
                return
            self._ready[block_id] = action
            if self._oracle.global_seq(self._epoch, self._pos) > \
                    action.deadline_seq:
                # landed after its deadline passed: the consume already
                # went through as late/miss, but keep the arrival visible
                self._n["late_arrivals"] += 1
                self._late_arrivals.inc()

    def on_load_failed(self, block_id: int) -> None:
        """Placement failed (worker died, UFS error, HBM store full):
        release the budget and back off exponentially before replanning
        the block — a permanent failure must not become a hot loop."""
        with self._lock:
            action = self._inflight.pop(block_id, None)
            if action is not None:
                self._held[action.tier] -= action.ref.length
            fails = self._retry.get(block_id, (0, 0.0))[0] + 1
            backoff = min(30.0,
                          self._retry_backoff_s * (2 ** (fails - 1)))
            self._retry[block_id] = (fails,
                                     time.monotonic() + backoff)

    def on_evicted(self, block_id: int) -> None:
        """Residency lost before consumption (pin raced an explicit
        free): the block is no longer a guaranteed hit."""
        with self._lock:
            action = self._ready.pop(block_id, None)
            if action is not None:
                self._held[action.tier] -= action.ref.length

    # -- consumer callbacks -------------------------------------------------
    def on_consume(self, ref: BlockRef, *,
                   resident_hint: bool = False,
                   generation: Optional[int] = None) -> str:
        """Classify one consume and advance the cursor. The placement's
        budget hold is released; DRAM pins are the agent's to drop (it
        learns via the returned outcome path in the service). A consume
        carrying a superseded generation token is ignored (OUTCOME_STALE)
        — no cursor movement, no counters."""
        with self._lock:
            if generation is not None and \
                    generation != self._generation:
                return OUTCOME_STALE
            bid = ref.block_id
            action = self._ready.pop(bid, None)
            if action is not None:
                self._held[action.tier] -= action.ref.length
                outcome = OUTCOME_HIT
            elif resident_hint:
                # resident through a path the scheduler didn't drive
                # (e.g. HBM retention from a previous epoch)
                outcome = OUTCOME_HIT
            elif bid in self._inflight:
                outcome = OUTCOME_LATE
                # leave the in-flight hold: on_loaded will move it to
                # ready and a later epoch can still hit it
            else:
                outcome = OUTCOME_MISS
            self._pos += 1
            if self._pos >= self._oracle.epoch_len():
                self._epoch, self._pos = self._epoch + 1, 0
            key = {OUTCOME_HIT: "hits", OUTCOME_LATE: "late",
                   OUTCOME_MISS: "misses"}[outcome]
            self._n[key] += 1
        if outcome == OUTCOME_HIT:
            self._hits.inc()
            self._ready_timer.update(0.0)
        elif outcome == OUTCOME_LATE:
            self._late.inc()
        else:
            self._miss.inc()
        return outcome

    def record_stall(self, seconds: float) -> None:
        """Block-ready stall of a late/miss consume (how long the
        consumer waited for data that should already have been there)."""
        self._ready_timer.update(max(0.0, seconds))

    # -- introspection ------------------------------------------------------
    def held_bytes(self, tier: str) -> int:
        with self._lock:
            return self._held[tier]

    def is_ready(self, block_id: int) -> bool:
        with self._lock:
            return block_id in self._ready

    def ready_count(self) -> int:
        with self._lock:
            return len(self._ready)

    def inflight_count(self) -> int:
        with self._lock:
            return len(self._inflight)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            held = dict(self._held)
            ready, inflight = len(self._ready), len(self._inflight)
            epoch, pos = self._epoch, self._pos
            n = dict(self._n)
        total = n["hits"] + n["late"] + n["misses"]
        return {
            "epoch": epoch, "pos": pos,
            "ready_blocks": ready, "inflight_blocks": inflight,
            "held_hbm_bytes": held[TIER_HBM],
            "held_dram_bytes": held[TIER_DRAM],
            "hits": n["hits"], "late": n["late"],
            "misses": n["misses"],
            "late_arrivals": n["late_arrivals"],
            "hit_rate": (n["hits"] / total) if total else 0.0,
        }
