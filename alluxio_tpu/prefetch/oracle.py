"""Access oracle: the exact future block-access order from a seeded shuffle.

Clairvoyance, not prediction (NoPFS, arxiv 2101.08734): a training run
that shuffles with a known seed visits blocks in a sequence that is a
pure function of ``(manifest, seed, epoch)``. The oracle materializes
that sequence per host shard and answers "what are the next *k*
accesses after cursor position *p*" — including across the epoch
boundary, so the tail of epoch *e* already prefetches the head of
epoch *e+1*.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Iterator, List, Sequence, Tuple

import numpy as np

#: epoch sequences kept hot: the live epoch plus a lookahead window
#: several epochs deep (planner) plus the previous epoch (stragglers)
_EPOCH_CACHE_SIZE = 12


@dataclass(frozen=True)
class BlockRef:
    """One block of the dataset, with everything an executor needs to
    make it resident (UFS source for cold loads, identity for pins)."""

    path: str
    block_index: int
    block_id: int
    length: int
    offset: int = 0
    file_id: int = 0
    ufs_path: str = ""
    mount_id: int = 0
    persisted: bool = False


@dataclass(frozen=True)
class DatasetManifest:
    """Immutable block-level view of the dataset, in file order."""

    blocks: Tuple[BlockRef, ...] = field(default_factory=tuple)
    #: the resolved (path, FileInfo) pairs behind ``blocks`` — kept so
    #: a consumer wiring a loader to the same paths reuses them
    #: instead of paying a second get_status round per file
    file_infos: Tuple = field(default_factory=tuple)

    @classmethod
    def from_fs(cls, fs, paths: Sequence[str]) -> "DatasetManifest":
        """Resolve paths through the metadata master into block refs
        (block ids, per-block lengths, and the UFS coordinates the
        async-cache path needs for cold loads)."""
        blocks: List[BlockRef] = []
        file_infos: List[tuple] = []
        for path in paths:
            info = fs.get_status(path)
            file_infos.append((str(path), info))
            fbis = fs.fs_master.get_file_block_info_list(info.path)
            for i, fbi in enumerate(fbis):
                blocks.append(BlockRef(
                    path=info.path, block_index=i,
                    block_id=fbi.block_info.block_id,
                    length=fbi.block_info.length,
                    offset=fbi.offset, file_id=info.file_id,
                    ufs_path=info.ufs_path, mount_id=info.mount_id,
                    persisted=info.persisted))
        return cls(blocks=tuple(blocks), file_infos=tuple(file_infos))

    @property
    def total_bytes(self) -> int:
        return sum(b.length for b in self.blocks)

    def __len__(self) -> int:
        return len(self.blocks)

    def __iter__(self) -> Iterator[BlockRef]:
        return iter(self.blocks)


class AccessOracle:
    """Per-host exact access sequences for every epoch.

    The permutation for epoch *e* is drawn from
    ``np.random.SeedSequence([seed, e])`` — independent of process,
    cursor state, or call order, so every host (and the scheduler, and a
    re-started agent) derives the identical sequence. Hosts consume
    disjoint strided shards of the global permutation (host *h* of *H*
    takes positions ``h, h+H, h+2H, ...``), mirroring per-host sharded
    loading.
    """

    def __init__(self, manifest: DatasetManifest, seed: int, *,
                 num_hosts: int = 1, host_index: int = 0) -> None:
        if not 0 <= host_index < num_hosts:
            raise ValueError(
                f"host_index {host_index} out of range for {num_hosts} hosts")
        self.manifest = manifest
        self.seed = int(seed)
        self.num_hosts = num_hosts
        self.host_index = host_index
        self._lock = threading.Lock()
        #: LRU of generated epoch sequences — keyed on USE, not on the
        #: epoch being generated: the planner's window walks several
        #: epochs ahead of the consumer each tick, and a relative
        #: eviction rule would thrash (regenerate O(n) permutations
        #: every tick, inside the scheduler's lock)
        self._cache: "OrderedDict[int, List[BlockRef]]" = OrderedDict()

    # -- sequences ----------------------------------------------------------
    def epoch_sequence(self, epoch: int) -> List[BlockRef]:
        """This host's exact access order for ``epoch`` (stable across
        calls and processes)."""
        with self._lock:
            seq = self._cache.get(epoch)
            if seq is not None:
                self._cache.move_to_end(epoch)
                return seq
            rng = np.random.default_rng(
                np.random.SeedSequence([self.seed, int(epoch)]))
            perm = rng.permutation(len(self.manifest.blocks))
            seq = [self.manifest.blocks[i]
                   for i in perm[self.host_index::self.num_hosts]]
            self._cache[epoch] = seq
            while len(self._cache) > _EPOCH_CACHE_SIZE:
                self._cache.popitem(last=False)
            return seq

    def epoch_len(self) -> int:
        """Accesses this host makes per epoch."""
        n, h = len(self.manifest.blocks), self.num_hosts
        return (n - self.host_index + h - 1) // h

    def global_seq(self, epoch: int, pos: int) -> int:
        """Monotone global sequence number of access ``pos`` in ``epoch``
        (the deadline currency the scheduler tracks lateness in)."""
        return epoch * self.epoch_len() + pos

    def window(self, epoch: int, pos: int,
               k: int) -> List[Tuple[int, BlockRef]]:
        """The next ``k`` accesses at-or-after ``(epoch, pos)`` as
        ``(global_seq, ref)`` pairs, continuing into subsequent epochs —
        the clairvoyant lookahead the scheduler plans from."""
        out: List[Tuple[int, BlockRef]] = []
        per_epoch = self.epoch_len()
        if per_epoch == 0:
            return out
        e, p = epoch, pos
        while len(out) < k:
            seq = self.epoch_sequence(e)
            while p < len(seq) and len(out) < k:
                out.append((self.global_seq(e, p), seq[p]))
                p += 1
            e, p = e + 1, 0
        return out
