"""Prefetch agent: heartbeat loop that executes placement plans.

Each tick: observe completions first (frees budget), then pull the next
plan from the scheduler and issue it — DRAM placements as async
worker-tier loads (the job service's load path: ``async_cache`` into the
co-located worker, reference ``job/plans/load.py``) followed by an
eviction pin so the annotator cannot drop the block before its consume;
HBM placements through the consumer loader's adopt hook. All work is
non-blocking: a tick never waits on a transfer.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from alluxio_tpu.heartbeat import HeartbeatExecutor
from alluxio_tpu.metrics import metrics
from alluxio_tpu.prefetch.oracle import BlockRef
from alluxio_tpu.prefetch.scheduler import (
    PlacementAction, PrefetchScheduler, TIER_HBM,
)
from alluxio_tpu.utils.tracing import annotate

LOG = logging.getLogger(__name__)


class WorkerTierExecutor:
    """Makes blocks resident in a worker's DRAM/MEM tier and pins them.

    Issues ``async_cache`` (the same worker path DistributedLoad rides)
    against a target worker chosen local-first, then polls the block
    master for the commit to land; once resident, takes a prefetch pin
    so eviction pressure cannot undo the placement before the consume.
    """

    def __init__(self, block_master, worker_client_fn: Callable,
                 *, local_host: str = "",
                 load_timeout_s: float = 60.0) -> None:
        self._bm = block_master
        self._client_fn = worker_client_fn
        self._local_host = local_host
        self._load_timeout_s = load_timeout_s
        self._lock = threading.Lock()
        #: block_id -> (ref, issue time) awaiting commit
        self._pending: Dict[int, tuple] = {}
        #: block_id -> (worker address, refcount). REFCOUNTED: the
        #: cross-epoch lookahead can re-pin a block for epoch e+1 while
        #: epoch e's consume is between classify and release; a plain
        #: slot would let that release destroy the new placement's pin
        self._pinned: Dict[int, tuple] = {}
        #: placements that completed synchronously (already resident);
        #: drained by the next poll() so the scheduler learns of them
        self._completed: List[int] = []
        self._m = metrics()

    def _pick_worker(self):
        infos = self._bm.get_worker_infos()
        if not infos:
            return None
        for w in infos:
            if self._local_host and \
                    w.address.tiered_identity.value("host") == \
                    self._local_host:
                return w.address
        return infos[0].address

    def _resident_or_source(self, ref: BlockRef) -> str:
        """Shared submit preamble: ``"done"`` (already resident, pinned
        and queued for poll), ``"cold"`` (has a UFS source to load
        from), or ``"unavailable"`` (cannot be placed right now)."""
        try:
            info = self._bm.get_block_info(ref.block_id)
        except Exception:  # noqa: BLE001 master transition
            return "unavailable"
        if info.locations and self._pin(ref.block_id,
                                        info.locations[0].address):
            # already resident: complete synchronously, surface via poll
            with self._lock:
                self._completed.append(ref.block_id)
            return "done"
        if not (ref.persisted and ref.ufs_path):
            return "unavailable"  # no UFS source to load from
        return "cold"

    def submit(self, ref: BlockRef) -> bool:
        """Start one placement; returns False when it cannot even be
        issued (no worker / no cold source and not cached anywhere)."""
        state = self._resident_or_source(ref)
        if state != "cold":
            return state == "done"
        addr = self._pick_worker()
        if addr is None:
            return False
        try:
            # tagged PREFETCH: with worker QoS on, speculative loads
            # drain after on-demand reads and client-issued fills,
            # and an on-demand reader arriving first promotes them
            self._client_fn(addr).async_cache(
                ref.block_id, ref.ufs_path, ref.offset, ref.length,
                ref.mount_id, qos_class="PREFETCH")
        except Exception:  # noqa: BLE001 worker transition: report failed
            LOG.debug("async_cache submit failed for block %d",
                      ref.block_id, exc_info=True)
            return False
        with self._lock:
            self._pending[ref.block_id] = (ref, time.monotonic())
        self._m.counter("Client.PrefetchLoadsIssued").inc()
        return True

    def _pin(self, block_id: int, addr) -> bool:
        try:
            if not self._client_fn(addr).prefetch_pin(block_id):
                return False
        except Exception:  # noqa: BLE001
            LOG.debug("prefetch pin failed for block %d", block_id,
                      exc_info=True)
            return False
        with self._lock:
            prev = self._pinned.get(block_id)
            # the worker-side pin is one TTL slot (a re-pin refreshes
            # it); the refcount is client-side bookkeeping only
            self._pinned[block_id] = (addr, prev[1] + 1 if prev else 1)
        self._m.counter("Client.PrefetchBlocksPinned").inc()
        return True

    def poll(self) -> "Tuple[List[int], List[int]]":
        """``(done, failed)`` block ids since the last poll. A block is
        done only once it is BOTH committed and pinned — reporting an
        unpinned block ready would let eviction turn a guaranteed hit
        into a cold read the accounting still calls a hit. Pin failures
        retry next tick; a load that never lands within the timeout is
        failed (the scheduler releases its budget and backs off)."""
        now = time.monotonic()
        with self._lock:
            pending = list(self._pending.items())
            done: List[int] = self._completed
            self._completed = []
        failed: List[int] = []
        if not pending:
            return done, failed
        # ONE batched master RPC per tick: per-block get_block_info
        # would put lookahead-many sequential RPCs on every heartbeat
        # of every training host
        try:
            infos = {i.block_id: i for i in self._bm.get_block_infos(
                [bid for bid, _ in pending])}
        except Exception:  # noqa: BLE001 master transition
            infos = {}
        for bid, (_ref, issued_at) in pending:
            # the timeout covers the WHOLE placement — commit AND pin.
            # A perpetually-failing pin (stale master location for a
            # restarted worker) or an unreachable master must also
            # fail out, or the block holds scheduler budget forever
            # and prefetch silently stops once such blocks accumulate
            info = infos.get(bid)
            if info is not None and info.locations and \
                    self._pin(bid, info.locations[0].address):
                with self._lock:
                    self._pending.pop(bid, None)
                done.append(bid)
            elif now - issued_at > self._load_timeout_s:
                with self._lock:
                    self._pending.pop(bid, None)
                failed.append(bid)
            # else: retry next tick
        return done, failed

    def unpin(self, block_id: int) -> None:
        """Drop one hold on the eviction pin; the worker-side pin goes
        only when the last hold does (no-op if not held)."""
        with self._lock:
            entry = self._pinned.get(block_id)
            if entry is None:
                return
            addr, count = entry
            if count > 1:
                self._pinned[block_id] = (addr, count - 1)
                return
            del self._pinned[block_id]
        try:
            self._client_fn(addr).prefetch_unpin(block_id)
        except Exception:  # noqa: BLE001 worker gone: pin died with it
            LOG.debug("prefetch unpin failed for block %d", block_id,
                      exc_info=True)

    def pinned_blocks(self) -> List[int]:
        with self._lock:
            return list(self._pinned)

    def close(self) -> None:
        # force-release regardless of refcount: nothing consumes after
        # close, and the TTL would otherwise hold the blocks for minutes
        with self._lock:
            pinned = dict(self._pinned)
            self._pinned.clear()
        for bid, (addr, _count) in pinned.items():
            try:
                self._client_fn(addr).prefetch_unpin(bid)
            except Exception:  # noqa: BLE001
                LOG.debug("prefetch unpin failed for block %d", bid,
                          exc_info=True)


class JobServiceExecutor(WorkerTierExecutor):
    """DRAM placements through the job service instead of direct worker
    RPCs: one DistributedLoad plan (``job/plans/load.py``) per distinct
    file path, fanned out by the job master to workers co-located with
    the data. Block readiness and pinning stay per-block via the block
    master — the plan is the transport, not the accounting. Coarser
    than ``async_cache`` (a load plan caches the whole file), which is
    the right trade once files span many blocks across many workers.
    """

    def __init__(self, block_master, worker_client_fn, job_client, *,
                 local_host: str = "") -> None:
        super().__init__(block_master, worker_client_fn,
                         local_host=local_host)
        self._job = job_client
        #: path -> running load job id (one plan covers every block of
        #: the path; finished jobs are dropped so a later eviction can
        #: trigger a fresh plan)
        self._jobs: Dict[str, int] = {}

    def submit(self, ref: BlockRef) -> bool:
        state = self._resident_or_source(ref)
        if state != "cold":
            return state == "done"
        with self._lock:
            job_id = self._jobs.get(ref.path)
        if job_id is None:
            try:
                job_id = self._job.run({"type": "load", "path": ref.path,
                                        "replication": 1})
            except Exception:  # noqa: BLE001 job master transition
                LOG.debug("load job submit failed for %s", ref.path,
                          exc_info=True)
                return False
            with self._lock:
                self._jobs[ref.path] = job_id
            self._m.counter("Client.PrefetchLoadJobs").inc()
        with self._lock:
            self._pending[ref.block_id] = (ref, time.monotonic())
        return True

    def poll(self) -> "Tuple[List[int], List[int]]":
        done, failed = super().poll()
        with self._lock:
            jobs = list(self._jobs.items())
        for path, jid in jobs:
            try:
                status = self._job.get_status(jid).status
            except Exception:  # noqa: BLE001
                continue
            if status in ("COMPLETED", "FAILED", "CANCELED"):
                with self._lock:
                    self._jobs.pop(path, None)
        return done, failed


class PrefetchAgent(HeartbeatExecutor):
    """One control-loop tick: completions -> plan -> issue.

    ``hbm_adopt`` (when bound) is the loader's hook that host-reads a
    block and adopts it into the HBM page store. The host read can be a
    cold UFS read-through (seconds), so adopts run on a dedicated
    worker thread — the heartbeat tick itself never waits on a
    transfer, DRAM issues and completion polling keep flowing while an
    adopt is in flight. Without the hook, HBM placements degrade to
    DRAM placements (still a tier hit, one H2D away).
    """

    def __init__(self, scheduler: PrefetchScheduler,
                 executor: WorkerTierExecutor,
                 hbm_adopt: Optional[Callable[[BlockRef], bool]] = None
                 ) -> None:
        self._scheduler = scheduler
        self._executor = executor
        self._hbm_adopt = hbm_adopt
        self._hbm_pool = None
        self._m = metrics()

    def bind_hbm(self, fn: Optional[Callable[[BlockRef], bool]]) -> None:
        self._hbm_adopt = fn

    def heartbeat(self) -> None:
        with annotate("atpu.prefetch.tick"):
            done, failed = self._executor.poll()
            for bid in done:
                self._scheduler.on_loaded(bid)
            for bid in failed:
                self._scheduler.on_load_failed(bid)
            for action in self._scheduler.plan():
                self._issue(action)

    def _issue(self, action: PlacementAction) -> None:
        ref = action.ref
        with annotate("atpu.prefetch.place"):
            if action.tier == TIER_HBM and self._hbm_adopt is not None:
                if self._hbm_pool is None:
                    from concurrent.futures import ThreadPoolExecutor

                    self._hbm_pool = ThreadPoolExecutor(
                        max_workers=1,
                        thread_name_prefix="prefetch-hbm-adopt")
                self._hbm_pool.submit(self._adopt, ref)
                return
            if not self._executor.submit(ref):
                self._scheduler.on_load_failed(ref.block_id)

    def _adopt(self, ref: BlockRef) -> None:
        """HBM placement body (adopt worker thread): blocking host read
        + async device_put + page-store adopt, then the scheduler
        callback either way."""
        with annotate("atpu.prefetch.hbm_adopt"):
            adopt = self._hbm_adopt
            try:
                ok = adopt is not None and adopt(ref)
            except Exception:  # noqa: BLE001 loader closed mid-adopt
                LOG.debug("hbm adopt failed for block %d", ref.block_id,
                          exc_info=True)
                ok = False
        if ok:
            self._m.counter("Client.PrefetchHbmAdopted").inc()
            self._scheduler.on_loaded(ref.block_id)
        else:
            self._scheduler.on_load_failed(ref.block_id)

    def unpin(self, block_id: int) -> None:
        self._executor.unpin(block_id)

    def close(self) -> None:
        if self._hbm_pool is not None:
            # don't run queued adopts at shutdown; the in-flight one
            # finishes (its loader hook checks closed-ness itself)
            try:
                self._hbm_pool.shutdown(wait=True, cancel_futures=True)
            except TypeError:  # python < 3.9
                self._hbm_pool.shutdown(wait=True)
            self._hbm_pool = None
        self._executor.close()
