"""Prefetch service facade: oracle + scheduler + agent as one control loop.

Built from configuration (``atpu.prefetch.*`` keys), bound to a
:class:`~alluxio_tpu.client.jax_io.DeviceBlockLoader` consumer, and
driven either by its own heartbeat thread (production) or by explicit
:meth:`tick` calls (tests, via the scheduled-timer harness).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.heartbeat import HeartbeatContext, HeartbeatThread
from alluxio_tpu.prefetch.agent import PrefetchAgent, WorkerTierExecutor
from alluxio_tpu.prefetch.oracle import (
    AccessOracle, BlockRef, DatasetManifest,
)
from alluxio_tpu.prefetch.scheduler import (
    OUTCOME_HIT, PrefetchScheduler,
)


class PrefetchService:
    """Owns the clairvoyant control loop for one consumer's dataset."""

    def __init__(self, oracle: AccessOracle, scheduler: PrefetchScheduler,
                 agent: PrefetchAgent, *,
                 heartbeat_interval_s: float = 0.1) -> None:
        self.oracle = oracle
        self.scheduler = scheduler
        self.agent = agent
        self._interval = heartbeat_interval_s
        self._thread: Optional[HeartbeatThread] = None
        self._lock = threading.Lock()
        self._closed = False

    # -- construction -------------------------------------------------------
    @classmethod
    def from_conf(cls, conf: Configuration, fs, paths: Sequence[str], *,
                  seed: int, num_hosts: int = 1, host_index: int = 0,
                  local_host: str = "", job_client=None,
                  worker_client_fn: Optional[Callable] = None
                  ) -> Optional["PrefetchService"]:
        """Assemble from ``atpu.prefetch.*`` keys; None when disabled —
        callers pass that straight to the loader, whose behavior is then
        byte-identical to a loader that never heard of prefetching.
        With ``job_client``, DRAM placements ride DistributedLoad plans
        through the job service instead of direct worker RPCs."""
        if not conf.get_bool(Keys.PREFETCH_ENABLED):
            return None
        manifest = DatasetManifest.from_fs(fs, paths)
        oracle = AccessOracle(manifest, seed, num_hosts=num_hosts,
                              host_index=host_index)
        scheduler = PrefetchScheduler(
            oracle,
            lookahead_blocks=conf.get_int(Keys.PREFETCH_LOOKAHEAD_BLOCKS),
            budget_bytes=conf.get_bytes(Keys.PREFETCH_BUDGET_BYTES),
            hbm_fraction=conf.get_float(Keys.PREFETCH_HBM_FRACTION))
        if worker_client_fn is None:
            # the FileSystem's data-plane cache: keyed on the same
            # data_port-or-rpc_port every other worker RPC uses
            worker_client_fn = fs.store.worker_client
        if job_client is not None:
            from alluxio_tpu.prefetch.agent import JobServiceExecutor

            executor = JobServiceExecutor(fs.block_master,
                                          worker_client_fn, job_client,
                                          local_host=local_host)
        else:
            executor = WorkerTierExecutor(fs.block_master,
                                          worker_client_fn,
                                          local_host=local_host)
        agent = PrefetchAgent(scheduler, executor)
        return cls(oracle, scheduler, agent,
                   heartbeat_interval_s=conf.get_duration_s(
                       Keys.PREFETCH_HEARTBEAT_INTERVAL))

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "PrefetchService":
        """Start the heartbeat-driven agent loop."""
        with self._lock:
            if self._closed:
                raise RuntimeError("prefetch service is closed")
            if self._thread is None:
                self._thread = HeartbeatThread(
                    HeartbeatContext.CLIENT_PREFETCH_AGENT, self.agent,
                    self._interval)
                self._thread.start()
        return self

    def tick(self) -> None:
        """One agent tick, synchronously (deterministic test driving)."""
        self.agent.heartbeat()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.stop()  # HeartbeatThread closes the agent (and pins)
        else:
            self.agent.close()

    def __enter__(self) -> "PrefetchService":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- consumer (loader) integration --------------------------------------
    def epoch_sequence(self, epoch: int) -> List[BlockRef]:
        return self.oracle.epoch_sequence(epoch)

    def begin_epoch(self, epoch: int) -> int:
        """Rewind the cursor; returns the generation token the epoch's
        consumes must carry (stale-producer fencing)."""
        return self.scheduler.begin_epoch(epoch)

    def bind_hbm(self, adopt_fn: Optional[Callable[[BlockRef], bool]]
                 ) -> None:
        """Bind (or unbind) the loader's HBM adopt hook."""
        self.agent.bind_hbm(adopt_fn)

    def on_consume(self, ref: BlockRef, *, resident_hint: bool = False,
                   generation: Optional[int] = None) -> str:
        """Classify a consume and move the cursor. Does NOT drop the
        eviction pin — the consumer calls :meth:`release` once its read
        holds the block's own lock, so eviction cannot slip into the
        unpin->open window."""
        return self.scheduler.on_consume(ref, resident_hint=resident_hint,
                                         generation=generation)

    def release(self, ref: BlockRef) -> None:
        """Consume finished: drop the block's eviction pin (no-op when
        none is held)."""
        self.agent.unpin(ref.block_id)

    def invalidate(self, block_id: int) -> None:
        """Residency lost outside the control loop (an explicit free, a
        worker death, an out-of-band remove): drop the ready state and
        any pin so the next window replans the block instead of
        mis-classifying its consume as a hit. Wire this to store/worker
        eviction listeners when the deployment has them."""
        self.scheduler.on_evicted(block_id)
        self.agent.unpin(block_id)

    def record_stall(self, seconds: float) -> None:
        self.scheduler.record_stall(seconds)

    # -- introspection ------------------------------------------------------
    def wait_ready(self, min_blocks: int, *, timeout_s: float = 30.0,
                   tick: bool = False) -> bool:
        """Wait until at least ``min_blocks`` placements are resident
        (optionally self-driving ticks when no heartbeat thread runs) —
        the warm-up gate before a measured run."""
        deadline = time.monotonic() + timeout_s
        while self.scheduler.ready_count() < min_blocks:
            if time.monotonic() > deadline:
                return False
            if tick:
                self.tick()
            time.sleep(0.005)
        return True

    def stats(self) -> Dict[str, float]:
        return self.scheduler.stats()


__all__ = ["PrefetchService", "OUTCOME_HIT"]
