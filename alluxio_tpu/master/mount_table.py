"""Mount table: namespace path <-> UFS path mapping.

Re-design of ``core/server/master/.../file/meta/MountTable.java:66`` (resolve
``:358``): nested mounts, read-only/shared flags, reverse resolution, and
per-mount options. State is journaled by the FileSystemMaster.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from alluxio_tpu.utils.exceptions import (
    AlreadyExistsError, InvalidPathError, NotFoundError,
)
from alluxio_tpu.utils.uri import SEPARATOR, AlluxioURI

ROOT = "/"


@dataclass
class MountInfo:
    mount_id: int
    alluxio_path: str
    ufs_uri: str
    read_only: bool = False
    shared: bool = False
    properties: Dict[str, str] = field(default_factory=dict)

    def to_wire(self) -> dict:
        return {"mount_id": self.mount_id, "alluxio_path": self.alluxio_path,
                "ufs_uri": self.ufs_uri, "read_only": self.read_only,
                "shared": self.shared, "properties": dict(self.properties)}

    @staticmethod
    def from_wire(d: dict) -> "MountInfo":
        return MountInfo(**d)


@dataclass
class Resolution:
    """Result of mapping a namespace path to its UFS location."""

    mount_info: MountInfo
    ufs_path: str  # full UFS uri string for this path

    @property
    def mount_id(self) -> int:
        return self.mount_info.mount_id


class MountTable:
    def __init__(self) -> None:
        self._mounts: Dict[str, MountInfo] = {}
        self._lock = threading.RLock()

    # -- mutation (called under journal application) ------------------------
    def add(self, info: MountInfo) -> None:
        path = AlluxioURI(info.alluxio_path).path
        with self._lock:
            if path in self._mounts:
                raise AlreadyExistsError(f"mount point {path} already exists")
            for existing in self._mounts.values():
                e_ufs = existing.ufs_uri.rstrip(SEPARATOR)
                n_ufs = info.ufs_uri.rstrip(SEPARATOR)
                if not existing.shared and not info.shared and (
                        e_ufs == n_ufs
                        or e_ufs.startswith(n_ufs + SEPARATOR)
                        or n_ufs.startswith(e_ufs + SEPARATOR)):
                    raise InvalidPathError(
                        f"UFS path {info.ufs_uri} overlaps existing mount "
                        f"{existing.ufs_uri}")
            self._mounts[path] = MountInfo(
                info.mount_id, path, info.ufs_uri, info.read_only,
                info.shared, dict(info.properties))

    def delete(self, alluxio_path: str) -> MountInfo:
        path = AlluxioURI(alluxio_path).path
        with self._lock:
            if path == ROOT:
                raise InvalidPathError("cannot unmount root")
            info = self._mounts.pop(path, None)
            if info is None:
                raise NotFoundError(f"no mount point at {path}")
            return info

    # -- queries ------------------------------------------------------------
    def get_mount_point(self, uri: AlluxioURI) -> Optional[str]:
        """Longest mount-point prefix covering ``uri``."""
        path = uri.path
        with self._lock:
            best: Optional[str] = None
            for mp in self._mounts:
                if AlluxioURI(mp).is_ancestor_of(uri):
                    if best is None or len(mp) > len(best):
                        best = mp
            return best

    def is_mount_point(self, uri: AlluxioURI) -> bool:
        with self._lock:
            return uri.path in self._mounts

    def is_mount_path(self, path: str) -> bool:
        """``is_mount_point`` for a plain path string (hot listing loop:
        no AlluxioURI construction per child)."""
        with self._lock:
            return path in self._mounts

    def contains_mount_below(self, uri: AlluxioURI) -> bool:
        """True if any mount point (other than at uri) is nested under uri."""
        with self._lock:
            for mp in self._mounts:
                if mp != uri.path and uri.is_ancestor_of(AlluxioURI(mp)):
                    return True
            return False

    def resolve(self, uri: AlluxioURI) -> Resolution:
        """Map a namespace path to (mount, full UFS path)
        (reference: ``MountTable.java:358``)."""
        mp = self.get_mount_point(uri)
        if mp is None:
            raise NotFoundError(f"path {uri} is not covered by any mount")
        with self._lock:
            info = self._mounts[mp]
        rel = uri.path[len(mp):].lstrip(SEPARATOR)
        base = info.ufs_uri.rstrip(SEPARATOR)
        ufs_path = f"{base}{SEPARATOR}{rel}" if rel else (
            info.ufs_uri if info.ufs_uri.endswith(SEPARATOR) or not rel
            else base)
        return Resolution(mount_info=info, ufs_path=ufs_path)

    def reverse_resolve(self, ufs_uri: str) -> Optional[AlluxioURI]:
        """Map a UFS path back into the namespace (longest-prefix mount)."""
        with self._lock:
            best: Optional[Tuple[str, MountInfo]] = None
            for mp, info in self._mounts.items():
                base = info.ufs_uri.rstrip(SEPARATOR)
                if ufs_uri == base or ufs_uri.startswith(base + SEPARATOR) or (
                        info.ufs_uri.endswith(SEPARATOR)
                        and ufs_uri.startswith(info.ufs_uri)):
                    if best is None or len(base) > len(best[1].ufs_uri.rstrip(SEPARATOR)):
                        best = (mp, info)
            if best is None:
                return None
            mp, info = best
            rel = ufs_uri[len(info.ufs_uri.rstrip(SEPARATOR)):].lstrip(SEPARATOR)
            return AlluxioURI(mp).join(rel) if rel else AlluxioURI(mp)

    def mount_points(self) -> List[MountInfo]:
        with self._lock:
            return [MountInfo(i.mount_id, i.alluxio_path, i.ufs_uri,
                              i.read_only, i.shared, dict(i.properties))
                    for i in self._mounts.values()]

    def get_by_id(self, mount_id: int) -> Optional[MountInfo]:
        with self._lock:
            for info in self._mounts.values():
                if info.mount_id == mount_id:
                    return info
            return None

    def clear(self) -> None:
        with self._lock:
            self._mounts.clear()

    # -- snapshot -----------------------------------------------------------
    def snapshot(self) -> list:
        with self._lock:
            return [i.to_wire() for i in self._mounts.values()]

    def restore(self, snap: list) -> None:
        with self._lock:
            self._mounts.clear()
            for d in snap or []:
                info = MountInfo.from_wire(d)
                self._mounts[info.alluxio_path] = info
