"""The namespace inode tree.

Re-design of ``core/server/master/.../file/meta/InodeTree.java:84`` +
``InodeTreePersistentState.java:71``.

**Locking rationale.** The reference implements fine-grained per-inode
read/write locks with lock lists (``InodeLockManager.java:47``,
``SimpleInodeLockList``) — ~8k LoC of subtle ordering. Here the tree is a
**single-writer state machine behind one tree-level RW lock**: queries take
the read lock; every mutation is serialized through the journal and applied
under the write lock. On a Python control plane (GIL; 1 socket per master
host) the fine-grained scheme buys nothing, and single-writer application is
what makes journal replay trivially deterministic — the design SURVEY.md
section 7 ("hard parts") recommends.

All mutations arrive as journal entries via ``process_entry`` — the tree is
a ``Journaled`` component; the FileSystemMaster validates + emits entries,
it never pokes tree state directly.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set

from alluxio_tpu.journal.format import EntryType, JournalEntry, Journaled
from alluxio_tpu.master.inode import Inode, PersistenceState
from alluxio_tpu.master.metastore import HeapInodeStore, InodeStore
from alluxio_tpu.master.ttl import TtlBucketList
from alluxio_tpu.utils.exceptions import (
    FileDoesNotExistError, InvalidPathError,
)
from alluxio_tpu.utils.locks import RWLock
from alluxio_tpu.utils.uri import AlluxioURI

ROOT_ID_PARENT = -1


@dataclass
class PathLookup:
    """Resolution of a path: the inodes that exist along it
    (reference: ``LockedInodePath``)."""

    uri: AlluxioURI
    inodes: List[Inode] = field(default_factory=list)  # root..deepest existing

    @property
    def exists(self) -> bool:
        return len(self.inodes) == self.uri.depth() + 1

    @property
    def inode(self) -> Inode:
        if not self.exists:
            raise FileDoesNotExistError(f"path {self.uri} does not exist")
        return self.inodes[-1]

    @property
    def deepest(self) -> Inode:
        return self.inodes[-1]

    @property
    def missing_components(self) -> List[str]:
        comps = self.uri.path_components()
        return list(comps[len(self.inodes) - 1:])


class InodeTree(Journaled):
    journal_name = "InodeTree"

    def __init__(self, store: Optional[InodeStore] = None) -> None:
        self._store = store if store is not None else HeapInodeStore()
        self.lock = RWLock()
        self._root_id: Optional[int] = None
        self.ttl_buckets = TtlBucketList()
        self.pinned_ids: Set[int] = set()
        self.to_be_persisted_ids: Set[int] = set()
        #: files currently marked PersistenceState.LOST — rebuilt on
        #: replay/restore so the LostFileDetector can recover them
        #: after a master restart
        self.lost_file_ids: Set[int] = set()
        #: files with replication_min>0 or replication_max>=0; the
        #: ReplicationChecker walks only these (reference: the pinned/
        #: replication-limited inode registries in InodeTreePersistentState)
        self.replication_limited_ids: Set[int] = set()
        self._inode_count = 0

    # ------------------------------------------------------------------ read
    @property
    def root(self) -> Optional[Inode]:
        return self._store.get(self._root_id) if self._root_id is not None else None

    @property
    def inode_count(self) -> int:
        return self._inode_count

    def get_inode(self, inode_id: int) -> Optional[Inode]:
        return self._store.get(inode_id)

    def lookup(self, uri: AlluxioURI) -> PathLookup:
        """Walk the path from root; returns all inodes that exist."""
        result = PathLookup(uri=uri)
        root = self.root
        if root is None:
            raise InvalidPathError("inode tree not initialized")
        result.inodes.append(root)
        cur = root
        for name in uri.path_components():
            child_id = self._store.get_child_id(cur.id, name)
            if child_id is None:
                break
            child = self._store.get(child_id)
            if child is None:
                break
            result.inodes.append(child)
            cur = child
        return result

    def get_path(self, inode: Inode) -> AlluxioURI:
        """Reconstruct the full path of an inode by walking parents."""
        parts: List[str] = []
        cur: Optional[Inode] = inode
        while cur is not None and cur.parent_id != ROOT_ID_PARENT:
            parts.append(cur.name)
            cur = self._store.get(cur.parent_id)
        return AlluxioURI("/" + "/".join(reversed(parts)))

    def child_names(self, inode: Inode) -> List[str]:
        return self._store.child_names(inode.id)

    def parent_of(self, inode: Inode) -> Optional[Inode]:
        if inode.parent_id == ROOT_ID_PARENT:
            return None
        return self._store.get(inode.parent_id)

    def path_of_id(self, inode_id: int) -> Optional[AlluxioURI]:
        """Current full path of an inode id, or None when it no longer
        exists (callers hold the tree lock)."""
        inode = self._store.get(inode_id)
        if inode is None:
            return None
        return self.get_path(inode)

    def children(self, inode: Inode) -> Iterator[Inode]:
        for name in self._store.child_names(inode.id):
            cid = self._store.get_child_id(inode.id, name)
            if cid is not None:
                child = self._store.get(cid)
                if child is not None:
                    yield child

    def descendants(self, inode: Inode) -> Iterator[Inode]:
        """Post-order descendants (children before parents) for deletes."""
        for child in list(self.children(inode)):
            if child.is_directory:
                yield from self.descendants(child)
            yield child

    # ------------------------------------------------- journal application
    def process_entry(self, entry: JournalEntry) -> bool:
        t, p = entry.type, entry.payload
        if t == EntryType.INODE_DIRECTORY or t == EntryType.INODE_FILE:
            self._apply_create(Inode.from_wire_dict(p))
        elif t == EntryType.UPDATE_INODE:
            self._apply_update(p)
        elif t == EntryType.NEW_BLOCK:
            self._apply_new_block(p)
        elif t == EntryType.COMPLETE_FILE:
            self._apply_complete(p)
        elif t == EntryType.DELETE_FILE:
            self._apply_delete(p)
        elif t == EntryType.RENAME:
            self._apply_rename(p)
        elif t == EntryType.SET_ATTRIBUTE:
            self._apply_set_attribute(p)
        elif t == EntryType.SET_ACL:
            self._apply_set_acl(p)
        elif t == EntryType.PERSIST_FILE:
            self._apply_persist(p)
        else:
            return False
        return True

    def _apply_create(self, inode: Inode) -> None:
        self._store.put(inode)
        self._inode_count += 1
        if inode.parent_id == ROOT_ID_PARENT:
            self._root_id = inode.id
        else:
            self._store.add_child(inode.parent_id, inode.name, inode.id)
            parent = self._store.get(inode.parent_id)
            if parent is not None:
                parent.last_modification_time_ms = max(
                    parent.last_modification_time_ms, inode.creation_time_ms)
                self._store.put(parent)
        if inode.ttl >= 0:
            self.ttl_buckets.insert(inode.id, inode.creation_time_ms, inode.ttl)
        if inode.pinned:
            self.pinned_ids.add(inode.id)
        self._track_replication(inode)

    def _apply_update(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        for k, v in p.items():
            if k != "id" and hasattr(inode, k):
                setattr(inode, k, v)
        self._store.put(inode)

    def _apply_set_acl(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        inode.xattr = dict(p.get("xattr", {}))
        inode.last_modification_time_ms = p.get(
            "op_time_ms", inode.last_modification_time_ms)
        self._store.put(inode)

    def _apply_new_block(self, p: dict) -> None:
        inode = self._store.get(p["file_id"])
        if inode is None:
            return
        inode.block_ids.append(p["block_id"])
        self._store.put(inode)

    def _apply_complete(self, p: dict) -> None:
        inode = self._store.get(p["file_id"])
        if inode is None:
            return
        inode.completed = True
        inode.length = p["length"]
        inode.last_modification_time_ms = p.get("op_time_ms",
                                                inode.last_modification_time_ms)
        if "block_ids" in p and p["block_ids"] is not None:
            inode.block_ids = list(p["block_ids"])
        self._store.put(inode)

    def _apply_delete(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        self._store.remove_child(inode.parent_id, inode.name)
        self._store.remove(inode.id)
        self._inode_count -= 1
        self.pinned_ids.discard(inode.id)
        self.to_be_persisted_ids.discard(inode.id)
        self.lost_file_ids.discard(inode.id)
        self.replication_limited_ids.discard(inode.id)
        if inode.ttl >= 0:
            self.ttl_buckets.remove(inode.id)
        parent = self._store.get(inode.parent_id)
        if parent is not None:
            parent.last_modification_time_ms = max(
                parent.last_modification_time_ms,
                p.get("op_time_ms", parent.last_modification_time_ms))
            self._store.put(parent)

    def _apply_rename(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        self._store.remove_child(inode.parent_id, inode.name)
        inode.parent_id = p["new_parent_id"]
        inode.name = p["new_name"]
        inode.last_modification_time_ms = p.get(
            "op_time_ms", inode.last_modification_time_ms)
        self._store.put(inode)
        self._store.add_child(inode.parent_id, inode.name, inode.id)

    def _apply_set_attribute(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        if "pinned" in p and p["pinned"] is not None:
            inode.pinned = p["pinned"]
            if inode.pinned:
                self.pinned_ids.add(inode.id)
                inode.pinned_media = list(p.get("pinned_media") or [])
            else:
                self.pinned_ids.discard(inode.id)
                inode.pinned_media = []
        if "ttl" in p and p["ttl"] is not None:
            if inode.ttl >= 0:
                self.ttl_buckets.remove(inode.id)
            inode.ttl = p["ttl"]
            inode.ttl_action = p.get("ttl_action") or inode.ttl_action
            if inode.ttl >= 0:
                self.ttl_buckets.insert(
                    inode.id, p.get("op_time_ms", inode.creation_time_ms),
                    inode.ttl)
        for k in ("owner", "group", "mode", "replication_min",
                  "replication_max", "persistence_state",
                  "lost_pending_persist"):
            if p.get(k) is not None:
                setattr(inode, k, p[k])
        self._track_replication(inode)
        if p.get("persistence_state") == PersistenceState.TO_BE_PERSISTED:
            self.to_be_persisted_ids.add(inode.id)
        elif p.get("persistence_state") is not None:
            self.to_be_persisted_ids.discard(inode.id)
        if p.get("persistence_state") == PersistenceState.LOST:
            self.lost_file_ids.add(inode.id)
        elif p.get("persistence_state") is not None:
            self.lost_file_ids.discard(inode.id)
        if p.get("xattr") is not None:
            inode.xattr.update(p["xattr"])
        if p.get("op_time_ms"):
            inode.last_modification_time_ms = p["op_time_ms"]
        self._store.put(inode)

    def _apply_persist(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        inode.persistence_state = PersistenceState.PERSISTED
        inode.ufs_fingerprint = p.get("ufs_fingerprint", inode.ufs_fingerprint)
        self.to_be_persisted_ids.discard(inode.id)
        self.lost_file_ids.discard(inode.id)
        self._store.put(inode)

    def _track_replication(self, inode: Inode) -> None:
        if not inode.is_directory and (inode.replication_min > 0 or
                                       inode.replication_max >= 0):
            self.replication_limited_ids.add(inode.id)
        else:
            self.replication_limited_ids.discard(inode.id)

    # ---------------------------------------------------------- checkpoint
    def snapshot(self) -> dict:
        inode_dicts = []
        for iid in self._store.all_ids():
            inode = self._store.get(iid)
            if inode is not None:
                inode_dicts.append(inode.to_wire_dict())
        return {
            "root_id": self._root_id,
            "inodes": inode_dicts,
        }

    def restore(self, snap: dict) -> None:
        self._store.clear()
        self.ttl_buckets.clear()
        self.pinned_ids.clear()
        self.to_be_persisted_ids.clear()
        self.lost_file_ids.clear()
        self.replication_limited_ids.clear()
        self._inode_count = 0
        self._root_id = snap.get("root_id")
        for d in snap.get("inodes", []):
            inode = Inode.from_wire_dict(d)
            self._store.put(inode)
            self._inode_count += 1
            if inode.parent_id != ROOT_ID_PARENT:
                self._store.add_child(inode.parent_id, inode.name, inode.id)
            if inode.ttl >= 0:
                self.ttl_buckets.insert(inode.id, inode.creation_time_ms,
                                        inode.ttl)
            if inode.pinned:
                self.pinned_ids.add(inode.id)
            if inode.persistence_state == PersistenceState.TO_BE_PERSISTED:
                self.to_be_persisted_ids.add(inode.id)
            if inode.persistence_state == PersistenceState.LOST:
                self.lost_file_ids.add(inode.id)
            self._track_replication(inode)

    def _empty_snapshot(self) -> dict:
        return {"root_id": None, "inodes": []}
