"""The namespace inode tree.

Re-design of ``core/server/master/.../file/meta/InodeTree.java:84`` +
``InodeTreePersistentState.java:71``.

**Locking rationale.** The reference implements fine-grained per-inode
read/write locks with lock lists (``InodeLockManager.java:47``,
``SimpleInodeLockList``). This tree started life as a single-writer state
machine behind one tree-level RW lock; at millions-of-users metadata rates
that one lock became the cluster ceiling (BENCH_SUITE: ListStatus ~1.6k
ops/s while the data plane streams GB/s), so the scheme is now **two
level**:

- ``self.lock`` (tree-level RW lock) is held in READ mode by every
  path-locked operation and in WRITE mode only by heavyweight multi-phase
  operations (mount/unmount, UFS metadata load, commit_persist,
  snapshot/restore).  A tree-write therefore still excludes everything —
  the safe fallback for paths not worth striping.
- ``lock_path()`` hands out a :class:`LockedInodePath` — per-inode
  read/write locks acquired root→leaf along the path (read on ancestors,
  write on the terminal/deepest-existing inode only), mirroring the
  reference's ``SimpleInodeLockList``.  Independent subtrees — the common
  case for per-host training shards — no longer serialize.
- **WRITE_EDGE locking** (reference: ``InodeTree.LockPattern.WRITE_EDGE``):
  with ``edge_locking`` on (the default), a create takes only a READ lock
  on the deepest existing inode plus a WRITE lock on the *edge*
  ``(parent_id, name)`` it is about to fill; deletes/renames write-lock
  their terminal AND its parent edge.  Sibling creates/deletes under ONE
  hot directory — the "many trainers materializing shards into one dir"
  pattern — no longer serialize on the parent inode's write lock; only
  same-NAME operations contend.  The parent read lock still excludes a
  concurrent delete of the parent (which needs the parent's write lock).

Acquisition order is canonical and audited (``lint/pytest_lockaudit``):
``InodeTree.lock`` (read) → ``InodeTree.inode_lock`` (root→leaf, write at
the tail) → ``InodeTree.edge_lock`` (after ALL inode locks; pairs sort
their ≤2 edges by ``(parent_id, name)``) → everything downstream (journal
commit queue, BlockMaster).  Multi-path operations (rename) acquire their
two lock lists as one merged plan in lexicographic path order.

All mutations arrive as journal entries via ``process_entry`` — the tree is
a ``Journaled`` component; the FileSystemMaster validates + emits entries,
it never pokes tree state directly.  Applies are serialized by the journal
system; the small id registries (pinned/TTL/persist sets) carry their own
``registry_lock`` so snapshot readers never iterate a mutating set.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterator, List, Optional, Set, Tuple

from alluxio_tpu.journal.format import EntryType, JournalEntry, Journaled
from alluxio_tpu.master.inode import Inode, PersistenceState
from alluxio_tpu.master.metastore import HeapInodeStore, InodeStore
from alluxio_tpu.master.ttl import TtlBucketList
from alluxio_tpu.utils.exceptions import (
    FileDoesNotExistError, InvalidPathError,
)
from alluxio_tpu.utils.locks import RWLock
from alluxio_tpu.utils.uri import AlluxioURI

ROOT_ID_PARENT = -1

#: entry types that mutate the namespace — each application bumps
#: ``InodeTree.change_version`` (the listing cache's coherence stamp)
_MUTATING_TYPES = frozenset((
    EntryType.INODE_DIRECTORY, EntryType.INODE_FILE, EntryType.UPDATE_INODE,
    EntryType.NEW_BLOCK, EntryType.COMPLETE_FILE, EntryType.DELETE_FILE,
    EntryType.RENAME, EntryType.SET_ATTRIBUTE, EntryType.SET_ACL,
    EntryType.PERSIST_FILE,
))

#: (registry, timer) cache: the lock-wait timer updates on EVERY
#: path-locked metadata op, so the per-call registry lock + dict lookup
#: must stay off the hot path — but tests' ``reset_metrics()`` swaps the
#: registry, so the cache keys on registry identity, not process
#: lifetime (same constraint ``master/metrics_master.py`` documents)
_timer_cache: "Tuple[object, object]" = (None, None)


def _lock_wait_timer():
    global _timer_cache
    from alluxio_tpu.metrics import metrics

    reg = metrics()
    cached_reg, timer = _timer_cache
    if cached_reg is not reg:
        timer = reg.timer("Master.MetadataInodeLockWaitTime")
        _timer_cache = (reg, timer)
    return timer


class InodeLockManager:
    """Pool of keyed RW locks, created on demand and swept when idle
    (reference: ``InodeLockManager.java:47`` — there a weak-value map).
    Keys are inode ids for the inode pool and ``(parent_id, name)``
    tuples for the edge pool — any hashable works.

    ``checkout``/``checkin`` refcount each lock so a sweep can never
    evict a lock some thread still holds: two paths locking the same
    key MUST share one RWLock object, and eviction-while-held would
    silently split them."""

    #: idle locks are swept once the pool outgrows this (a pool entry
    #: is ~a hundred bytes; 64k ≈ the hot working set of a large run)
    MAX_IDLE_POOL = 65536

    def __init__(self) -> None:
        self._locks: Dict[object, list] = {}  # key -> [lock, refcount]
        self._pool_lock = threading.Lock()
        #: test-harness hook (lint/pytest_lockaudit): wraps every fresh
        #: RWLock in an audited proxy (``InodeTree.inode_lock`` /
        #: ``InodeTree.edge_lock``)
        self._proxy_factory = None

    def checkout(self, key):
        with self._pool_lock:
            ent = self._locks.get(key)
            if ent is None:
                lock = RWLock()
                if self._proxy_factory is not None:
                    lock = self._proxy_factory(lock)
                ent = self._locks[key] = [lock, 0]
            ent[1] += 1
            return ent[0]

    def checkin(self, key) -> None:
        with self._pool_lock:
            ent = self._locks.get(key)
            if ent is None:
                return
            ent[1] -= 1
            if ent[1] <= 0 and len(self._locks) > self.MAX_IDLE_POOL:
                # amortized sweep of ALL idle entries (refcount 0 means
                # no thread can be inside acquire/release on it)
                for k in [k for k, e in self._locks.items() if e[1] <= 0]:
                    del self._locks[k]

    def pool_size(self) -> int:
        with self._pool_lock:
            return len(self._locks)


class LockedInodePath:
    """An ordered per-inode lock list along ``uri`` (reference:
    ``SimpleInodeLockList`` + ``LockedInodePath``): read locks root→parent,
    write lock on the terminal inode — or, when the terminal does not
    exist (create), on the deepest EXISTING inode, under which all new
    inodes are linked.

    Acquisition is optimistic: walk the tree unlocked (the store is
    internally synchronized), acquire the planned locks root→leaf, then
    re-validate every edge of the locked chain against the live tree —
    a concurrent rename/delete/create that moved the path retries the
    walk.  Validated chains are then stable: every inode in the chain is
    read-held here, and any namespace mutation of it (or of the edge
    below the deepest) requires a write lock this list excludes.
    """

    def __init__(self, tree: "InodeTree", uri: AlluxioURI, *,
                 write: bool = False, write_parent: bool = False) -> None:
        self._tree = tree
        self.uri = uri
        self.write = write
        #: also write-lock the terminal's parent (atomic replace:
        #: create(overwrite=True) deletes the terminal then re-creates
        #: under the parent inside ONE lock scope)
        self._write_parent = write_parent
        self._held: List[Tuple[int, str, object]] = []
        self.lookup: Optional[PathLookup] = None

    # -- acquisition --------------------------------------------------------
    def acquire(self) -> "LockedInodePath":
        tree = self._tree
        comps = self.uri.path_components()
        try:
            while True:
                chain, modes, full, edge = _plan(tree, comps, self.write,
                                                 self._write_parent)
                _acquire_planned(tree, zip(chain, modes), self._held)
                if edge is not None:
                    _acquire_edges(tree, [edge], self._held)
                if _validate_chain(tree, chain, comps, full):
                    self.lookup = PathLookup(uri=self.uri, inodes=chain)
                    return self
                self.release()
        except BaseException:
            # a store error (e.g. a SQLITE metastore hiccup) mid-plan or
            # mid-validate must not leak held locks: a leaked terminal
            # write lock would wedge its path forever
            self.release()
            raise

    def release(self) -> None:
        _release_held(self._tree, self._held)


def _plan(tree: "InodeTree", comps, write: bool, write_parent: bool):
    """Walk (unlocked) and plan lock modes root→leaf plus, under edge
    locking, the write-mode edge ``(parent_id, name)`` the operation
    mutates.  Read on ancestors; the terminal inode is write-locked when
    it exists (its fields mutate), while a CREATE write-locks only the
    missing edge and READ-locks the deepest existing inode — sibling
    creates under one directory stop excluding each other."""
    root = tree.root
    if root is None:
        raise InvalidPathError("inode tree not initialized")
    store = tree._store
    chain: List[Inode] = [root]
    cur = root
    for name in comps:
        cid = store.get_child_id(cur.id, name)
        if cid is None:
            break
        child = store.get(cid)
        if child is None:
            break
        chain.append(child)
        cur = child
    full = len(chain) == len(comps) + 1
    modes = ["r"] * len(chain)
    edge: Optional[Tuple[int, str]] = None
    if write:
        if tree.edge_locking:
            if full:
                # existing terminal: write the inode (field mutations)
                # AND its parent edge (delete/rename unlink it)
                modes[-1] = "w"
                if len(chain) >= 2:
                    edge = (chain[-2].id, comps[len(chain) - 2])
                if write_parent and len(chain) >= 2:
                    modes[-2] = "w"
            elif len(comps) > 0:
                # create: the deepest existing inode stays read-held
                # (keeping it alive — deleting it needs its write lock);
                # the FIRST MISSING edge is the thing being filled in
                edge = (chain[-1].id, comps[len(chain) - 1])
        else:
            modes[-1] = "w"
            if write_parent and full and len(chain) >= 2:
                modes[-2] = "w"
    return chain, modes, full, edge


def _acquire_planned(tree: "InodeTree", planned, held: List[Tuple]) -> None:
    """Acquire ``(inode, mode)`` pairs in the given order, recording
    into ``held`` (release via ``_release_held``)."""
    mgr = tree.lock_manager
    for inode, mode in planned:
        lock = mgr.checkout(inode.id)
        if mode == "w":
            lock.acquire_write()
        else:
            lock.acquire_read()
        held.append(("inode", inode.id, mode, lock))


def _acquire_edges(tree: "InodeTree", edges, held: List[Tuple]) -> None:
    """Write-acquire edge locks AFTER every inode lock (the canonical
    order); multi-edge callers pass them sorted by ``(parent_id,
    name)`` — the total order that keeps two renames from deadlocking."""
    mgr = tree.edge_lock_manager
    for edge in edges:
        lock = mgr.checkout(edge)
        lock.acquire_write()
        held.append(("edge", edge, "w", lock))


def _release_held(tree: "InodeTree", held: List[Tuple]) -> None:
    for kind, key, mode, lock in reversed(held):
        if mode == "w":
            lock.release_write()
        else:
            lock.release_read()
        if kind == "edge":
            tree.edge_lock_manager.checkin(key)
        else:
            tree.lock_manager.checkin(key)
    held.clear()


def _validate_chain(tree: "InodeTree", chain: List[Inode], comps,
                    full: bool) -> bool:
    store = tree._store
    if tree._root_id != chain[0].id:
        return False
    for i, child in enumerate(chain[1:]):
        # validate against the REQUESTED component names, not the
        # (mutable) inode.name attr: a same-parent rename keeps the
        # edge consistent with inode.name while leaving our path
        if store.get_child_id(chain[i].id, comps[i]) != child.id:
            return False
    if not full:
        # the first missing component must still be missing, or the
        # lock list stops above the true terminal
        if store.get_child_id(chain[-1].id, comps[len(chain) - 1]) \
                is not None:
            return False
    return True


class LockedInodePathPair:
    """Two lock lists acquired as ONE merged plan (rename).  The union
    of both chains is taken with the strongest mode per inode — the two
    root-down chains share exactly their common path prefix, so merging
    avoids the same-thread read→write upgrade a sequential acquisition
    would deadlock on — and is acquired prefix-first, then the two
    divergent suffixes in lexicographic path order (the canonical order
    all multi-path operations share)."""

    def __init__(self, tree: "InodeTree", first: AlluxioURI,
                 second: AlluxioURI) -> None:
        self._tree = tree
        self._first, self._second = first, second
        self._held: List[Tuple[int, str, object]] = []
        self.first_lookup: Optional[PathLookup] = None
        self.second_lookup: Optional[PathLookup] = None

    def acquire(self) -> "LockedInodePathPair":
        tree = self._tree
        a_uri, b_uri = sorted((self._first, self._second),
                              key=lambda u: u.path)
        a_comps, b_comps = a_uri.path_components(), b_uri.path_components()
        try:
            while True:
                a_chain, a_modes, a_full, a_edge = _plan(
                    tree, a_comps, True, False)
                b_chain, b_modes, b_full, b_edge = _plan(
                    tree, b_comps, True, False)
                # merged plan: strongest mode per inode; shared inodes are
                # exactly the chains' common prefix (root-down paths)
                want: Dict[int, str] = {}
                order: List[Inode] = []
                for chain, modes in ((a_chain, a_modes),
                                     (b_chain, b_modes)):
                    for inode, mode in zip(chain, modes):
                        if inode.id not in want:
                            want[inode.id] = mode
                            order.append(inode)
                        elif mode == "w":
                            want[inode.id] = "w"
                _acquire_planned(tree, ((i, want[i.id]) for i in order),
                                 self._held)
                # both edges AFTER the merged inode plan, in the global
                # (parent_id, name) total order — concurrent pairs can
                # never hold one edge while waiting on the other crosswise
                edges = sorted({e for e in (a_edge, b_edge)
                                if e is not None})
                _acquire_edges(tree, edges, self._held)
                if _validate_chain(tree, a_chain, a_comps, a_full) and \
                        _validate_chain(tree, b_chain, b_comps, b_full):
                    lookups = {
                        a_uri.path: PathLookup(uri=a_uri, inodes=a_chain),
                        b_uri.path: PathLookup(uri=b_uri, inodes=b_chain),
                    }
                    self.first_lookup = lookups[self._first.path]
                    self.second_lookup = lookups[self._second.path]
                    return self
                self.release()
        except BaseException:
            self.release()  # never leak a partial merged plan
            raise

    def release(self) -> None:
        _release_held(self._tree, self._held)


class _PathHandle:
    """Minimal ``lock_path`` result holder: a resolved lookup whose
    locks are managed by the enclosing scope (coarse mode and the
    pair-lock wrapper both use it)."""

    def __init__(self, lookup: "PathLookup") -> None:
        self.lookup = lookup

    def release(self) -> None:  # pragma: no cover - symmetry only
        pass


@dataclass
class PathLookup:
    """Resolution of a path: the inodes that exist along it
    (reference: ``LockedInodePath``)."""

    uri: AlluxioURI
    inodes: List[Inode] = field(default_factory=list)  # root..deepest existing

    @property
    def exists(self) -> bool:
        return len(self.inodes) == self.uri.depth() + 1

    @property
    def inode(self) -> Inode:
        if not self.exists:
            raise FileDoesNotExistError(f"path {self.uri} does not exist")
        return self.inodes[-1]

    @property
    def deepest(self) -> Inode:
        return self.inodes[-1]

    @property
    def missing_components(self) -> List[str]:
        comps = self.uri.path_components()
        return list(comps[len(self.inodes) - 1:])


class InodeTree(Journaled):
    journal_name = "InodeTree"

    def __init__(self, store: Optional[InodeStore] = None, *,
                 coarse_locking: bool = False,
                 edge_locking: bool = True) -> None:
        self._store = store if store is not None else HeapInodeStore()
        self.lock = RWLock()
        self.lock_manager = InodeLockManager()
        #: WRITE_EDGE lock pool, keyed ``(parent_id, name)`` — acquired
        #: strictly AFTER every inode lock (audited order)
        self.edge_lock_manager = InodeLockManager()
        #: True: ``lock_path`` degrades to the tree-level lock (the
        #: pre-striping single-lock master) — bench baseline + escape
        #: hatch; striped is the default
        self.coarse_locking = coarse_locking
        #: False: creates fall back to write-locking the deepest existing
        #: inode (the pre-WRITE_EDGE scheme) — bench baseline
        self.edge_locking = edge_locking
        #: guards the id registries below (pinned/to-be-persisted/lost/
        #: replication-limited sets + inode_count + change_version):
        #: journal applies mutate them while snapshot readers copy them,
        #: and striped locking means those no longer share the tree lock
        self.registry_lock = threading.Lock()
        #: monotonic namespace-mutation counter (bumped per applied
        #: mutating journal entry).  "version unchanged" == "namespace
        #: unchanged" — the listing cache's coherence stamp, replacing
        #: the tree-write-lock version that striping made incomplete.
        self.change_version = 0
        self._root_id: Optional[int] = None
        self.ttl_buckets = TtlBucketList()
        self.pinned_ids: Set[int] = set()
        self.to_be_persisted_ids: Set[int] = set()
        #: files currently marked PersistenceState.LOST — rebuilt on
        #: replay/restore so the LostFileDetector can recover them
        #: after a master restart
        self.lost_file_ids: Set[int] = set()
        #: files with replication_min>0 or replication_max>=0; the
        #: ReplicationChecker walks only these (reference: the pinned/
        #: replication-limited inode registries in InodeTreePersistentState)
        self.replication_limited_ids: Set[int] = set()
        self._inode_count = 0
        #: invalidation-log feed (FileSystemMaster installs
        #: ``invalidations.append``).  Called from ``process_entry`` —
        #: the JOURNAL APPLY path — so primary and tailing standbys
        #: advance the same deterministic md_version sequence; the RPC
        #: methods themselves never append (docs/ha.md).
        self.invalidation_sink: Optional[Callable[[str], None]] = None
        #: the log itself (FileSystemMaster wires it alongside the
        #: sink): checkpoint snapshots carry its version so a master
        #: bootstrapping from a checkpoint — which skips the entries the
        #: checkpoint covers — still counts the same md_version a full
        #: replay would (docs/ha.md)
        self.invalidation_log = None

    # ------------------------------------------------------------- locking
    @contextlib.contextmanager
    def lock_path(self, uri: AlluxioURI, *, write: bool = False,
                  write_parent: bool = False):
        """Scope holding the tree lock (read) plus an ordered per-inode
        lock list along ``uri`` — read locks on ancestors, write lock on
        the terminal (or deepest existing, for creates).  Yields the
        list with a fresh :class:`PathLookup` in ``.lookup``.  In coarse
        mode this is exactly the old single-lock critical section."""
        if self.coarse_locking:
            guard = self.lock.write_locked() if write \
                else self.lock.read_locked()
            with guard:
                yield _PathHandle(self.lookup(uri))
            return
        t0 = time.perf_counter()
        self.lock.acquire_read()
        lip = LockedInodePath(self, uri, write=write,
                              write_parent=write_parent)
        try:
            lip.acquire()
        except BaseException:
            self.lock.release_read()
            raise
        _lock_wait_timer().update(time.perf_counter() - t0)
        try:
            yield lip
        finally:
            lip.release()
            self.lock.release_read()

    @contextlib.contextmanager
    def lock_path_pair(self, first: AlluxioURI, second: AlluxioURI, *,
                       write: bool = True):
        """Two lock lists for a two-path operation (rename).  Lists are
        acquired in lexicographic path order — every multi-path caller
        converging on the same total order is what keeps two concurrent
        renames from deadlocking — and yielded in CALLER order."""
        if self.coarse_locking:
            guard = self.lock.write_locked() if write \
                else self.lock.read_locked()
            with guard:
                yield (_PathHandle(self.lookup(first)),
                       _PathHandle(self.lookup(second)))
            return
        t0 = time.perf_counter()
        self.lock.acquire_read()
        pair = LockedInodePathPair(self, first, second)
        try:
            pair.acquire()
        except BaseException:
            self.lock.release_read()
            raise
        _lock_wait_timer().update(time.perf_counter() - t0)
        try:
            yield (_PathHandle(pair.first_lookup),
                   _PathHandle(pair.second_lookup))
        finally:
            pair.release()
            self.lock.release_read()

    # ------------------------------------------------------------------ read
    @property
    def root(self) -> Optional[Inode]:
        return self._store.get(self._root_id) if self._root_id is not None else None

    @property
    def inode_count(self) -> int:
        return self._inode_count

    def get_inode(self, inode_id: int) -> Optional[Inode]:
        return self._store.get(inode_id)

    def lookup(self, uri: AlluxioURI) -> PathLookup:
        """Walk the path from root; returns all inodes that exist."""
        result = PathLookup(uri=uri)
        root = self.root
        if root is None:
            raise InvalidPathError("inode tree not initialized")
        result.inodes.append(root)
        cur = root
        for name in uri.path_components():
            child_id = self._store.get_child_id(cur.id, name)
            if child_id is None:
                break
            child = self._store.get(child_id)
            if child is None:
                break
            result.inodes.append(child)
            cur = child
        return result

    def get_path(self, inode: Inode) -> AlluxioURI:
        """Reconstruct the full path of an inode by walking parents."""
        parts: List[str] = []
        cur: Optional[Inode] = inode
        while cur is not None and cur.parent_id != ROOT_ID_PARENT:
            parts.append(cur.name)
            cur = self._store.get(cur.parent_id)
        return AlluxioURI("/" + "/".join(reversed(parts)))

    def child_names(self, inode: Inode) -> List[str]:
        return self._store.child_names(inode.id)

    def parent_of(self, inode: Inode) -> Optional[Inode]:
        if inode.parent_id == ROOT_ID_PARENT:
            return None
        return self._store.get(inode.parent_id)

    def path_of_id(self, inode_id: int) -> Optional[AlluxioURI]:
        """Current full path of an inode id, or None when it no longer
        exists (callers hold the tree lock)."""
        inode = self._store.get(inode_id)
        if inode is None:
            return None
        return self.get_path(inode)

    def children(self, inode: Inode,
                 start_after: Optional[str] = None) -> Iterator[Inode]:
        """Stream children in name order via the store's iterator
        contract — one range scan on LSM (one lookup per child instead
        of the old three), resumable at ``start_after`` for paged
        listings."""
        for _name, cid in self._store.iter_edges(inode.id, start_after):
            child = self._store.get(cid)
            if child is not None:
                yield child

    def has_children(self, inode: Inode) -> bool:
        return self._store.has_children(inode.id)

    def descendants(self, inode: Inode) -> Iterator[Inode]:
        """Post-order descendants (children before parents) for deletes."""
        for child in list(self.children(inode)):
            if child.is_directory:
                yield from self.descendants(child)
            yield child

    # ------------------------------------------------- journal application
    def process_entry(self, entry: JournalEntry) -> bool:
        # Invalidation paths resolve around the apply: delete/rename need
        # the PRE-apply path (the inode edge is gone after), creates the
        # POST-apply one.  Feeding the sink from the apply path — not the
        # RPC methods — makes the invalidation-log version a pure
        # function of the applied journal, so a tailing standby counts
        # the SAME md_version the primary stamps (docs/ha.md).
        if entry.type == EntryType.INVALIDATE_PATH:
            # a client-cache invalidation with no metadata mutation of
            # its own (block-location drift, free): journaled purely so
            # the version sequence advances identically on primary and
            # tailing standbys
            with self.registry_lock:
                self.change_version += 1
            sink = self.invalidation_sink
            if sink is not None:
                sink(entry.payload.get("path", "/"))
            return True
        pre_paths: List[str] = []
        # a "covered" DELETE_FILE is a recursive delete's descendant:
        # the delete ROOT's own entry invalidates the whole subtree by
        # client-side prefix semantics, and appending one ring entry
        # per victim would push a large delete past the bounded ring's
        # horizon — a cluster-wide cache reset where one prefix does
        covered = bool(entry.payload.get("covered"))
        if self.invalidation_sink is not None and not covered and \
                entry.type in (EntryType.DELETE_FILE, EntryType.RENAME):
            uri = self.path_of_id(entry.payload.get("id"))
            if uri is not None:
                pre_paths.append(uri.path)
        out = self._process_entry(entry)
        # bump AFTER the mutation lands: a concurrent lister that read
        # the pre-bump version can then never cache a post-mutation
        # stamp on pre-mutation data — the race fails as a cache miss,
        # never as a stale hit
        if entry.type in _MUTATING_TYPES:
            with self.registry_lock:
                self.change_version += 1
            sink = self.invalidation_sink
            if sink is not None:
                # post-apply resolution, same stale-hit ordering as the
                # change_version bump above: the version moves only once
                # the mutated state is visible
                paths = list(pre_paths)
                if entry.type not in (EntryType.DELETE_FILE,):
                    target = entry.payload.get("id",
                                               entry.payload.get("file_id"))
                    uri = self.path_of_id(target) if target is not None \
                        else None
                    if uri is not None and uri.path not in paths:
                        paths.append(uri.path)
                for p in paths:
                    sink(p)
        return out

    def _process_entry(self, entry: JournalEntry) -> bool:
        t, p = entry.type, entry.payload
        if t == EntryType.INODE_DIRECTORY or t == EntryType.INODE_FILE:
            self._apply_create(Inode.from_wire_dict(p))
        elif t == EntryType.UPDATE_INODE:
            self._apply_update(p)
        elif t == EntryType.NEW_BLOCK:
            self._apply_new_block(p)
        elif t == EntryType.COMPLETE_FILE:
            self._apply_complete(p)
        elif t == EntryType.DELETE_FILE:
            self._apply_delete(p)
        elif t == EntryType.RENAME:
            self._apply_rename(p)
        elif t == EntryType.SET_ATTRIBUTE:
            self._apply_set_attribute(p)
        elif t == EntryType.SET_ACL:
            self._apply_set_acl(p)
        elif t == EntryType.PERSIST_FILE:
            self._apply_persist(p)
        else:
            return False
        return True

    def _apply_create(self, inode: Inode) -> None:
        self._store.put(inode)
        with self.registry_lock:
            self._inode_count += 1
        if inode.parent_id == ROOT_ID_PARENT:
            self._root_id = inode.id
        else:
            self._store.add_child(inode.parent_id, inode.name, inode.id)
            parent = self._store.get(inode.parent_id)
            if parent is not None:
                parent.last_modification_time_ms = max(
                    parent.last_modification_time_ms, inode.creation_time_ms)
                self._store.put(parent)
        if inode.ttl >= 0:
            self.ttl_buckets.insert(inode.id, inode.creation_time_ms, inode.ttl)
        with self.registry_lock:
            if inode.pinned:
                self.pinned_ids.add(inode.id)
            self._track_replication(inode)

    def _apply_update(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        for k, v in p.items():
            if k != "id" and hasattr(inode, k):
                setattr(inode, k, v)
        self._store.put(inode)

    def _apply_set_acl(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        inode.xattr = dict(p.get("xattr", {}))
        inode.last_modification_time_ms = p.get(
            "op_time_ms", inode.last_modification_time_ms)
        self._store.put(inode)

    def _apply_new_block(self, p: dict) -> None:
        inode = self._store.get(p["file_id"])
        if inode is None:
            return
        inode.block_ids.append(p["block_id"])
        self._store.put(inode)

    def _apply_complete(self, p: dict) -> None:
        inode = self._store.get(p["file_id"])
        if inode is None:
            return
        inode.completed = True
        inode.length = p["length"]
        inode.last_modification_time_ms = p.get("op_time_ms",
                                                inode.last_modification_time_ms)
        if "block_ids" in p and p["block_ids"] is not None:
            inode.block_ids = list(p["block_ids"])
        self._store.put(inode)

    def _apply_delete(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        self._store.remove_child(inode.parent_id, inode.name)
        self._store.remove(inode.id)
        with self.registry_lock:
            self._inode_count -= 1
            self.pinned_ids.discard(inode.id)
            self.to_be_persisted_ids.discard(inode.id)
            self.lost_file_ids.discard(inode.id)
            self.replication_limited_ids.discard(inode.id)
        if inode.ttl >= 0:
            self.ttl_buckets.remove(inode.id)
        parent = self._store.get(inode.parent_id)
        if parent is not None:
            parent.last_modification_time_ms = max(
                parent.last_modification_time_ms,
                p.get("op_time_ms", parent.last_modification_time_ms))
            self._store.put(parent)

    def _apply_rename(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        self._store.remove_child(inode.parent_id, inode.name)
        inode.parent_id = p["new_parent_id"]
        inode.name = p["new_name"]
        inode.last_modification_time_ms = p.get(
            "op_time_ms", inode.last_modification_time_ms)
        self._store.put(inode)
        self._store.add_child(inode.parent_id, inode.name, inode.id)

    def _apply_set_attribute(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        if "pinned" in p and p["pinned"] is not None:
            inode.pinned = p["pinned"]
            with self.registry_lock:
                if inode.pinned:
                    self.pinned_ids.add(inode.id)
                    inode.pinned_media = list(p.get("pinned_media") or [])
                else:
                    self.pinned_ids.discard(inode.id)
                    inode.pinned_media = []
        if "ttl" in p and p["ttl"] is not None:
            if inode.ttl >= 0:
                self.ttl_buckets.remove(inode.id)
            inode.ttl = p["ttl"]
            inode.ttl_action = p.get("ttl_action") or inode.ttl_action
            if inode.ttl >= 0:
                self.ttl_buckets.insert(
                    inode.id, p.get("op_time_ms", inode.creation_time_ms),
                    inode.ttl)
        for k in ("owner", "group", "mode", "replication_min",
                  "replication_max", "persistence_state",
                  "lost_pending_persist"):
            if p.get(k) is not None:
                setattr(inode, k, p[k])
        with self.registry_lock:
            self._track_replication(inode)
            if p.get("persistence_state") == PersistenceState.TO_BE_PERSISTED:
                self.to_be_persisted_ids.add(inode.id)
            elif p.get("persistence_state") is not None:
                self.to_be_persisted_ids.discard(inode.id)
            if p.get("persistence_state") == PersistenceState.LOST:
                self.lost_file_ids.add(inode.id)
            elif p.get("persistence_state") is not None:
                self.lost_file_ids.discard(inode.id)
        if p.get("xattr") is not None:
            inode.xattr.update(p["xattr"])
        if p.get("op_time_ms"):
            inode.last_modification_time_ms = p["op_time_ms"]
        self._store.put(inode)

    def _apply_persist(self, p: dict) -> None:
        inode = self._store.get(p["id"])
        if inode is None:
            return
        inode.persistence_state = PersistenceState.PERSISTED
        inode.ufs_fingerprint = p.get("ufs_fingerprint", inode.ufs_fingerprint)
        with self.registry_lock:
            self.to_be_persisted_ids.discard(inode.id)
            self.lost_file_ids.discard(inode.id)
        self._store.put(inode)

    def _track_replication(self, inode: Inode) -> None:
        # callers hold ``registry_lock``
        if not inode.is_directory and (inode.replication_min > 0 or
                                       inode.replication_max >= 0):
            self.replication_limited_ids.add(inode.id)
        else:
            self.replication_limited_ids.discard(inode.id)

    # ---------------------------------------------------------- checkpoint
    def snapshot(self) -> dict:
        # a store with a native checkpoint (LSM: sealed runs + empty WAL)
        # snapshots itself — no inode-by-inode materialization; HEAP /
        # SQLITE keep the original inode-list format byte-for-byte
        store_state = self._store.checkpoint_state()
        if store_state is not None:
            snap = {"root_id": self._root_id, "store_state": store_state}
        else:
            inode_dicts = []
            for iid in self._store.all_ids():
                inode = self._store.get(iid)
                if inode is not None:
                    inode_dicts.append(inode.to_wire_dict())
            snap = {
                "root_id": self._root_id,
                "inodes": inode_dicts,
            }
        if self.invalidation_log is not None:
            # restoring from this checkpoint skips the applied entries
            # it covers, so the version they advanced must ride along —
            # md_version stays a pure function of the applied journal
            snap["invalidation_version"] = self.invalidation_log.version
        return snap

    def restore(self, snap: dict) -> None:
        if self.invalidation_log is not None:
            self.invalidation_log.restore_version(
                snap.get("invalidation_version", 0))
        self._store.clear()
        self.ttl_buckets.clear()
        with self.registry_lock:
            self.pinned_ids.clear()
            self.to_be_persisted_ids.clear()
            self.lost_file_ids.clear()
            self.replication_limited_ids.clear()
            self._inode_count = 0
            self.change_version += 1
        self._root_id = snap.get("root_id")
        if "store_state" in snap:
            # native restore: adopt the run set wholesale, then rebuild
            # the derived side state (ttl buckets, id registries, count)
            # with ONE streaming pass — same bootstrap a replay would
            # produce, minus re-journaling every inode
            try:
                self._store.restore_state(snap["store_state"])
            except NotImplementedError:
                self._restore_cross_kind(snap["store_state"])
                return
            for inode in self._store.iter_inodes():
                self._index_restored(inode)
            return
        for d in snap.get("inodes", []):
            inode = Inode.from_wire_dict(d)
            self._store.put(inode)
            if inode.parent_id != ROOT_ID_PARENT:
                self._store.add_child(inode.parent_id, inode.name, inode.id)
            self._index_restored(inode)

    def _restore_cross_kind(self, store_state: dict) -> None:
        """An LSM-native checkpoint arriving at a master whose own store
        has no native format (HEAP/SQLITE standby behind an LSM primary):
        hydrate through a throwaway LSM reader instead of failing the
        bootstrap."""
        import shutil
        import tempfile

        from alluxio_tpu.master.metastore.lsm import LsmInodeStore

        tmp = tempfile.mkdtemp(prefix="atpu_lsm_restore_")
        try:
            reader = LsmInodeStore(tmp, compaction=False)
            reader.restore_state(store_state)
            for inode in reader.iter_inodes():
                self._store.put(inode)
                if inode.parent_id != ROOT_ID_PARENT:
                    self._store.add_child(inode.parent_id, inode.name,
                                          inode.id)
                self._index_restored(inode)
            reader.close()
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def _index_restored(self, inode: Inode) -> None:
        if inode.ttl >= 0:
            self.ttl_buckets.insert(inode.id, inode.creation_time_ms,
                                    inode.ttl)
        with self.registry_lock:
            self._inode_count += 1
            if inode.pinned:
                self.pinned_ids.add(inode.id)
            if inode.persistence_state == PersistenceState.TO_BE_PERSISTED:
                self.to_be_persisted_ids.add(inode.id)
            if inode.persistence_state == PersistenceState.LOST:
                self.lost_file_ids.add(inode.id)
            self._track_replication(inode)

    def _empty_snapshot(self) -> dict:
        return {"root_id": None, "inodes": []}
