"""Continuous health-rule engine ("cluster doctor").

Turns the metrics history (:mod:`alluxio_tpu.metrics.history`) into
ranked, firing/resolved alerts: each declarative rule watches a
windowed signal — sustained input-stall fraction, cache hit-ratio
drop, UFS-fetch error rate, hedge-win-rate spike, heartbeat staleness,
async-cache rejections, per-worker read-latency p99 regression — and
produces an :class:`Alert` with severity, evidence window and a
remediation hint.  Firing and resolution are debounced so a single
noisy sample can neither page nor un-page an operator.

The engine is the continuous counterpart of the point-in-time
``fsadmin doctor`` / ``fsadmin report stall`` checks: the subsystems
shipped before it (clairvoyant prefetch, hedged remote reads, striped
UFS fetch) only pay off if their effectiveness is *watched*, not
sampled by hand.
"""

from __future__ import annotations

import dataclasses
import logging
import statistics
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

LOG = logging.getLogger(__name__)

SEVERITIES = ("critical", "warning", "info")

#: sort rank: critical first
_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}


@dataclasses.dataclass
class Alert:
    rule: str
    severity: str
    subject: str          # "cluster", a source name, ...
    state: str            # pending | firing | resolved
    value: float
    threshold: float
    since: float          # first continuously-violating evaluation
    window_s: float
    summary: str
    remediation: str
    fired_at: Optional[float] = None
    resolved_at: Optional[float] = None
    evidence: dict = dataclasses.field(default_factory=dict)

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Violation:
    subject: str
    value: float
    summary: str
    evidence: dict = dataclasses.field(default_factory=dict)


class HealthContext:
    """What a rule may look at: the history store, the latest
    per-source snapshots, and 'now'."""

    def __init__(self, history, store, now: float,
                 expected_workers: Optional[
                     List[Tuple[str, float]]] = None) -> None:
        self.history = history
        self.store = store
        self.now = now
        #: (source, registered_for_s) for every LIVE registered worker
        #: — lets the staleness rule flag a worker whose metrics
        #: source expired from the snapshot store entirely (its
        #: metrics thread died while block heartbeats keep it
        #: registered), instead of silently self-resolving at the TTL
        self.expected_workers = expected_workers or []

    # -------------------------------------------------- history helpers
    def window_points(self, name: str, source: str,
                      window_s: float) -> List[Tuple[float, float]]:
        if self.history is None:
            return []
        return self.history.window(name, source, window_s, now=self.now)

    def window_mean(self, name: str, source: str,
                    window_s: float) -> Optional[float]:
        pts = self.window_points(name, source, window_s)
        if not pts:
            return None
        return sum(v for _, v in pts) / len(pts)

    def window_rate(self, name: str, source: str,
                    window_s: float) -> Optional[float]:
        """Counter increase per second across the window: total
        increase over total elapsed time, summing deltas across reset
        boundaries (a negative delta is a counter reset and contributes
        0).  NOT a mean of per-segment rates — equal weighting would
        let one increment landing in a short inter-heartbeat gap
        inflate the whole window's rate by orders of magnitude."""
        pts = self.window_points(name, source, window_s)
        if len(pts) < 2:
            return None
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            return None
        increase = 0.0
        prev = pts[0][1]
        for _, v in pts[1:]:
            if v > prev:
                increase += v - prev
            prev = v
        return increase / span

    def sources_for(self, name: str) -> List[str]:
        if self.history is None:
            return []
        return self.history.sources_for(name)

    # ---------------------------------------------------- store helpers
    def per_source(self, name: str) -> Dict[str, float]:
        """Latest value of ``name`` in every source's last snapshot
        (includes timer sub-metrics the Cluster.* aggregation skips)."""
        if self.store is None:
            return {}
        return self.store.per_source(name)

    def source_ages(self) -> Dict[str, float]:
        if self.store is None:
            return {}
        return self.store.sources()


class HealthRule:
    """One declarative rule.  ``probe`` returns the current violations;
    the engine owns the firing/resolved lifecycle."""

    def __init__(self, name: str, *, severity: str, window_s: float,
                 threshold: float, remediation: str, description: str,
                 probe: Callable[[HealthContext], List[Violation]],
                 fire_after_s: Optional[float] = None,
                 resolve_after_s: Optional[float] = None,
                 needs_history: bool = False) -> None:
        assert severity in SEVERITIES, severity
        self.name = name
        self.severity = severity
        self.window_s = window_s
        self.threshold = threshold
        self.remediation = remediation
        self.description = description
        self.probe = probe
        self.fire_after_s = fire_after_s      # None -> engine default
        self.resolve_after_s = resolve_after_s
        #: probe reads the metrics HISTORY (not just the snapshot
        #: store): with history disabled it would silently no-op, so
        #: the monitor must not advertise it as watching
        self.needs_history = needs_history

    def to_wire(self) -> dict:
        return {"name": self.name, "severity": self.severity,
                "window_s": self.window_s, "threshold": self.threshold,
                "description": self.description,
                "remediation": self.remediation}


def _worker_sources(ctx: HealthContext, metric: str) -> List[str]:
    return [s for s in ctx.sources_for(metric) if s.startswith("worker-")]


def default_rules(*, stall_threshold: float = 0.5,
                  stall_window_s: float = 60.0,
                  hit_ratio_floor: float = 0.5,
                  hit_ratio_min_bytes_per_s: float = float(1 << 20),
                  ufs_error_rate_per_s: float = 0.02,
                  hedge_win_ratio: float = 0.5,
                  hedge_min_rate_per_s: float = 0.05,
                  heartbeat_stale_s: float = 60.0,
                  missing_source_grace_s: float = 300.0,
                  async_reject_rate_per_s: float = 0.01,
                  p99_regression_factor: float = 3.0,
                  p99_floor_s: float = 0.001,
                  inode_lock_wait_p99_s: float = 0.05) -> List[HealthRule]:
    """The shipped rule catalog (thresholds are the documented
    defaults; docs/observability.md carries the operator table)."""

    def stall(ctx: HealthContext) -> List[Violation]:
        # per-client first — the subject names the loader to fix, and
        # raw client series tick at heartbeat granularity while the
        # Cluster.* mean is sampled coarser; fall back to the cluster
        # aggregate when no per-client series survived (e.g. the
        # series cap ate them)
        metric = "Client.InputBoundFraction"
        out = []
        for src in ctx.sources_for(metric):
            v = ctx.window_mean(metric, src, stall_window_s)
            if v is not None and v > stall_threshold:
                out.append(Violation(
                    src, v,
                    f"input-bound fraction {v:.2f} sustained over "
                    f"{stall_window_s:.0f}s (threshold {stall_threshold})",
                    {"metric": metric, "window_s": stall_window_s}))
        if out:
            return out
        metric = "Cluster.InputBoundFraction"
        v = ctx.window_mean(metric, "cluster", stall_window_s)
        if v is None or v <= stall_threshold:
            return []
        return [Violation(
            "cluster", v,
            f"input-bound fraction {v:.2f} sustained over "
            f"{stall_window_s:.0f}s (threshold {stall_threshold})",
            {"metric": metric, "window_s": stall_window_s})]

    def hit_ratio(ctx: HealthContext) -> List[Violation]:
        # the buckets Client.BytesRead.* actually records (HBM hits
        # never do a host read, so there is no .hbm byte counter)
        buckets = ("shm", "remote", "ufs", "unknown")
        rates = {}
        for b in buckets:
            r = ctx.window_rate(f"Cluster.BytesRead.{b}", "cluster",
                                stall_window_s)
            if r is not None:
                rates[b] = r
        total = sum(rates.values())
        if total < hit_ratio_min_bytes_per_s:
            return []  # idle cluster: a ratio of nothing is noise
        ratio = 1.0 - rates.get("ufs", 0.0) / total
        if ratio >= hit_ratio_floor:
            return []
        return [Violation(
            "cluster", ratio,
            f"cache hit ratio {ratio:.2f} below {hit_ratio_floor} "
            f"({rates.get('ufs', 0.0):.0f} B/s cold of "
            f"{total:.0f} B/s total)",
            {"metric": "Cluster.BytesRead.*", "rates": rates,
             "window_s": stall_window_s})]

    def ufs_errors(ctx: HealthContext) -> List[Violation]:
        out = []
        metric = "Worker.UfsFetchFailures"
        for src in _worker_sources(ctx, metric):
            r = ctx.window_rate(metric, src, 120.0)
            if r is not None and r > ufs_error_rate_per_s:
                out.append(Violation(
                    src, r,
                    f"UFS fetch failures at {r:.3f}/s on {src}",
                    {"metric": metric, "window_s": 120.0}))
        return out

    def hedge_spike(ctx: HealthContext) -> List[Violation]:
        hedges = ctx.window_rate("Cluster.RemoteReadHedges", "cluster",
                                 stall_window_s)
        wins = ctx.window_rate("Cluster.RemoteReadHedgeWins", "cluster",
                               stall_window_s)
        if not hedges or hedges < hedge_min_rate_per_s:
            return []
        ratio = (wins or 0.0) / hedges
        if ratio <= hedge_win_ratio:
            return []
        return [Violation(
            "cluster", ratio,
            f"hedged remote reads winning {100 * ratio:.0f}% of races "
            f"({hedges:.2f} hedges/s) — a straggling worker is "
            f"consistently losing",
            {"metric": "Cluster.RemoteReadHedge*",
             "hedges_per_s": hedges, "window_s": stall_window_s})]

    def stale_heartbeats(ctx: HealthContext) -> List[Violation]:
        # workers only: clients come and go with their jobs, and a
        # normal client exit must not read as "node dead" for the
        # whole source TTL
        out = []
        ages = ctx.source_ages()
        for src, age in ages.items():
            if src.startswith("worker-") and age > heartbeat_stale_s:
                out.append(Violation(
                    src, age,
                    f"no metrics heartbeat from {src} for {age:.0f}s",
                    {"stale_after_s": heartbeat_stale_s}))
        # a registered worker with NO snapshot at all: its metrics
        # thread died long enough ago that the source TTL'd out of
        # the store (block heartbeats keep it registered, so
        # worker-lost stays quiet) — the alert must not self-resolve
        # just because the evidence expired.  The grace period keeps
        # freshly-registered workers quiet until their first report
        # is overdue.
        for src, registered_for_s in ctx.expected_workers:
            if src in ages or registered_for_s < missing_source_grace_s:
                continue
            out.append(Violation(
                src, registered_for_s,
                f"registered worker {src} has no metrics snapshot "
                f"(last report expired from the store — its metrics "
                f"heartbeat thread is likely dead)",
                {"registered_for_s": registered_for_s,
                 "stale_after_s": heartbeat_stale_s}))
        return out

    def worker_lost(ctx: HealthContext) -> List[Violation]:
        # outlives heartbeat-staleness: once the block master declares
        # the worker lost, its snapshot is cleared (staleness goes
        # quiet) but the death must not silently read as OK — the
        # history end marker keeps this firing until the worker
        # re-registers or the marker ages out with history retention
        if ctx.history is None:
            return []
        out = []
        for src, ended in ctx.history.ended_sources(now=ctx.now).items():
            if not src.startswith("worker-"):
                continue
            age = max(0.0, ctx.now - ended)
            out.append(Violation(
                src, age,
                f"{src} was declared lost {age:.0f}s ago and has not "
                f"re-registered",
                {"ended_at": ended}))
        return out

    def async_rejected(ctx: HealthContext) -> List[Violation]:
        out = []
        metric = "Worker.AsyncCacheRejected"
        for src in _worker_sources(ctx, metric):
            r = ctx.window_rate(metric, src, 120.0)
            if r is not None and r > async_reject_rate_per_s:
                out.append(Violation(
                    src, r,
                    f"async cache-fill requests rejected at {r:.3f}/s "
                    f"on {src} (queue saturated)",
                    {"metric": metric, "window_s": 120.0}))
        return out

    def p99_regression(ctx: HealthContext) -> List[Violation]:
        metric = "Worker.ReadBlockTime.p99"
        per = {s: v for s, v in ctx.per_source(metric).items()
               if s.startswith("worker-")}
        if len(per) < 2:
            return []  # no fleet to regress against
        med = statistics.median(per.values())
        out = []
        for src, v in per.items():
            # the absolute floor gates the OUTLIER, not the median: a
            # fast memory-serving fleet (median far below the floor)
            # must still flag a worker regressing to disk-bound
            # latencies, while sub-floor noise on an idle fleet stays
            # quiet
            if v <= p99_floor_s or v <= med * p99_regression_factor:
                continue
            ratio = v / med if med > 0 else float(p99_regression_factor)
            # value is the regression RATIO — same unit as the
            # factor threshold, or _rank inverts the ordering
            out.append(Violation(
                src, ratio,
                f"warm read p99 {1e3 * v:.1f}ms/MiB on {src} is "
                f"{ratio:.1f}x the fleet median "
                f"({1e3 * med:.1f}ms/MiB)",
                {"metric": metric, "fleet_median_s": med,
                 "p99_s": v}))
        return out

    def metadata_lock_contention(ctx: HealthContext) -> List[Violation]:
        # the master self-samples this series on the health tick
        # (process._sample_metadata_history) — sustained inode-lock
        # acquisition p99 means the striped metadata control plane is
        # convoying (hot directory, coarse-fallback storm, or a slow
        # journal flusher backing up writers)
        metric = "Master.MetadataInodeLockWaitTime.p99"
        v = ctx.window_mean(metric, "master", stall_window_s)
        if v is None or v <= inode_lock_wait_p99_s:
            return []
        return [Violation(
            "master", v,
            f"inode-lock acquisition p99 {1e3 * v:.1f}ms sustained over "
            f"{stall_window_s:.0f}s (threshold "
            f"{1e3 * inode_lock_wait_p99_s:.0f}ms)",
            {"metric": metric, "p99_s": v,
             "threshold_s": inode_lock_wait_p99_s})]

    return [
        HealthRule(
            "metadata-lock-contention", severity="warning",
            window_s=stall_window_s, threshold=inode_lock_wait_p99_s,
            probe=metadata_lock_contention, needs_history=True,
            description="metadata operations queue on inode path locks",
            remediation="find the hot directory (spread writers across "
                        "subtrees), check journal flush latency "
                        "(Master.MetadataJournalFlushTime), and see "
                        "docs/metadata.md for the locking model"),
        HealthRule(
            "input-stall-sustained", severity="critical",
            window_s=stall_window_s, threshold=stall_threshold,
            probe=stall, needs_history=True,
            description="loaders spend most of their wall time waiting "
                        "for input",
            remediation="run `fsadmin report stall` for the tier "
                        "verdict; warm the cache or enable clairvoyant "
                        "prefetch (atpu.prefetch.*)"),
        HealthRule(
            "cache-hit-ratio-drop", severity="warning",
            window_s=stall_window_s, threshold=hit_ratio_floor,
            probe=hit_ratio, needs_history=True,
            description="cold UFS bytes are displacing cached reads",
            remediation="check eviction pressure (worker capacity) and "
                        "prefetch coverage; see docs/ufs_cold_reads.md"),
        HealthRule(
            "ufs-fetch-errors", severity="critical", window_s=120.0,
            threshold=ufs_error_rate_per_s, probe=ufs_errors,
            needs_history=True,
            description="a worker's striped UFS fetches are failing",
            remediation="inspect the worker's log and UFS "
                        "credentials/quotas; stripes retry once then "
                        "fail the read"),
        HealthRule(
            "hedge-win-rate-spike", severity="warning",
            window_s=stall_window_s, threshold=hedge_win_ratio,
            probe=hedge_spike, needs_history=True,
            description="hedged remote reads keep beating the primary "
                        "replica",
            remediation="a worker is straggling: check its host load "
                        "and NIC; see docs/remote_reads.md"),
        HealthRule(
            "heartbeat-staleness", severity="warning",
            window_s=heartbeat_stale_s, threshold=heartbeat_stale_s,
            probe=stale_heartbeats, fire_after_s=0.0,
            description="a node stopped shipping metrics heartbeats",
            remediation="node dead or partitioned: check the process "
                        "and the master address it is configured with"),
        HealthRule(
            "worker-lost", severity="critical", window_s=0.0,
            threshold=0.0, probe=worker_lost, needs_history=True,
            fire_after_s=0.0,
            description="the block master declared a worker lost and "
                        "it has not come back",
            remediation="restart the worker or remove it from the "
                        "fleet; the alert ages out with history "
                        "retention (atpu.master.metrics.history."
                        "retention) or resolves on re-registration"),
        HealthRule(
            "async-cache-rejected", severity="warning", window_s=120.0,
            threshold=async_reject_rate_per_s,
            probe=async_rejected, needs_history=True,
            description="worker async cache-fill queue is saturated",
            remediation="raise atpu.worker.async.cache.queue.max / "
                        ".threads, or slow the prefetch agent"),
        HealthRule(
            "read-latency-p99-regression", severity="warning",
            window_s=0.0, threshold=p99_regression_factor,
            probe=p99_regression,
            description="one worker's read p99 regressed vs the fleet "
                        "median",
            remediation="compare the worker's host (CPU steal, disk, "
                        "GC pauses) against its peers; drain it if it "
                        "cannot keep up"),
    ]


def tenant_overload_rule(shed_counts_fn: Callable[[], Dict[str, int]],
                         *, shed_rate_per_s: float = 1.0,
                         window_s: float = 60.0) -> HealthRule:
    """Flags a principal whose master RPCs are being shed at a
    sustained rate — i.e. a tenant exceeding its admission-control
    share.  ``shed_counts_fn`` is the admission controller's
    ``shed_counts`` (principal -> cumulative shed count); the probe
    derives per-principal rates by diffing successive snapshots, so it
    needs neither the history store nor per-principal metric series
    (which would mint attacker-controlled cardinality)."""
    state = {"prev": {}, "at": None}
    #: probes closer together than this keep the previous baseline: a
    #: query-driven evaluate() (fsadmin report health) landing 0.3s
    #: after the heartbeat tick must not turn 2 shed RPCs into a
    #: 6.7/s "flood"
    MIN_PROBE_WINDOW_S = 1.0

    def probe(ctx: HealthContext) -> List[Violation]:
        try:
            counts = shed_counts_fn()
        except Exception:  # noqa: BLE001 - never take the doctor down
            LOG.debug("tenant-overload probe failed", exc_info=True)
            return []
        prev, at = state["prev"], state["at"]
        if at is not None and ctx.now - at < MIN_PROBE_WINDOW_S:
            return []  # too soon: keep the baseline, rate another day
        state["prev"], state["at"] = dict(counts), ctx.now
        if at is None:
            return []  # first probe: no baseline to rate against
        dt = ctx.now - at
        if dt <= 0:
            return []
        out = []
        for principal, shed in counts.items():
            rate = (shed - prev.get(principal, 0)) / dt
            if rate > shed_rate_per_s:
                out.append(Violation(
                    f"tenant:{principal}", rate,
                    f"principal {principal!r} is being shed "
                    f"{rate:.1f} master RPCs/s — it is flooding past "
                    f"its admission rate",
                    {"shed_total": shed, "window_s": dt}))
        return out

    return HealthRule(
        "tenant-over-share", severity="warning", window_s=window_s,
        threshold=shed_rate_per_s, probe=probe,
        description="one principal's master RPCs are being shed at a "
                    "sustained rate (admission control)",
        remediation="the tenant is flooding: check its job config, "
                    "raise atpu.master.rpc.admission.rate if the "
                    "fleet genuinely grew, or leave the shedding in "
                    "place — victims are already protected; see "
                    "`fsadmin report qos` and docs/qos.md")


def quorum_degraded_rule(expected: int, *,
                         window_s: float = 30.0) -> HealthRule:
    """Fires while fewer masters than configured are alive in the HA
    quorum (``Master.HaQuorumLive`` vs ``Master.HaQuorumExpected``,
    sampled by the primary on the health tick — docs/ha.md).  A lost
    standby costs nothing *now*; the alert exists because the next
    failure is the outage — and the remediation timeline can show the
    operator exactly when redundancy was lost."""

    def probe(ctx: HealthContext) -> List[Violation]:
        live = ctx.window_mean("Master.HaQuorumLive", "master", window_s)
        if live is None:
            return []
        want = ctx.window_mean("Master.HaQuorumExpected", "master",
                               window_s) or float(expected)
        if live >= want - 0.5:  # mean over a window: tolerate one blip
            return []
        return [Violation(
            "master-quorum", live,
            f"only {live:.1f} of {want:.0f} masters alive in the HA "
            f"quorum — failover margin degraded",
            {"metric": "Master.HaQuorumLive", "window_s": window_s,
             "expected": want})]

    return HealthRule(
        "master-quorum-degraded", severity="warning",
        window_s=window_s, threshold=float(expected), probe=probe,
        needs_history=True,
        description="fewer masters than configured are alive in the "
                    "HA quorum",
        remediation="restart the dead master (or replace the host): "
                    "`fsadmin report masters` names the missing "
                    "member; while degraded, another failure can take "
                    "the namespace down — see docs/ha.md")


def metastore_compaction_debt_rule(max_runs: int = 24, *,
                                   window_s: float = 60.0) -> HealthRule:
    """Fires while the LSM metastore's sorted-run count stays above the
    configured debt threshold (``Master.MetastoreRuns``, sampled on the
    health tick).  Every point lookup probes each run's bloom filter and
    every listing merges all runs, so an ever-growing run count means
    compaction is losing the race with flushes — reads degrade first,
    then disk fills with un-merged duplicates.  HEAP/SQLITE backends
    report zero runs, keeping the rule inert there."""

    def probe(ctx: HealthContext) -> List[Violation]:
        runs = ctx.window_mean("Master.MetastoreRuns", "master", window_s)
        if runs is None or runs <= float(max_runs):
            return []
        return [Violation(
            "master-metastore", runs,
            f"LSM metastore carries {runs:.0f} sorted runs (threshold "
            f"{max_runs}) — compaction is not keeping up with flushes",
            {"metric": "Master.MetastoreRuns", "window_s": window_s,
             "threshold": max_runs})]

    return HealthRule(
        "metastore-compaction-debt", severity="warning",
        window_s=window_s, threshold=float(max_runs), probe=probe,
        needs_history=True,
        description="the LSM metastore's sorted-run count is sustained "
                    "above the compaction-debt threshold",
        remediation="compaction is starved or wedged: check master CPU "
                    "headroom and the metastore disk, lower "
                    "atpu.master.metastore.lsm.memtable.bytes churn or "
                    "raise atpu.master.metastore.compaction.debt.runs "
                    "if the namespace genuinely grew; see "
                    "`fsadmin report metastore` and docs/metadata.md")


class _Tracked:
    __slots__ = ("alert", "clean_since", "clean_observed_s")

    def __init__(self, alert: Alert, now: float) -> None:
        self.alert = alert
        #: first evaluation that observed the rule clean (None while
        #: violating) — resolution debounces on *observed* clean time,
        #: not wall time since the last violation, so a gap between
        #: evaluations cannot count as a clean streak nobody watched
        self.clean_since: Optional[float] = None
        #: accumulated clean time the evaluator actually watched: the
        #: sum of inter-evaluation gaps with clean observations at both
        #: ends, each capped near the evaluation cadence — a stalled
        #: heartbeat's unobserved span resolves nothing
        self.clean_observed_s: float = 0.0


class HealthMonitor:
    """Evaluates the rule catalog on a heartbeat; owns alert lifecycle.

    pending --(violated >= fire_after)--> firing
    firing --(clean >= resolve_after)--> resolved (kept in a ring)
    pending --(clean once)--> dropped silently
    """

    def __init__(self, metrics_master, *,
                 rules: Optional[List[HealthRule]] = None,
                 fire_after_s: float = 30.0,
                 resolve_after_s: float = 60.0,
                 eval_interval_s: Optional[float] = None,
                 worker_sources_fn: Optional[Callable[
                     [], List[Tuple[str, float]]]] = None,
                 clock: Callable[[], float] = time.time,
                 registry=None) -> None:
        self._mm = metrics_master
        #: returns (source, registered_for_s) for live registered
        #: workers; feeds HealthContext.expected_workers
        self._worker_sources_fn = worker_sources_fn
        self.rules = rules if rules is not None else default_rules()
        self.fire_after_s = fire_after_s
        self.resolve_after_s = resolve_after_s
        self._clock = clock
        #: called after every evaluation with (firing_alerts, now) —
        #: OUTSIDE the monitor lock, so a listener may query the
        #: monitor.  The remediation engine subscribes here.
        self.alert_listeners: List[Callable[[List[Alert], float],
                                            None]] = []
        self._tracked: Dict[Tuple[str, str], _Tracked] = {}
        self._resolved: deque = deque(maxlen=50)
        self._lock = threading.Lock()
        self._eval_gate = threading.Lock()  # query-driven eval rate limit
        self._last_eval: float = 0.0
        #: counted-clean-gap ceiling (see _Tracked.clean_observed_s);
        #: 3x the heartbeat period tolerates jitter, None = uncapped
        #: (callers that drive evaluate() themselves, e.g. tests)
        self._clean_gap_cap_s = 3.0 * eval_interval_s \
            if eval_interval_s else None
        if registry is None:
            from alluxio_tpu.metrics import metrics

            registry = metrics()
        registry.register_gauge("Master.Health.AlertsFiring",
                                lambda: float(len(self.firing())))
        self._eval_timer = registry.timer("Master.Health.EvalTime")

    # ---------------------------------------------------------- evaluate
    def evaluate(self, now: Optional[float] = None) -> List[Alert]:
        """One evaluation pass; returns the currently-firing alerts."""
        from alluxio_tpu.utils.tracing import tracer

        ts = self._clock() if now is None else now
        with tracer().span("atpu.master.health.evaluate"), \
                self._eval_timer.time():
            if self._mm is not None:
                self._mm.drain_history(now=ts)
            expected = None
            if self._worker_sources_fn is not None:
                try:
                    expected = self._worker_sources_fn()
                except Exception:  # noqa: BLE001 - never take the
                    # doctor down over a topology read
                    LOG.debug("worker-topology read failed", exc_info=True)
            ctx = HealthContext(
                getattr(self._mm, "history", None),
                getattr(self._mm, "store", None), ts,
                expected_workers=expected)
            with self._lock:
                for rule in self.rules:
                    try:
                        violations = rule.probe(ctx)
                    except Exception:  # noqa: BLE001 - a broken rule
                        # must not take the doctor down
                        LOG.warning("health rule %s failed",
                                    rule.name, exc_info=True)
                        continue
                    self._apply(rule, violations, ts)
                self._last_eval = ts
                firing = [t.alert for t in self._tracked.values()
                          if t.alert.state == "firing"]
            for listener in self.alert_listeners:
                try:
                    listener(firing, ts)
                except Exception:  # noqa: BLE001 - a broken actor must
                    # not take the doctor down either
                    LOG.warning("health alert listener failed",
                                exc_info=True)
            return firing

    def _apply(self, rule: HealthRule,
               violations: List[Violation], now: float) -> None:
        fire_after = rule.fire_after_s if rule.fire_after_s is not None \
            else self.fire_after_s
        resolve_after = rule.resolve_after_s \
            if rule.resolve_after_s is not None else self.resolve_after_s
        seen = set()
        for v in violations:
            key = (rule.name, v.subject)
            seen.add(key)
            t = self._tracked.get(key)
            if t is None:
                t = self._tracked[key] = _Tracked(Alert(
                    rule=rule.name, severity=rule.severity,
                    subject=v.subject, state="pending", value=v.value,
                    threshold=rule.threshold, since=now,
                    window_s=rule.window_s, summary=v.summary,
                    remediation=rule.remediation,
                    evidence=v.evidence), now)
            t.clean_since = None
            t.alert.value = v.value
            t.alert.summary = v.summary
            t.alert.evidence = v.evidence
            if t.alert.state == "pending" and \
                    now - t.alert.since >= fire_after:
                t.alert.state = "firing"
                t.alert.fired_at = now
        # lifecycle for tracked alerts this rule did NOT re-violate
        for key in [k for k in self._tracked if k[0] == rule.name
                    and k not in seen]:
            t = self._tracked[key]
            if t.alert.state == "pending":
                del self._tracked[key]  # debounce ate the blip
                continue
            if t.clean_since is None:
                t.clean_since = now
                t.clean_observed_s = 0.0
            else:
                # _last_eval still holds the PREVIOUS evaluation's ts
                # (evaluate() stamps it after the rule loop)
                gap = now - self._last_eval
                if self._clean_gap_cap_s is not None:
                    gap = min(gap, self._clean_gap_cap_s)
                t.clean_observed_s += max(0.0, gap)
            if t.clean_observed_s >= resolve_after:
                t.alert.state = "resolved"
                t.alert.resolved_at = now
                self._resolved.append(t.alert)
                del self._tracked[key]

    # ------------------------------------------------------------ report
    def firing(self) -> List[Alert]:
        with self._lock:
            return [t.alert for t in self._tracked.values()
                    if t.alert.state == "firing"]

    @staticmethod
    def _rank(a: Alert) -> tuple:
        sev = _SEV_RANK.get(a.severity, len(SEVERITIES))
        # severity of the violation = how far the value sits from the
        # threshold in WHICHEVER direction the rule fires (hit-ratio
        # violates below its floor: ratio 0.05 must outrank 0.45)
        if not a.threshold:
            over = a.value
        elif a.value > a.threshold:
            over = a.value / a.threshold
        elif a.value > 0:
            over = a.threshold / a.value
        else:
            over = float("inf")
        return (sev, -over, a.rule, a.subject)

    #: query-driven evaluations (get_health RPC, /api/v1/master/health)
    #: within this of the last pass serve the existing lifecycle state:
    #: a dashboard refresh storm must not repeat the O(series) probe
    #: scans per request, and at most this much staleness is invisible
    #: next to fire_after/resolve_after debounce
    QUERY_EVAL_MIN_INTERVAL_S = 1.0

    def fresh_report(self, evaluate: bool = True) -> dict:
        """Evaluate-then-report, shared by the RPC and web surfaces so
        neither serves a stale lifecycle state (rate-limited — the
        periodic heartbeat is the workhorse, queries only top up).
        The gate serializes concurrent queries: one evaluates, the
        rest wait and see the fresh ``_last_eval``."""
        if evaluate:
            with self._eval_gate:
                if self._clock() - self._last_eval >= \
                        self.QUERY_EVAL_MIN_INTERVAL_S:
                    self.evaluate()
        return self.report()

    def report(self) -> dict:
        """Ranked wire view: what `fsadmin report health` and
        /api/v1/master/health serve."""
        with self._lock:
            firing = sorted(
                (t.alert for t in self._tracked.values()
                 if t.alert.state == "firing"), key=self._rank)
            pending = sorted(
                (t.alert for t in self._tracked.values()
                 if t.alert.state == "pending"), key=self._rank)
            resolved = list(self._resolved)[-10:]
            status = "OK"
            if any(a.severity == "warning" for a in firing):
                status = "WARN"
            if any(a.severity == "critical" for a in firing):
                status = "CRITICAL"
            return {
                "status": status,
                "evaluated_at": self._last_eval,
                "alerts": [a.to_wire() for a in firing],
                "pending": [a.to_wire() for a in pending],
                "recently_resolved": [a.to_wire() for a in
                                      reversed(resolved)],
                "rules": [r.to_wire() for r in self.rules],
            }
