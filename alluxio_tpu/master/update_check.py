"""Update checker (reference: ``core/server/master/src/main/java/
alluxio/master/meta/UpdateChecker.java`` — the periodic "is a newer
version available" heartbeat).

Departures, on purpose: OFF by default (phone-home from a storage
master is opt-in here, where the reference ships it enabled), and the
check endpoint is a plain JSON document (``{"latest": "x.y.z"}``) at a
configurable URL rather than a hardcoded vendor service — clusters can
point it at an internal mirror.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Optional, Tuple

from alluxio_tpu import __version__
from alluxio_tpu.heartbeat import HeartbeatExecutor

LOG = logging.getLogger(__name__)


def _parse_version(v: str, width: int = 4) -> Tuple[int, ...]:
    """Zero-padded to ``width`` components so "1.0" == "1.0.0"."""
    parts = []
    for tok in v.strip().split("."):
        num = ""
        for ch in tok:
            if not ch.isdigit():
                break
            num += ch
        parts.append(int(num) if num else 0)
    return tuple((parts + [0] * width)[:width])


class UpdateChecker(HeartbeatExecutor):
    """One tick = one version probe; failures are logged-and-ignored
    (a storage master must never degrade because a version endpoint
    is down)."""

    def __init__(self, check_url: str, *,
                 current_version: str = __version__,
                 timeout_s: float = 10.0) -> None:
        self._url = check_url
        self._timeout = timeout_s
        self.current_version = current_version
        self.latest_version: Optional[str] = None
        self.update_available = False

    def heartbeat(self) -> None:
        if not self._url:
            return
        try:
            with urllib.request.urlopen(self._url,
                                        timeout=self._timeout) as r:
                doc = json.loads(r.read() or b"{}")
            latest = str(doc.get("latest", "")).strip() \
                if isinstance(doc, dict) else ""
        except Exception as e:  # noqa: BLE001 advisory only
            LOG.debug("update check against %s failed: %s",
                      self._url, e)
            return
        if not latest:
            return
        self.latest_version = latest
        newer = _parse_version(latest) > _parse_version(
            self.current_version)
        if newer and not self.update_available:
            LOG.info("a newer alluxio-tpu release is available: "
                     "%s (running %s)", latest, self.current_version)
        self.update_available = newer
