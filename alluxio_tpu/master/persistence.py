"""Async-persist scheduling: drain requests into job-service persist jobs.

Re-design of the PersistenceScheduler/PersistenceChecker heartbeats in
``core/server/master/src/main/java/alluxio/master/file/
DefaultFileSystemMaster.java:3810,4001``: files completed with
ASYNC_THROUGH land in the FSM's persist-request queue; each tick this
scheduler submits a ``persist`` plan per request, then tracks
outstanding jobs — failed jobs are retried (bounded), completed ones
are dropped (the plan itself marks the inode persisted).

Requests are tracked by INODE ID, not path (reference ``PersistJob``
is fileId-keyed): the path is re-resolved at every submission, so a
file renamed between completion and persist is persisted at its
CURRENT path — a path-keyed queue silently lost durability on rename
and could resurrect the old path in the UFS via the failed job's
parent mkdirs (observed as a ghost ``/cp`` directory after
``mv /cp /moved`` raced the scheduler).
"""

from __future__ import annotations

import logging
from typing import Dict, Tuple

LOG = logging.getLogger(__name__)


class PersistenceScheduler:
    MAX_ATTEMPTS = 3

    def __init__(self, fs_master, job_client) -> None:
        self._fsm = fs_master
        self._jobs = job_client
        #: job_id -> (inode_id, attempt)
        self._inflight: Dict[int, Tuple[int, int]] = {}
        #: inode_id -> attempt number for the next submission
        self._pending: Dict[int, int] = {}

    def heartbeat(self) -> None:
        self._check_inflight()
        for inode_id in self._fsm.pop_persist_requests():
            self._pending.setdefault(inode_id, 1)
        self._submit_pending()

    def _submit_pending(self) -> None:
        for inode_id, attempt in list(self._pending.items()):
            path = self._fsm.current_path_of(inode_id)
            if path is None:
                # deleted since scheduling: nothing left to persist
                LOG.debug("persist of inode %d dropped: gone", inode_id)
                del self._pending[inode_id]
                continue
            try:
                job_id = self._jobs.run({"type": "persist",
                                         "path": str(path),
                                         "inode_id": inode_id})
            except Exception:  # noqa: BLE001 job master down: stays
                LOG.debug("persist submit failed for %s", path,
                          exc_info=True)
                continue  # pending; next tick re-resolves and retries
            del self._pending[inode_id]
            self._inflight[job_id] = (inode_id, attempt)

    def _check_inflight(self) -> None:
        for job_id in list(self._inflight):
            inode_id, attempt = self._inflight[job_id]
            try:
                info = self._jobs.get_status(job_id)
            except Exception:  # noqa: BLE001 transient: retry next tick
                LOG.debug("persist job %s status probe failed",
                          job_id, exc_info=True)
                continue
            if info.status == "COMPLETED":
                del self._inflight[job_id]
            elif info.status in ("FAILED", "CANCELED"):
                del self._inflight[job_id]
                if attempt < self.MAX_ATTEMPTS:
                    LOG.warning("persist of inode %d failed (attempt "
                                "%d): %s — retrying", inode_id, attempt,
                                info.error_message)
                    self._pending[inode_id] = attempt + 1
                else:
                    LOG.error("persist of inode %d failed after %d "
                              "attempts: %s", inode_id, attempt,
                              info.error_message)

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)
