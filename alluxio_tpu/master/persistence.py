"""Async-persist scheduling: drain requests into job-service persist jobs.

Re-design of the PersistenceScheduler/PersistenceChecker heartbeats in
``core/server/master/src/main/java/alluxio/master/file/
DefaultFileSystemMaster.java:3810,4001``: files completed with
ASYNC_THROUGH land in the FSM's persist-request queue; each tick this
scheduler submits a ``persist`` plan per request, then tracks outstanding
jobs — failed jobs are re-queued (bounded retries), completed ones are
dropped (the plan itself marks the inode persisted).
"""

from __future__ import annotations

import logging
from typing import Dict, Tuple

LOG = logging.getLogger(__name__)


class PersistenceScheduler:
    MAX_ATTEMPTS = 3

    def __init__(self, fs_master, job_client) -> None:
        self._fsm = fs_master
        self._jobs = job_client
        #: job_id -> (path, attempt)
        self._inflight: Dict[int, Tuple[str, int]] = {}
        #: path -> attempt count for requeues
        self._attempts: Dict[str, int] = {}

    def heartbeat(self) -> None:
        self._check_inflight()
        self._submit_new()

    def _submit_new(self) -> None:
        for _inode_id, path in self._fsm.pop_persist_requests().items():
            attempt = self._attempts.get(path, 0) + 1
            try:
                job_id = self._jobs.run({"type": "persist", "path": path})
            except Exception:  # noqa: BLE001 job master down: requeue
                LOG.debug("persist submit failed for %s", path,
                          exc_info=True)
                self._requeue(path)
                continue
            self._inflight[job_id] = (path, attempt)
            self._attempts[path] = attempt

    def _check_inflight(self) -> None:
        for job_id in list(self._inflight):
            path, attempt = self._inflight[job_id]
            try:
                info = self._jobs.get_status(job_id)
            except Exception:  # noqa: BLE001 transient: retry next tick
                continue
            if info.status == "COMPLETED":
                del self._inflight[job_id]
                self._attempts.pop(path, None)
            elif info.status in ("FAILED", "CANCELED"):
                del self._inflight[job_id]
                if attempt < self.MAX_ATTEMPTS:
                    LOG.warning("persist of %s failed (attempt %d): %s — "
                                "requeueing", path, attempt,
                                info.error_message)
                    self._requeue(path)
                else:
                    LOG.error("persist of %s failed after %d attempts: %s",
                              path, attempt, info.error_message)
                    self._attempts.pop(path, None)

    def _requeue(self, path: str) -> None:
        try:
            self._fsm.schedule_async_persistence(path)
        except Exception:  # noqa: BLE001 deleted file / closing journal
            LOG.debug("requeue of %s dropped", path, exc_info=True)

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)
