"""Self-healing remediation engine: bounded, audited actions on alerts.

The health engine (:mod:`alluxio_tpu.master.health`) diagnoses; this
module closes the loop.  It subscribes to the monitor's firing alerts
and executes a small catalog of **bounded** actions:

- **quarantine** — a worker flagged by the heartbeat-staleness or
  read-latency-p99-regression rule stops receiving new block
  placements and prefetch targets (the block master's placement
  listing filters it); released automatically after the alert
  resolves and a probation period passes;
- **hot-block re-replication** — a p99-regressed worker's hottest
  blocks (its top-tier residents) get one extra replica through the
  replication checker / job service, so reads drain away from the
  straggler without waiting for it to die;
- **adaptive retuning** — sustained hedge-win-rate or input-stall
  alerts push new hedge-quantile / stripe-concurrency /
  prefetch-byte-budget values to clients as a config overlay
  piggybacked on the metrics-heartbeat response, reverting when the
  alert clears.

Safety is the design center, not an afterthought: every action obeys a
per-(kind, subject) **cooldown** and a sliding-window **action cap**;
``dry.run`` audits what would happen without doing any of it; and
every action — including every *suppressed* one — lands in a bounded
audit ring, a trace span, and ``Master.Remediation*`` metrics-history
series, so ``fsadmin report health`` can render the full
cause → action → resolution timeline.  With
``atpu.master.remediation.enabled=false`` (the default) the engine is
never constructed and the cluster behaves exactly as before.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

LOG = logging.getLogger(__name__)

ACTION_QUARANTINE = "quarantine"
ACTION_REREPLICATE = "re-replicate"
ACTION_RETUNE = "retune"
ACTION_RELEASE = "release"
ACTION_REVERT = "revert"

#: rules whose worker subject gets quarantined
QUARANTINE_RULES = ("heartbeat-staleness", "read-latency-p99-regression")
#: rules whose worker subject gets its hot blocks re-replicated
REREPLICATE_RULES = ("read-latency-p99-regression",)

#: conf keys the retuning overlay may push (the client clamps again on
#: its side — a wild master cannot push a client off a cliff)
OVERLAY_HEDGE_QUANTILE = "atpu.user.remote.read.hedge.quantile"
OVERLAY_REMOTE_CONCURRENCY = "atpu.user.remote.read.concurrency"
OVERLAY_PREFETCH_BUDGET = "atpu.prefetch.budget.bytes"


@dataclasses.dataclass
class AuditRecord:
    """One row of the cause → action → resolution timeline."""

    id: int
    at: float
    action: str           # quarantine | re-replicate | retune | release | revert
    rule: str             # the alert rule that caused it
    subject: str          # the alert subject it acted on
    outcome: str          # executed | dry-run | suppressed-cap |
    #                       suppressed-cooldown | skipped | failed
    summary: str
    detail: dict = dataclasses.field(default_factory=dict)
    #: when the triggering alert stopped firing (None while it burns)
    resolved_at: Optional[float] = None
    #: when the action was undone (quarantine released / overlay
    #: reverted); one-shot actions (re-replication) never set it
    reverted_at: Optional[float] = None

    def to_wire(self) -> dict:
        return dataclasses.asdict(self)


class _Active:
    """A reversible action currently in force (quarantine / overlay)."""

    __slots__ = ("record", "holders", "probation_since", "worker_id")

    def __init__(self, record: AuditRecord, holders: set,
                 worker_id: Optional[int] = None) -> None:
        self.record = record
        #: (rule, subject) alert keys keeping the action in force
        self.holders = holders
        #: first evaluation that saw every holder resolved
        self.probation_since: Optional[float] = None
        self.worker_id = worker_id


class RemediationEngine:
    """Subscribes to :class:`HealthMonitor` evaluations and acts.

    ``block_master`` is duck-typed (quarantine_worker / release_worker /
    get_worker_infos / get_worker) so benches and unit tests can drive
    the engine against a stub on a fake clock.
    """

    AUDIT_CAPACITY = 256

    def __init__(self, block_master, *, metrics_master=None,
                 dry_run: bool = False,
                 max_actions_per_window: int = 4,
                 window_s: float = 600.0,
                 cooldown_s: float = 300.0,
                 probation_s: float = 60.0,
                 rereplicate_blocks: int = 8,
                 quarantine_max_fraction: float = 0.5,
                 hedge_quantile_base: float = 0.95,
                 remote_concurrency_base: int = 4,
                 prefetch_budget_base: int = 256 << 20,
                 clock: Callable[[], float] = time.time,
                 registry=None) -> None:
        self._bm = block_master
        self._mm = metrics_master
        self.dry_run = bool(dry_run)
        self.max_actions_per_window = max(0, int(max_actions_per_window))
        self.window_s = float(window_s)
        self.cooldown_s = float(cooldown_s)
        self.probation_s = float(probation_s)
        self.rereplicate_blocks = max(1, int(rereplicate_blocks))
        self.quarantine_max_fraction = min(
            1.0, max(0.0, float(quarantine_max_fraction)))
        self._bases = {
            OVERLAY_HEDGE_QUANTILE: float(hedge_quantile_base),
            OVERLAY_REMOTE_CONCURRENCY: int(remote_concurrency_base),
            OVERLAY_PREFETCH_BUDGET: int(prefetch_budget_base),
        }
        self._clock = clock
        self._replication = None
        self._lock = threading.Lock()
        self._audit: deque = deque(maxlen=self.AUDIT_CAPACITY)
        self._next_id = 1
        #: executed/dry-run action timestamps inside the cap window
        self._window: deque = deque()
        #: (kind, subject) -> last attempt ts (cooldown anchor)
        self._last_attempt: Dict[Tuple[str, str], float] = {}
        #: (kind, subject, reason) -> ts of the last suppression we
        #: audited — one audit row per suppression episode, not one per
        #: evaluation tick
        self._suppression_logged: Dict[Tuple[str, str, str], float] = {}
        #: reversible actions in force
        self._active: Dict[Tuple[str, str], _Active] = {}
        #: the pushed overlay, rebuilt on change; heartbeat handlers
        #: read the reference without taking the engine lock
        self._overlay_wire: Dict[str, object] = {}
        self.overlay_version = 0
        #: history sampling is change-driven with a periodic keepalive:
        #: ingesting 4 series on EVERY health tick costs more than the
        #: whole idle remediation pass (measured ~40us vs ~20us) and
        #: would blow the <2% tick budget bench-selfheal gates
        self._history_dirty = True
        self._last_history_sample = float("-inf")
        self.HISTORY_KEEPALIVE_S = 300.0
        if registry is None:
            from alluxio_tpu.metrics import metrics

            registry = metrics()
        self._c_actions = registry.counter("Master.RemediationActions")
        self._c_dry = registry.counter("Master.RemediationDryRun")
        self._c_suppressed = registry.counter(
            "Master.RemediationSuppressed")
        self._c_failed = registry.counter("Master.RemediationFailed")
        registry.register_gauge(
            "Master.RemediationQuarantined",
            lambda: float(sum(1 for k in self._active
                              if k[0] == ACTION_QUARANTINE)))
        registry.register_gauge(
            "Master.RemediationOverlayKeys",
            lambda: float(len(self._overlay_wire)))

    # ----------------------------------------------------------- wiring
    def bind_replication(self, checker) -> None:
        """Late-bound like the replication heartbeat itself: the job
        service boots after the metadata master."""
        self._replication = checker

    def heartbeat_overlay(self) -> Tuple[Dict[str, object], int]:
        """(overlay, version) for the metrics-heartbeat response; lock-
        free — the dict reference is swapped atomically on change."""
        return self._overlay_wire, self.overlay_version

    # ------------------------------------------------------------- tick
    def on_alerts(self, alerts: List, now: Optional[float] = None) -> None:
        """One remediation pass over the monitor's firing alerts —
        registered as a HealthMonitor alert listener, so it runs right
        after every evaluation with that evaluation's timestamp."""
        ts = self._clock() if now is None else now
        if not alerts and not self._active:
            # quiet cluster: keep the tick tax near zero (no span —
            # nothing to trace), but still sweep bookkeeping and emit
            # the keepalive history sample
            with self._lock:
                self._prune_window(ts)
                self._sample_history(ts)
            return
        import contextlib

        from alluxio_tpu.utils.tracing import tracer

        t = tracer()
        span = t.span("atpu.master.remediation.evaluate") if t.enabled \
            else contextlib.nullcontext()
        with span, self._lock:
            self._prune_window(ts)
            firing = {(a.rule, a.subject) for a in alerts}
            for a in alerts:
                self._consider(a, ts)
            self._sweep_resolved(firing, ts)
            self._sample_history(ts)

    # --------------------------------------------------------- decisions
    def _consider(self, alert, now: float) -> None:
        subject = alert.subject
        if alert.rule in QUARANTINE_RULES and \
                subject.startswith("worker-"):
            key = (ACTION_QUARANTINE, subject)
            active = self._active.get(key)
            if active is not None:
                active.holders.add((alert.rule, subject))
                active.probation_since = None
            elif not self._cooling(ACTION_QUARANTINE, alert.rule,
                                   subject, now):
                self._attempt(
                    ACTION_QUARANTINE, alert.rule, subject, now,
                    lambda: self._do_quarantine(subject),
                    f"stop placing new blocks / prefetch targets on "
                    f"{subject}",
                    reversible=True)
        if alert.rule in REREPLICATE_RULES and \
                subject.startswith("worker-") and \
                not self._cooling(ACTION_REREPLICATE, alert.rule,
                                  subject, now):
            self._attempt(
                ACTION_REREPLICATE, alert.rule, subject, now,
                lambda: self._do_rereplicate(subject),
                f"re-replicate the hottest blocks off {subject}")
        retune = self._retune_for(alert.rule)
        if retune:
            key = (ACTION_RETUNE, alert.rule)
            active = self._active.get(key)
            if active is not None:
                active.holders.add((alert.rule, subject))
                active.probation_since = None
            elif not self._cooling(ACTION_RETUNE, alert.rule, subject,
                                   now):
                self._attempt(
                    ACTION_RETUNE, alert.rule, subject, now,
                    lambda: self._do_retune(retune),
                    "push client tuning overlay "
                    + ", ".join(f"{k}={v}" for k, v in retune.items()),
                    reversible=True, active_key=key,
                    detail={"overlay": dict(retune)})

    def _retune_for(self, rule: str) -> Dict[str, object]:
        """The overlay one rule's firing asks for — values derived from
        the master's conf defaults, clamped again client-side."""
        if rule == "hedge-win-rate-spike":
            # hedges keep beating the primary: hedge EARLIER so reads
            # stop waiting out the straggler's tail
            base = self._bases[OVERLAY_HEDGE_QUANTILE]
            return {OVERLAY_HEDGE_QUANTILE:
                    round(max(0.5, base * 0.8), 3)}
        if rule == "input-stall-sustained":
            # loaders starve: widen the pipes and the prefetch horizon
            return {
                OVERLAY_PREFETCH_BUDGET:
                    int(self._bases[OVERLAY_PREFETCH_BUDGET]) * 2,
                OVERLAY_REMOTE_CONCURRENCY:
                    min(16, int(
                        self._bases[OVERLAY_REMOTE_CONCURRENCY]) * 2),
            }
        return {}

    # -------------------------------------------------- attempt pipeline
    def _attempt(self, kind: str, rule: str, subject: str, now: float,
                 execute: Callable[[], dict], summary: str, *,
                 reversible: bool = False,
                 active_key: Optional[Tuple[str, str]] = None,
                 detail: Optional[dict] = None) -> None:
        """Cap -> dry-run -> execute, auditing each gate (the cooldown
        gate runs in :meth:`_cooling` BEFORE the call sites build
        summaries and closures — it is the hot per-tick path while an
        alert burns).  Suppressions are audited once per episode."""
        cd_key = (kind, subject)
        if len(self._window) >= self.max_actions_per_window:
            self._suppress(kind, rule, subject, now, "suppressed-cap",
                           summary, self.window_s)
            return
        self._last_attempt[cd_key] = now
        self._window.append(now)
        if self.dry_run:
            record = self._audit_row(kind, rule, subject, now, "dry-run",
                                     summary, detail or {})
            self._c_dry.inc()
        else:
            # tracer().span, NOT utils.tracing.annotate: annotate also
            # stamps the jax device timeline (first use imports jax —
            # seconds — and each use builds a TraceAnnotation), and a
            # master control loop has no device timeline to stamp
            from alluxio_tpu.utils.tracing import tracer

            try:
                with tracer().span(f"atpu.master.remediation.{kind}"):
                    result = execute()
            except Exception as e:  # noqa: BLE001 - an unhealable
                # subject (worker vanished mid-decision, job service
                # down) must not take the health heartbeat with it
                record = self._audit_row(
                    kind, rule, subject, now, "failed", summary,
                    {**(detail or {}), "error": str(e)})
                self._c_failed.inc()
                LOG.warning("remediation %s on %s failed", kind, subject,
                            exc_info=True)
                return
            outcome = result.pop("outcome", "executed")
            record = self._audit_row(kind, rule, subject, now, outcome,
                                     summary, {**(detail or {}), **result})
            if outcome == "executed":
                self._c_actions.inc()
            elif reversible:
                # a skipped reversible action (healthy-capacity floor,
                # no job service) is NOT in force: tracking it active
                # would later "release" something never applied
                return
        if reversible:
            key = active_key or (kind, subject)
            self._active[key] = _Active(
                record, {(rule, subject)},
                worker_id=record.detail.get("worker_id"))

    def _cooling(self, kind: str, rule: str, subject: str,
                 now: float) -> bool:
        """Cooldown gate, prechecked before any attempt machinery runs
        (the hot per-tick path while an alert burns).  The suppression
        is audited and counted once per episode — one row per denied
        episode reads like a decision; one per tick reads like a log
        flood."""
        last = self._last_attempt.get((kind, subject))
        if last is None or now - last >= self.cooldown_s:
            return False
        self._suppress(kind, rule, subject, now, "suppressed-cooldown",
                       f"{kind} on {subject} held by cooldown "
                       f"({self.cooldown_s:.0f}s)", self.cooldown_s)
        return True

    def _suppress(self, kind: str, rule: str, subject: str, now: float,
                  reason: str, summary: str, episode_s: float) -> None:
        log_key = (kind, subject, reason)
        last = self._suppression_logged.get(log_key)
        if last is not None and now - last < episode_s:
            return  # already audited+counted this suppression episode
        self._c_suppressed.inc()
        self._suppression_logged[log_key] = now
        self._audit_row(kind, rule, subject, now, reason, summary, {})

    def _audit_row(self, kind: str, rule: str, subject: str, now: float,
                   outcome: str, summary: str, detail: dict
                   ) -> AuditRecord:
        record = AuditRecord(id=self._next_id, at=now, action=kind,
                             rule=rule, subject=subject, outcome=outcome,
                             summary=summary, detail=detail)
        self._next_id += 1
        self._audit.append(record)
        self._history_dirty = True
        return record

    # --------------------------------------------------------- execution
    def _worker_id_for(self, source: str) -> Optional[int]:
        lookup = getattr(self._bm, "worker_id_for_source", None)
        if lookup is not None:
            return lookup(source)
        # duck-typed stub without the O(1) index: scan the listing
        for w in self._bm.get_worker_infos(include_quarantined=True):
            if f"worker-{w.address.host}:{w.address.rpc_port}" == source:
                return w.id
        return None

    def _do_quarantine(self, source: str) -> dict:
        wid = self._worker_id_for(source)
        if wid is None:
            raise LookupError(f"no registered worker matches {source}")
        # healthy-capacity floor: a systemic condition that flags the
        # whole fleet (e.g. a switch melting every worker's heartbeats)
        # must not let the engine empty the placement set — that would
        # amplify the outage it is meant to contain
        workers = self._bm.get_worker_infos(include_quarantined=True)
        qw = getattr(self._bm, "quarantined_workers", None)
        quarantined = len(qw()) if qw is not None else sum(
            1 for w in workers
            if getattr(w, "state", "") == "QUARANTINED")
        limit = max(1, int(self.quarantine_max_fraction * len(workers)))
        if quarantined + 1 > limit:
            return {"outcome": "skipped",
                    "reason": f"healthy-capacity floor: {quarantined} of "
                              f"{len(workers)} already quarantined "
                              f"(max {limit})",
                    "worker_id": wid}
        if not self._bm.quarantine_worker(wid):
            raise LookupError(f"worker {wid} vanished before quarantine")
        return {"worker_id": wid}

    def _do_rereplicate(self, source: str) -> dict:
        if self._replication is None:
            return {"outcome": "skipped",
                    "reason": "no job service attached"}
        wid = self._worker_id_for(source)
        info = self._bm.get_worker(wid) if wid is not None else None
        if info is None:
            raise LookupError(f"no registered worker matches {source}")
        # capacity_bytes_on_tiers is reference-swapped (never mutated
        # in place) so reading it is safe; blocks IS mutated in place
        # by worker heartbeats — take the block master's locked copy
        snapshot = getattr(self._bm, "worker_resident_blocks", None)
        blocks = snapshot(wid) if snapshot is not None \
            else dict(info.blocks)
        if blocks is None:
            raise LookupError(f"{source} vanished before re-replication")
        # "hottest" = resident in the worker's fastest tier: the
        # annotator promotes what is actually read, so top-tier
        # residency is the system's own heat signal
        top = next(iter(info.capacity_bytes_on_tiers), None)
        hot = [bid for bid, tier in blocks.items() if tier == top]
        hot = hot[:self.rereplicate_blocks]
        if not hot:
            return {"outcome": "skipped", "reason": "no resident blocks",
                    "worker_id": wid}
        launched = self._replication.request_replication(hot, replicas=1)
        return {"worker_id": wid, "blocks": launched,
                "requested": len(hot)}

    def _do_retune(self, overlay: Dict[str, object]) -> dict:
        merged = dict(self._overlay_wire)
        merged.update(overlay)
        self._overlay_wire = merged
        self.overlay_version += 1
        return {"overlay_version": self.overlay_version}

    # -------------------------------------------------------- resolution
    def _sweep_resolved(self, firing: set, now: float) -> None:
        for key in list(self._active):
            active = self._active[key]
            active.holders &= firing
            if active.holders:
                continue
            if active.probation_since is None:
                active.probation_since = now
                active.record.resolved_at = active.record.resolved_at \
                    or now
            if now - active.probation_since < self.probation_s:
                continue
            kind, subject = key
            try:
                self._undo(kind, subject, active, now)
            except Exception:  # noqa: BLE001 - release must not wedge
                LOG.warning("remediation undo %s on %s failed", kind,
                            subject, exc_info=True)
            del self._active[key]

    def _undo(self, kind: str, subject: str, active: _Active,
              now: float) -> None:
        active.record.reverted_at = now
        if kind == ACTION_QUARANTINE:
            released = False
            if not self.dry_run and active.worker_id is not None:
                released = self._bm.release_worker(active.worker_id)
            self._audit_row(
                ACTION_RELEASE, active.record.rule, subject, now,
                "dry-run" if self.dry_run else "executed",
                f"probation passed: {subject} back in the placement set",
                {"worker_id": active.worker_id, "released": released,
                 "acted_id": active.record.id})
        elif kind == ACTION_RETUNE:
            # drop this action's keys from the pushed overlay
            dropped = list((active.record.detail.get("overlay") or {}))
            merged = {k: v for k, v in self._overlay_wire.items()
                      if k not in dropped}
            self._overlay_wire = merged
            self.overlay_version += 1
            self._audit_row(
                ACTION_REVERT, active.record.rule, subject, now,
                "dry-run" if self.dry_run else "executed",
                "alert cleared: tuning overlay withdrawn "
                + ", ".join(dropped),
                {"overlay_version": self.overlay_version,
                 "acted_id": active.record.id})

    # -------------------------------------------------------- accounting
    def _prune_window(self, now: float) -> None:
        while self._window and now - self._window[0] > self.window_s:
            self._window.popleft()
        if len(self._suppression_logged) > 4 * self.AUDIT_CAPACITY:
            # bounded even if subjects churn forever
            self._suppression_logged.clear()

    def _sample_history(self, now: float) -> None:
        history = getattr(self._mm, "history", None)
        if history is None:
            return
        if not self._history_dirty and \
                now - self._last_history_sample < self.HISTORY_KEEPALIVE_S:
            return
        self._history_dirty = False
        self._last_history_sample = now
        history.ingest("master", {
            "Master.RemediationActions": float(self._c_actions.count),
            "Master.RemediationSuppressed":
                float(self._c_suppressed.count),
            "Master.RemediationQuarantined":
                float(sum(1 for k in self._active
                          if k[0] == ACTION_QUARANTINE)),
            "Master.RemediationOverlayKeys":
                float(len(self._overlay_wire)),
        }, now=now)

    # ------------------------------------------------------------ report
    def report(self) -> dict:
        """Wire view for get_health / /api/v1/master/remediation /
        `fsadmin report health` — the audited timeline plus what is in
        force right now."""
        with self._lock:
            quarantined = [
                {"subject": key[1],
                 "worker_id": active.worker_id,
                 "since": active.record.at,
                 "rule": active.record.rule,
                 "probation_since": active.probation_since}
                for key, active in self._active.items()
                if key[0] == ACTION_QUARANTINE]
            return {
                "enabled": True,
                "dry_run": self.dry_run,
                "actions_in_window": len(self._window),
                "max_actions_per_window": self.max_actions_per_window,
                "window_s": self.window_s,
                "cooldown_s": self.cooldown_s,
                "probation_s": self.probation_s,
                "quarantined": quarantined,
                "overlay": dict(self._overlay_wire),
                "overlay_version": self.overlay_version,
                "audit": [r.to_wire() for r in self._audit],
            }
