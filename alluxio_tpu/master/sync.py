"""UFS metadata-sync machinery.

Re-designs of the reference's sync subsystem:
- ``file/meta/UfsSyncPathCache.java`` -> :class:`UfsSyncPathCache` — when
  was a path (or its whole subtree) last synced, so the on-access gate can
  skip redundant UFS round-trips;
- ``file/meta/AsyncUfsAbsentPathCache.java`` -> :class:`AbsentPathCache` —
  remember UFS-absent paths so repeated misses don't hammer the store;
- ``file/activesync/{ActiveSyncManager.java:81,ActiveSyncer.java}`` ->
  :class:`ActiveSyncManager` — journaled sync points re-synced by a
  heartbeat. The reference rides HDFS iNotify; object stores have no event
  stream, so the TPU build polls with fingerprint diffs (the same
  mechanism the reference falls back to on full-sync intervals).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from alluxio_tpu.journal.format import EntryType
from alluxio_tpu.utils.uri import AlluxioURI

LOG = logging.getLogger(__name__)


class UfsSyncPathCache:
    """LRU map path -> (last_sync_ms, recursive). A recursive sync of /a
    also freshens /a/b lookups (reference: UfsSyncPathCache.shouldSync)."""

    def __init__(self, max_size: int = 100_000) -> None:
        self._entries: "collections.OrderedDict[str, Tuple[int, bool]]" = \
            collections.OrderedDict()
        self._max = max_size
        self._lock = threading.Lock()

    def notify_synced(self, path: str, now_ms: int,
                      recursive: bool = False) -> None:
        with self._lock:
            self._entries[path] = (now_ms, recursive)
            self._entries.move_to_end(path)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)

    def last_sync_ms(self, path: str) -> int:
        """Newest applicable sync time: the path's own, or any ancestor's
        recursive sync."""
        best = 0
        with self._lock:
            entry = self._entries.get(path)
            if entry is not None:
                best = entry[0]
            p = path
            while p and p != "/":
                p = p.rsplit("/", 1)[0] or "/"
                entry = self._entries.get(p)
                if entry is not None and entry[1]:
                    best = max(best, entry[0])
        return best

    def should_sync(self, path: str, now_ms: int,
                    interval_ms: int) -> bool:
        if interval_ms < 0:
            return False
        if interval_ms == 0:
            return True
        return now_ms - self.last_sync_ms(path) >= interval_ms

    def invalidate(self, path: str) -> None:
        with self._lock:
            self._entries.pop(path, None)


class AbsentPathCache:
    """Capped TTL set of UFS paths known to be absent
    (reference: AsyncUfsAbsentPathCache)."""

    def __init__(self, max_size: int = 10_000, ttl_s: float = 60.0) -> None:
        self._entries: "collections.OrderedDict[str, float]" = \
            collections.OrderedDict()
        self._max = max_size
        self._ttl = ttl_s
        self._lock = threading.Lock()

    def add(self, path: str) -> None:
        with self._lock:
            self._entries[path] = time.monotonic()
            self._entries.move_to_end(path)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)

    def is_absent(self, path: str) -> bool:
        with self._lock:
            t = self._entries.get(path)
            if t is None:
                return False
            if time.monotonic() - t > self._ttl:
                del self._entries[path]
                return False
            return True

    def remove(self, path: str) -> None:
        """A write created the path (or an ancestor changed): forget it and
        every cached descendant."""
        prefix = path.rstrip("/") + "/"
        with self._lock:
            self._entries.pop(path, None)
            for k in [k for k in self._entries
                      if k.startswith(prefix)]:
                del self._entries[k]

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


class ActiveSyncManager:
    """Journaled sync points + the polling re-sync pass
    (reference: ``ActiveSyncManager.java:81``; the heartbeat tick is the
    ``ActiveSyncer`` equivalent, registered as MASTER_ACTIVE_UFS_SYNC)."""

    journal_name = "ActiveSyncManager"

    def __init__(self, fs_master, journal) -> None:
        self._fsm = fs_master
        self._journal = journal
        self._points: List[str] = []
        self._lock = threading.Lock()
        #: per-point stats: path -> (last_run_ms, changed_count)
        self.last_runs: Dict[str, Tuple[int, int]] = {}
        journal.register(self)

    # -- API (exposed via fs shell startSync/stopSync) -----------------------
    def add_sync_point(self, path: "str | AlluxioURI") -> None:
        uri = AlluxioURI(path)
        self._fsm.get_status(uri)  # must exist (reference parity)
        with self._lock:
            if uri.path in self._points:
                return
        with self._journal.create_context() as ctx:
            ctx.append(EntryType.ADD_SYNC_POINT, {"path": uri.path})

    def remove_sync_point(self, path: "str | AlluxioURI") -> None:
        uri = AlluxioURI(path)
        with self._lock:
            if uri.path not in self._points:
                from alluxio_tpu.utils.exceptions import InvalidArgumentError

                raise InvalidArgumentError(
                    f"{uri.path} is not a sync point")
        with self._journal.create_context() as ctx:
            ctx.append(EntryType.REMOVE_SYNC_POINT, {"path": uri.path})

    def sync_points(self) -> List[str]:
        with self._lock:
            return list(self._points)

    # -- the ActiveSyncer tick ----------------------------------------------
    def heartbeat(self) -> None:
        for path in self.sync_points():
            try:
                changed = self._fsm.sync_metadata(path, recursive=True)
                self.last_runs[path] = (
                    int(time.time() * 1000), int(changed))
            except Exception:  # noqa: BLE001 - keep other points alive
                LOG.exception("active sync of %s failed", path)

    # -- journal contract ----------------------------------------------------
    def process_entry(self, entry) -> bool:
        if entry.type == EntryType.ADD_SYNC_POINT:
            with self._lock:
                p = entry.payload["path"]
                if p not in self._points:
                    self._points.append(p)
            return True
        if entry.type == EntryType.REMOVE_SYNC_POINT:
            with self._lock:
                try:
                    self._points.remove(entry.payload["path"])
                except ValueError:
                    pass
            return True
        return False

    def snapshot(self) -> dict:
        with self._lock:
            return {"points": list(self._points)}

    def restore(self, snap: dict) -> None:
        with self._lock:
            self._points = list(snap.get("points", []))

    def reset_state(self) -> None:
        with self._lock:
            self._points = []
