"""Master-side metadata invalidation log: the push half of the client
metadata cache.

Every namespace mutation appends ``(version, path)`` to a bounded ring;
``GetStatus``/``ListStatus`` responses carry the log's current version as
a stamp, and clients piggyback their applied version on the metrics
heartbeat — the response returns every invalidated path-prefix since,
so a warm client cache stays coherent within one heartbeat interval
without any per-read round trip (reference: Alluxio's
``MetadataCachingBaseFileSystem`` only has TTL expiry; the push protocol
follows the self-invalidating-cache framing of Hoard, arxiv 1812.00669,
over the PR-6 conf-overlay heartbeat channel).

Protocol invariants (see docs/metadata.md):

- The stamp is read BEFORE the data under the path lock, so a response's
  payload is always at least as new as its stamp; any later mutation has
  a larger version and WILL be delivered as an invalidation.
- A client only caches a response whose stamp >= its applied version —
  an older response might predate an invalidation the client already
  consumed, and would otherwise be retained forever.
- A client whose version fell off the ring (overflow, or first contact)
  gets ``reset`` and drops its whole cache.
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import List, Optional, Tuple

_metrics_fn = None


def _metrics():
    global _metrics_fn
    if _metrics_fn is None:
        from alluxio_tpu.metrics import metrics as _m

        _metrics_fn = _m
    return _metrics_fn()


class MetadataInvalidationLog:
    """Bounded ring of namespace invalidations, versioned monotonically.

    Entries are appended in strictly increasing version order, so a
    client's catch-up query bisects to its suffix — every heartbeat
    pays O(log n + new entries), not a scan of the whole ring under the
    lock every mutation contends on."""

    def __init__(self, capacity: int = 8192) -> None:
        self._lock = threading.Lock()
        self._capacity = max(16, capacity)
        self._entries: List[Tuple[int, str]] = []
        self._version = 0

    @property
    def version(self) -> int:
        """Current version (racy int read — monotonic, safe)."""
        return self._version

    def append(self, path: str) -> int:
        """Record that ``path`` (and, by client-side prefix semantics,
        its descendants and parent listing) changed.  Returns the new
        version."""
        with self._lock:
            self._version += 1
            self._entries.append((self._version, path))
            if len(self._entries) > 2 * self._capacity:
                # amortized trim: one O(capacity) copy per capacity
                # appends keeps append O(1) while a list stays
                # bisectable (a deque is O(n) to index)
                del self._entries[:-self._capacity]
            v = self._version
        _metrics().counter("Master.MetadataCacheInvalidations").inc()
        return v

    def restore_version(self, version: int) -> None:
        """Adopt a snapshot's version (journal component restore): the
        ring's entries are not part of the snapshot — readers below the
        floor get ``reset``, exactly as after a ring overflow."""
        with self._lock:
            self._version = int(version)
            self._entries.clear()

    def since(self, version: Optional[int]) -> dict:
        """Invalidations newer than ``version`` in wire form:
        ``{"to": v, "prefixes": [...], "reset": bool}``.  ``None`` (a
        client establishing its floor) and versions older than the ring
        both come back as ``reset`` — the client drops its cache and
        adopts ``to`` as its new applied version."""
        with self._lock:
            cur = self._version
            if version is None:
                return {"to": cur, "prefixes": [], "reset": True}
            version = int(version)
            if version > cur:
                # a version we never issued: the client tracked a
                # master that had applied MORE entries than us (e.g. a
                # deposed leader's torn, never-committed tail).  Unknown
                # horizon -> reset.
                return {"to": cur, "prefixes": [], "reset": True}
            if version == cur:
                return {"to": cur, "prefixes": [], "reset": False}
            retained = len(self._entries)
            oldest = self._entries[0][0] if retained else cur + 1
            if version < oldest - 1:
                return {"to": cur, "prefixes": [], "reset": True}
            start = bisect_right(self._entries, version,
                                 key=lambda e: e[0])
            prefixes = sorted({p for _v, p in self._entries[start:]})
            return {"to": cur, "prefixes": prefixes, "reset": False}
