"""Block master: block -> locations map, worker registry & liveness.

Re-design of ``core/server/master/.../block/DefaultBlockMaster.java:119``
(workerRegister ``:869``, workerHeartbeat ``:916``,
LostWorkerDetectionHeartbeatExecutor ``:1087``) and
``block/meta/MasterWorkerInfo.java``.

Journaled state: block lengths (``BLOCK_INFO``) and the container id
counter. Block *locations* are soft state reconstructed from worker
registrations/heartbeats — exactly the reference's split: a failover
rebuilds the location map from re-registration, never from the journal.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from alluxio_tpu.journal.format import EntryType, JournalEntry, Journaled
from alluxio_tpu.journal.system import JournalSystem
from alluxio_tpu.utils import ids
from alluxio_tpu.utils.clock import Clock, SystemClock
from alluxio_tpu.utils.exceptions import (
    BlockDoesNotExistError, NotFoundError,
)
from alluxio_tpu.utils.wire import (
    BlockInfo, BlockLocation, TieredIdentity, WorkerInfo, WorkerNetAddress,
)

LOG = logging.getLogger(__name__)


class WorkerCommand:
    """Commands piggybacked on heartbeat responses
    (reference: ``block_master.proto`` Command / CommandType)."""

    NOTHING = "NOTHING"
    REGISTER = "REGISTER"
    FREE = "FREE"
    DELETE = "DELETE"


@dataclass
class MasterWorkerInfo:
    id: int
    address: WorkerNetAddress
    start_time_ms: int = 0
    last_contact_ms: int = 0
    registered: bool = False
    capacity_bytes_on_tiers: Dict[str, int] = field(default_factory=dict)
    used_bytes_on_tiers: Dict[str, int] = field(default_factory=dict)
    #: block id -> tier alias
    blocks: Dict[int, str] = field(default_factory=dict)
    to_remove_blocks: Set[int] = field(default_factory=set)

    @property
    def capacity_bytes(self) -> int:
        return sum(self.capacity_bytes_on_tiers.values())

    @property
    def used_bytes(self) -> int:
        return sum(self.used_bytes_on_tiers.values())

    def to_wire(self, state: str = "LIVE") -> WorkerInfo:
        return WorkerInfo(
            id=self.id, address=self.address, state=state,
            capacity_bytes=self.capacity_bytes, used_bytes=self.used_bytes,
            start_time_ms=self.start_time_ms,
            last_contact_ms=self.last_contact_ms,
            capacity_bytes_on_tiers=dict(self.capacity_bytes_on_tiers),
            used_bytes_on_tiers=dict(self.used_bytes_on_tiers),
            block_count=len(self.blocks))


@dataclass
class MasterBlockMeta:
    block_id: int
    length: int = -1  # -1 until committed


class BlockMaster(Journaled):
    journal_name = "BlockMaster"

    def __init__(self, journal: JournalSystem, clock: Optional[Clock] = None,
                 worker_timeout_ms: int = 300_000) -> None:
        self._journal = journal
        journal.register(self)
        self._clock = clock or SystemClock()
        self._worker_timeout_ms = worker_timeout_ms
        self._lock = threading.RLock()
        # journaled
        self._blocks: Dict[int, MasterBlockMeta] = {}
        self.container_ids = ids.ContainerIdGenerator()
        # soft state
        self._workers: Dict[int, MasterWorkerInfo] = {}
        self._lost_workers: Dict[int, MasterWorkerInfo] = {}
        self._top_tiers: "frozenset[str]" = frozenset()
        self._address_to_id: Dict[str, int] = {}
        #: block id -> {worker id -> tier alias}
        self._locations: Dict[int, Dict[int, str]] = {}
        #: bumped on any location/topology change; "unchanged" means
        #: every derived per-file residency figure (in_memory_percentage,
        #: top tiers) is still valid — consumed by the listing cache
        self.location_version = 0
        #: block id -> {mesh position -> reporting host}: the HBM warm
        #: set reported by JAX clients (§2.11 device-mesh block map)
        self._device_locations: Dict[int, Dict[int, str]] = {}
        #: reporting host -> last report time (ms); reports are leases —
        #: a client that dies without clearing ages out (see
        #: prune_device_reports, driven by the lost-worker heartbeat)
        self._device_report_ms: Dict[str, int] = {}
        self.device_report_ttl_ms = 5 * 60 * 1000
        #: ids below this mark are covered by a journaled reservation
        self._container_reserved = 0
        self._reserve_lock = threading.Lock()
        self._lost_blocks: Set[int] = set()
        #: worker id -> quarantine start (ms): still registered, still
        #: serving its resident blocks, but filtered out of the
        #: placement listing (writes, UFS read-through policy picks,
        #: prefetch targets, replication targets) until released.
        #: Soft state owned by the remediation engine — like locations,
        #: never journaled: a failover drops quarantine and the health
        #: rules re-derive it if the worker is still sick.
        self._quarantined: Dict[int, int] = {}
        #: listeners fired on worker loss (elastic re-replication hook)
        self.lost_worker_listeners: List = []
        #: listeners fired on full (re-)registration — the only signal
        #: that a lost worker is genuinely back serving blocks (its
        #: metrics heartbeat alone is not: a worker whose block-sync
        #: thread is wedged keeps shipping metrics while serving nothing)
        self.registered_worker_listeners: List = []
        #: listeners fired (OUTSIDE the lock) with a batch of block ids
        #: whose LOCATIONS drifted — worker loss, quarantine/release, a
        #: re-replicated copy landing.  The master process routes these
        #: into the metadata invalidation log so client caches repair on
        #: the next heartbeat instead of waiting out their TTL
        #: (docs/ha.md; ROADMAP "location drift repairs only on TTL")
        self.location_change_listeners: List = []

    def _notify_location_change(self, block_ids: List[int]) -> None:
        """Fire location-drift listeners; caller must NOT hold the lock
        (listeners resolve block->path through the inode tree)."""
        if not block_ids:
            return
        for listener in self.location_change_listeners:
            try:
                listener(block_ids)
            except Exception:  # noqa: BLE001 - one bad hook must not block
                LOG.warning("location-change listener failed",
                            exc_info=True)

    #: container ids are journaled as a high-water mark in chunks of this
    #: size: one BLOCK_CONTAINER_ID entry covers the next N allocations,
    #: so create_file doesn't pay a journal flush per id. Replay resumes
    #: from the mark; ids the crashed master never handed out are simply
    #: skipped (ids are opaque). Reference:
    #: ``BlockContainerIdGenerator`` + ``JournalEntry.block_container_id``.
    CONTAINER_ID_RESERVATION = 1024

    # ------------------------------------------------------------ container
    def new_container_id(self) -> int:
        """Journaled container-id allocation via chunked reservation.

        The mark must be DURABLE before any id it covers is published:
        another RPC could use id mark-1 and group-commit its inode entry
        while this RPC's (deferred) reservation flush never happens, and
        replay would then re-issue used ids. Hence immediate_durability
        + publishing ``_container_reserved`` only after the write (one
        fsync per CONTAINER_ID_RESERVATION creates).

        Locking: a DEDICATED ``_reserve_lock``, never ``self._lock`` —
        journal writes apply entries under the journal lock and that
        apply path takes ``self._lock`` (``process_entry``), so holding
        ``self._lock`` while entering the journal would be an ABBA
        deadlock against any concurrent block mutation."""
        cid = self.container_ids.next_container_id()
        if cid >= self._container_reserved:
            with self._reserve_lock:
                if cid < self._container_reserved:  # another thread won
                    return cid
                mark = cid + self.CONTAINER_ID_RESERVATION
                with self._journal.immediate_durability(), \
                        self._journal.create_context() as ctx:
                    ctx.append(EntryType.BLOCK_CONTAINER_ID,
                               {"next_container_id": mark,
                                "owner": self.journal_name})
                self._container_reserved = mark
        return cid

    # -------------------------------------------------------------- workers
    def get_worker_id(self, address: WorkerNetAddress) -> int:
        """Address-keyed worker id lease
        (reference: ``DefaultBlockMaster.getWorkerId``)."""
        key = address.key()
        with self._lock:
            existing = self._address_to_id.get(key)
            if existing is not None:
                lost = self._lost_workers.pop(existing, None)
                if lost is not None:
                    self._workers[existing] = lost
                    self._refresh_top_tiers()
                return existing
            wid = ids.create_worker_id(address.host, address.rpc_port)
            info = MasterWorkerInfo(id=wid, address=address,
                                    start_time_ms=self._clock.millis(),
                                    last_contact_ms=self._clock.millis())
            self._workers[wid] = info
            self._address_to_id[key] = wid
            return wid

    def worker_register(self, worker_id: int,
                        capacity_bytes_on_tiers: Dict[str, int],
                        used_bytes_on_tiers: Dict[str, int],
                        blocks_on_tiers: Dict[str, List[int]],
                        address: Optional[WorkerNetAddress] = None) -> None:
        """Full (re-)registration with complete block list
        (reference: ``workerRegister``, ``DefaultBlockMaster.java:869``)."""
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None:
                info = self._lost_workers.pop(worker_id, None)
                if info is not None:
                    self._workers[worker_id] = info
            if info is None:
                if address is None:
                    raise NotFoundError(f"unknown worker id {worker_id}")
                info = MasterWorkerInfo(id=worker_id, address=address,
                                        start_time_ms=self._clock.millis())
                self._workers[worker_id] = info
                self._address_to_id[address.key()] = worker_id
            if address is not None:
                info.address = address
                self._address_to_id[address.key()] = worker_id
            # drop stale location info from a previous registration
            for bid in list(info.blocks):
                self._remove_location(bid, worker_id)
            info.blocks.clear()
            info.capacity_bytes_on_tiers = dict(capacity_bytes_on_tiers)
            info.used_bytes_on_tiers = dict(used_bytes_on_tiers)
            info.last_contact_ms = self._clock.millis()
            info.registered = True
            self._refresh_top_tiers()
            for tier, bids in blocks_on_tiers.items():
                for bid in bids:
                    if bid in self._blocks:
                        info.blocks[bid] = tier
                        self._add_location(bid, worker_id, tier)
                    else:
                        # master doesn't know this block -> tell worker to drop
                        info.to_remove_blocks.add(bid)
        for listener in self.registered_worker_listeners:
            try:
                listener(info)
            except Exception:  # noqa: BLE001 - one bad hook must not block registration
                LOG.warning("registered-worker listener failed for %s",
                            info.id, exc_info=True)

    def worker_heartbeat(self, worker_id: int,
                         used_bytes_on_tiers: Dict[str, int],
                         added_blocks: Dict[str, List[int]],
                         removed_blocks: List[int],
                         metrics: Optional[Dict[str, float]] = None) -> dict:
        """Periodic delta sync; returns a command
        (reference: ``workerHeartbeat``, ``DefaultBlockMaster.java:916``)."""
        with self._lock:
            info = self._workers.get(worker_id)
            if info is None or not info.registered:
                return {"command": WorkerCommand.REGISTER, "data": []}
            info.last_contact_ms = self._clock.millis()
            info.used_bytes_on_tiers = dict(used_bytes_on_tiers)
            for bid in removed_blocks:
                info.blocks.pop(bid, None)
                self._remove_location(bid, worker_id)
            for tier, bids in added_blocks.items():
                for bid in bids:
                    if bid in self._blocks:
                        info.blocks[bid] = tier
                        self._add_location(bid, worker_id, tier)
                    else:
                        info.to_remove_blocks.add(bid)
            if info.to_remove_blocks:
                data = sorted(info.to_remove_blocks)
                info.to_remove_blocks.clear()
                return {"command": WorkerCommand.FREE, "data": data}
            return {"command": WorkerCommand.NOTHING, "data": []}

    def _add_location(self, block_id: int, worker_id: int, tier: str) -> None:
        self._locations.setdefault(block_id, {})[worker_id] = tier
        self._lost_blocks.discard(block_id)
        self.location_version += 1

    def _remove_location(self, block_id: int, worker_id: int) -> None:
        locs = self._locations.get(block_id)
        if locs is not None:
            locs.pop(worker_id, None)
            self.location_version += 1
            if not locs:
                del self._locations[block_id]
                if block_id in self._blocks:
                    self._lost_blocks.add(block_id)

    def detect_lost_workers(self) -> List[int]:
        """Expire silent workers; fires lost-worker listeners
        (reference: LostWorkerDetectionHeartbeatExecutor,
        ``DefaultBlockMaster.java:1087``)."""
        self.prune_device_reports()
        now = self._clock.millis()
        newly_lost: List[MasterWorkerInfo] = []
        drifted: List[int] = []
        with self._lock:
            for wid, info in list(self._workers.items()):
                if now - info.last_contact_ms > self._worker_timeout_ms:
                    del self._workers[wid]
                    self._lost_workers[wid] = info
                    # a lost worker's quarantine dies with it: loss is
                    # the stronger state, and a later re-registration
                    # must start from a clean placement slate
                    self._quarantined.pop(wid, None)
                    info.registered = False
                    self._refresh_top_tiers()
                    drifted.extend(info.blocks)
                    for bid in list(info.blocks):
                        self._remove_location(bid, wid)
                    info.blocks.clear()
                    newly_lost.append(info)
        for info in newly_lost:
            for listener in self.lost_worker_listeners:
                try:
                    listener(info)
                except Exception:  # noqa: BLE001 - one bad hook must not block detection
                    LOG.warning("lost-worker listener failed for %s",
                                info.id, exc_info=True)
        self._notify_location_change(drifted)
        return [i.id for i in newly_lost]

    def worker_id_for_source(self, source: str) -> Optional[int]:
        """O(1) lookup of a LIVE worker by its metrics-source name
        (``worker-<host>:<rpc_port>``).  The remediation engine
        resolves alert subjects through this — scanning
        ``get_worker_infos`` would build a wire object per worker
        under the lock for every action taken."""
        if not source.startswith("worker-"):
            return None
        with self._lock:
            wid = self._address_to_id.get(source[len("worker-"):])
            return wid if wid in self._workers else None

    # ---------------------------------------------------------- quarantine
    def quarantine_worker(self, worker_id: int) -> bool:
        """Remove a live worker from the placement listing without
        touching its served blocks (remediation: a straggling or stale
        worker keeps serving what it has, but receives nothing new).
        Returns False for unknown/lost workers."""
        with self._lock:
            if worker_id not in self._workers:
                return False
            self._quarantined[worker_id] = self._clock.millis()
            self.location_version += 1
            drifted = list(self._workers[worker_id].blocks)
        self._notify_location_change(drifted)
        return True

    def release_worker(self, worker_id: int) -> bool:
        """Lift a quarantine (probation passed, or operator override)."""
        with self._lock:
            if self._quarantined.pop(worker_id, None) is None:
                return False
            self.location_version += 1
            info = self._workers.get(worker_id)
            drifted = list(info.blocks) if info is not None else []
        self._notify_location_change(drifted)
        return True

    def quarantined_workers(self) -> Dict[int, int]:
        """worker id -> quarantine start (ms since epoch)."""
        with self._lock:
            return dict(self._quarantined)

    def is_quarantined(self, worker_id: int) -> bool:
        with self._lock:
            return worker_id in self._quarantined

    def forget_worker(self, worker_id: int) -> None:
        """Expire one worker immediately (admin decommission / tests);
        same effect as the lost-worker detector firing for it."""
        with self._lock:
            info = self._workers.pop(worker_id, None)
            if info is None:
                return
            self._quarantined.pop(worker_id, None)
            self._lost_workers[worker_id] = info
            info.registered = False
            self._refresh_top_tiers()
            drifted = list(info.blocks)
            for bid in list(info.blocks):
                self._remove_location(bid, worker_id)
            info.blocks.clear()
        for listener in self.lost_worker_listeners:
            try:
                listener(info)
            except Exception:  # noqa: BLE001 - one bad hook must not block removal
                LOG.warning("lost-worker listener failed for %s",
                            info.id, exc_info=True)
        self._notify_location_change(drifted)

    # --------------------------------------------------------------- blocks
    def commit_block(self, worker_id: int, used_bytes_on_tier: int,
                     tier_alias: str, block_id: int, length: int) -> None:
        """Worker durably has the block; journal its length
        (reference: ``commitBlock``, ``block_master.proto:271``)."""
        with self._journal.create_context() as ctx:
            ctx.append(EntryType.BLOCK_INFO,
                       {"block_id": block_id, "length": length})
        drift = False
        with self._lock:
            info = self._workers.get(worker_id)
            if info is not None:
                # an ADDITIONAL replica landing (re-replication after a
                # quarantine/loss) is location drift other clients'
                # caches should hear about; the FIRST copy is the
                # writing client's own business
                locs = self._locations.get(block_id)
                drift = bool(locs) and worker_id not in locs
                info.blocks[block_id] = tier_alias
                info.used_bytes_on_tiers[tier_alias] = used_bytes_on_tier
                self._add_location(block_id, worker_id, tier_alias)
        if drift:
            self._notify_location_change([block_id])

    def commit_block_in_ufs(self, block_id: int, length: int) -> None:
        """Block persisted directly to UFS with no cached copy."""
        with self._journal.create_context() as ctx:
            ctx.append(EntryType.BLOCK_INFO,
                       {"block_id": block_id, "length": length})

    def remove_blocks(self, block_ids: List[int], delete_metadata: bool) -> None:
        """Mark blocks for removal on their workers; optionally drop metadata."""
        with self._lock:
            for bid in block_ids:
                for wid in list(self._locations.get(bid, {})):
                    w = self._workers.get(wid)
                    if w is not None:
                        w.to_remove_blocks.add(bid)
        if delete_metadata:
            with self._journal.create_context() as ctx:
                for bid in block_ids:
                    ctx.append(EntryType.DELETE_BLOCK, {"block_id": bid})

    def get_block_info(self, block_id: int) -> BlockInfo:
        with self._lock:
            meta = self._blocks.get(block_id)
            if meta is None:
                raise BlockDoesNotExistError(f"block {block_id} not found")
            return self._block_info_locked(meta)

    def _block_info_locked(self, meta: MasterBlockMeta) -> BlockInfo:
        locations = []
        for wid, tier in self._locations.get(meta.block_id, {}).items():
            w = self._workers.get(wid)
            if w is not None:
                locations.append(BlockLocation(worker_id=wid, address=w.address,
                                               tier_alias=tier))
        device_locations = [
            BlockLocation(
                worker_id=-(pos + 1), tier_alias="HBM",
                address=WorkerNetAddress(
                    host=host,
                    tiered_identity=TieredIdentity.from_spec(
                        f"host={host},mesh={pos}")))
            for pos, host in self._device_locations.get(
                meta.block_id, {}).items()]
        return BlockInfo(block_id=meta.block_id,
                         length=max(meta.length, 0), locations=locations,
                         device_locations=device_locations)

    # ------------------------------------------ device (HBM) warm-set map
    def report_device_blocks(self, host: str,
                             mesh_blocks: Dict[int, List[int]]) -> None:
        """A JAX client reports its warm set: mesh position -> resident
        block ids (SURVEY §2.11 "block map keyed by device mesh
        position"). Replaces that host's previous report, so a warm-set
        turnover is one call. Device residency is cache state like worker
        tiers — volatile, never journaled."""
        with self._lock:
            self._drop_device_host(host)
            for pos, bids in mesh_blocks.items():
                for bid in bids:
                    self._device_locations.setdefault(
                        int(bid), {})[int(pos)] = host
            self.location_version += 1
            if mesh_blocks:
                self._device_report_ms[host] = self._clock.millis()

    def _drop_device_host(self, host: str) -> None:
        for bid in list(self._device_locations):
            entry = self._device_locations[bid]
            for pos in [p for p, h in entry.items() if h == host]:
                del entry[pos]
            if not entry:
                del self._device_locations[bid]
        self._device_report_ms.pop(host, None)
        # device (HBM) residency feeds listing wire dicts — stale cache
        # entries would steer locality reads at hosts that dropped out
        self.location_version += 1

    def prune_device_reports(self) -> List[str]:
        """Age out device reports from hosts that stopped renewing (a
        crashed JAX client can't call clear); driven by the same
        heartbeat as lost-worker detection."""
        now = self._clock.millis()
        expired = []
        with self._lock:
            for host, ts in list(self._device_report_ms.items()):
                if now - ts > self.device_report_ttl_ms:
                    self._drop_device_host(host)
                    expired.append(host)
        return expired

    def clear_device_blocks(self, host: str) -> None:
        self.report_device_blocks(host, {})

    def device_block_map(self) -> Dict[int, Dict[int, str]]:
        """block id -> {mesh position: host} (introspection/report)."""
        with self._lock:
            return {bid: dict(m)
                    for bid, m in self._device_locations.items()}

    def get_block_infos(self, block_ids: List[int]) -> List[BlockInfo]:
        out = []
        with self._lock:
            for bid in block_ids:
                meta = self._blocks.get(bid)
                if meta is not None:
                    out.append(self._block_info_locked(meta))
        return out

    def block_exists(self, block_id: int) -> bool:
        with self._lock:
            return block_id in self._blocks

    # ------------------------------------------------------------- queries
    def worker_count(self) -> int:
        with self._lock:
            return len(self._workers)

    def lost_worker_count(self) -> int:
        with self._lock:
            return len(self._lost_workers)

    def registered_worker_count(self) -> int:
        with self._lock:
            return sum(1 for w in self._workers.values() if w.registered)

    def get_worker_infos(self, include_lost: bool = False,
                         include_quarantined: bool = True
                         ) -> List[WorkerInfo]:
        """Worker listing.  ``include_quarantined=False`` is the
        PLACEMENT view: quarantined workers vanish from it, which is
        what makes quarantine effective — every placement chooser
        (client write policy, UFS read-through pick, prefetch agent,
        replication targets) selects from this listing.  The default
        keeps them visible (marked ``QUARANTINED``) for reporting,
        health watching and in-process admin callers."""
        with self._lock:
            out = []
            for w in self._workers.values():
                if w.id in self._quarantined:
                    if include_quarantined:
                        out.append(w.to_wire("QUARANTINED"))
                else:
                    out.append(w.to_wire("LIVE"))
            if include_lost:
                out += [w.to_wire("LOST") for w in self._lost_workers.values()]
            return out

    def get_worker(self, worker_id: int) -> Optional[MasterWorkerInfo]:
        with self._lock:
            return self._workers.get(worker_id)

    def worker_resident_blocks(self, worker_id: int
                               ) -> Optional[Dict[int, str]]:
        """Locked copy of one worker's block -> tier map (None for
        unknown/lost workers).  ``MasterWorkerInfo.blocks`` is mutated
        in place by worker heartbeats, so iterating the live dict from
        another thread (the remediation engine picking hot blocks)
        would race a concurrent add/remove."""
        with self._lock:
            info = self._workers.get(worker_id)
            return dict(info.blocks) if info is not None else None

    def all_block_ids(self) -> List[int]:
        """Snapshot of every block id in the master map (integrity scan)."""
        with self._lock:
            return list(self._blocks)

    def has_locations(self, block_id: int) -> bool:
        """True when at least one live worker holds the block."""
        with self._lock:
            return bool(self._locations.get(block_id))

    def lost_blocks(self) -> Set[int]:
        with self._lock:
            return set(self._lost_blocks)

    def capacity_bytes_on_tiers(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        with self._lock:
            for w in self._workers.values():
                for tier, n in w.capacity_bytes_on_tiers.items():
                    out[tier] = out.get(tier, 0) + n
        return out

    def used_bytes_on_tiers(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        with self._lock:
            for w in self._workers.values():
                for tier, n in w.used_bytes_on_tiers.items():
                    out[tier] = out.get(tier, 0) + n
        return out

    def top_tiers(self) -> "frozenset[str]":
        """Aliases of each live worker's FASTEST tier, from registered
        topology (workers register tiers top-down; dict order carries
        the ordinal). Replaces hardcoded device-tier name lists —
        tier semantics belong to worker metadata (reference:
        ``worker/block/meta/StorageTier.java:48``). Cached: recomputed
        on membership changes, read lock-free (it sits on every
        ``_file_info`` call in a ``list_status`` loop)."""
        return self._top_tiers

    def _refresh_top_tiers(self) -> None:
        """Caller holds ``self._lock``."""
        out = set()
        for w in self._workers.values():
            for tier in w.capacity_bytes_on_tiers:
                out.add(tier)
                break  # first registered = top tier
        self._top_tiers = frozenset(out)
        self.location_version += 1

    # ---------------------------------------------------- journal contract
    def process_entry(self, entry: JournalEntry) -> bool:
        t, p = entry.type, entry.payload
        if t == EntryType.BLOCK_INFO:
            with self._lock:
                self._blocks[p["block_id"]] = MasterBlockMeta(
                    block_id=p["block_id"], length=p["length"])
        elif t == EntryType.DELETE_BLOCK:
            with self._lock:
                self._blocks.pop(p["block_id"], None)
                self._locations.pop(p["block_id"], None)
                self._lost_blocks.discard(p["block_id"])
        elif t == EntryType.BLOCK_CONTAINER_ID and \
                p.get("owner") == self.journal_name:
            if self._journal.is_primary():
                # live self-apply: the generator already advanced past
                # the ids being reserved; jumping it to the mark would
                # burn the whole chunk and re-reserve on EVERY call.
                # Only track the covered range.
                self._container_reserved = max(
                    self._container_reserved, p["next_container_id"])
            else:
                # replay / standby tailing: resume above the mark
                self.container_ids.restore(p["next_container_id"])
        else:
            return False
        return True

    def snapshot(self) -> dict:
        with self._lock:
            return {
                # the RESERVED mark, not peek: a checkpoint GCs the
                # segment holding the reservation entry, so the snapshot
                # must carry the full covered range or replay would
                # re-issue ids handed out after the checkpoint
                "next_container_id": max(self.container_ids.peek,
                                         self._container_reserved),
                "blocks": [(m.block_id, m.length) for m in self._blocks.values()],
            }

    def restore(self, snap: dict) -> None:
        with self._lock:
            self._blocks = {bid: MasterBlockMeta(bid, length)
                            for bid, length in snap.get("blocks", [])}
            self.container_ids = ids.ContainerIdGenerator(
                snap.get("next_container_id", 1))
            self._container_reserved = snap.get("next_container_id", 1)
            self._locations.clear()
            self._lost_blocks.clear()
