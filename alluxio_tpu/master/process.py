"""Master process assembly.

Re-design of ``core/server/master/.../{AlluxioMaster.java:35,
AlluxioMasterProcess.java:97,156,197,300}``: journal boot -> gain primacy ->
replay -> start masters + heartbeats -> serve RPC, with a **safe-mode
window** after primacy during which client ops are rejected while workers
re-register (reference: ``DefaultSafeModeManager``).
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import List, Optional

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.heartbeat import (
    HeartbeatContext, HeartbeatExecutor, HeartbeatThread,
)
from alluxio_tpu.journal.system import create_journal_system
from alluxio_tpu.master.block_master import BlockMaster
from alluxio_tpu.master.file_master import FileSystemMaster
from alluxio_tpu.metrics import metrics
from alluxio_tpu.rpc.core import RpcServer
from alluxio_tpu.rpc.master_service import (
    block_master_service, fs_master_service, meta_master_service,
)
from alluxio_tpu.utils.clock import Clock, SystemClock

LOG = logging.getLogger(__name__)


class _Exec(HeartbeatExecutor):
    def __init__(self, fn) -> None:
        self._fn = fn

    def heartbeat(self) -> None:
        self._fn()


class MasterProcess:
    def __init__(self, conf: Configuration, *,
                 clock: Optional[Clock] = None,
                 root_ufs_uri: Optional[str] = None) -> None:
        self._conf = conf
        self._clock = clock or SystemClock()
        jtype = str(conf.get(Keys.MASTER_JOURNAL_TYPE)).upper()
        if jtype == "EMBEDDED":
            lo = conf.get_ms(Keys.MASTER_EMBEDDED_JOURNAL_ELECTION_TIMEOUT_MIN)
            hi = conf.get_ms(Keys.MASTER_EMBEDDED_JOURNAL_ELECTION_TIMEOUT_MAX)
            self.journal = create_journal_system(
                jtype, conf.get(Keys.MASTER_JOURNAL_FOLDER),
                address=str(conf.get(
                    Keys.MASTER_EMBEDDED_JOURNAL_ADDRESS)),
                addresses=str(conf.get(
                    Keys.MASTER_EMBEDDED_JOURNAL_ADDRESSES)),
                election_timeout_ms=(int(lo), int(hi)),
                heartbeat_interval_ms=int(conf.get_ms(
                    Keys.MASTER_EMBEDDED_JOURNAL_HEARTBEAT_INTERVAL)),
                snapshot_period_entries=conf.get_int(
                    Keys.MASTER_EMBEDDED_JOURNAL_SNAPSHOT_PERIOD_ENTRIES))
        else:
            self.journal = create_journal_system(
                jtype, conf.get(Keys.MASTER_JOURNAL_FOLDER),
                max_log_size=conf.get_bytes(
                    Keys.MASTER_JOURNAL_LOG_SIZE_BYTES_MAX),
                checkpoint_period_entries=conf.get_int(
                    Keys.MASTER_JOURNAL_CHECKPOINT_PERIOD_ENTRIES))
        self.block_master = BlockMaster(
            self.journal, clock=self._clock,
            worker_timeout_ms=conf.get_ms(Keys.MASTER_WORKER_TIMEOUT))
        from alluxio_tpu.security.authorization import PermissionChecker
        from alluxio_tpu.security.user import get_os_user

        checker = PermissionChecker(
            enabled=conf.get_bool(
                Keys.SECURITY_AUTHORIZATION_PERMISSION_ENABLED),
            supergroup=str(conf.get(
                Keys.SECURITY_AUTHORIZATION_PERMISSION_SUPERGROUP)),
            superuser=get_os_user())
        self.permission_checker = checker
        from alluxio_tpu.master.metastore import create_inode_store

        # pluggable metastore backend (reference: HEAP/ROCKS/caching):
        # HEAP serves from dicts; SQLITE spills metadata > RAM to disk;
        # LSM is the capacity backend (WAL + memtable + sorted runs,
        # caching-wrapped hot set); CACHING fronts SQLITE with a bounded
        # write-back LRU
        inode_store = create_inode_store(
            str(conf.get(Keys.MASTER_METASTORE)),
            conf.get(Keys.MASTER_METASTORE_DIR),
            cache_size=conf.get_int(
                Keys.MASTER_METASTORE_INODE_CACHE_MAX_SIZE),
            lsm_options={
                "memtable_bytes": conf.get_bytes(
                    Keys.MASTER_METASTORE_LSM_MEMTABLE_BYTES),
                "max_runs_per_tier": conf.get_int(
                    Keys.MASTER_METASTORE_LSM_COMPACTION_TRIGGER),
                "wal_sync": conf.get_bool(
                    Keys.MASTER_METASTORE_LSM_WAL_SYNC),
            })
        self.fs_master = FileSystemMaster(
            self.block_master, self.journal, clock=self._clock,
            inode_store=inode_store,
            default_block_size=conf.get_bytes(
                Keys.USER_BLOCK_SIZE_BYTES_DEFAULT),
            permission_checker=checker,
            umask=int(conf.get(Keys.SECURITY_AUTHORIZATION_PERMISSION_UMASK)),
            ufs_path_cache_capacity=conf.get_int(
                Keys.MASTER_UFS_PATH_CACHE_CAPACITY))
        from alluxio_tpu.master.path_properties import (
            ConfigurationChecker, PathProperties,
        )
        from alluxio_tpu.master.sync import ActiveSyncManager

        self.active_sync = ActiveSyncManager(self.fs_master, self.journal)
        self.path_properties = PathProperties(self.journal)
        from alluxio_tpu.table.master import TableMaster

        def _table_fs_factory():
            from alluxio_tpu.client.file_system import FileSystem
            from alluxio_tpu.conf import Configuration

            return FileSystem(self.address,
                              conf=Configuration(load_env=False))

        def _table_job_factory():
            from alluxio_tpu.rpc.job_service import JobMasterClient

            return JobMasterClient(
                f"localhost:{conf.get_int(Keys.JOB_MASTER_RPC_PORT)}",
                conf=conf)

        # registered with the journal BEFORE replay so catalog entries
        # from prior runs find their component
        self.table_master = TableMaster(self.journal,
                                        fs_factory=_table_fs_factory,
                                        job_client_factory=_table_job_factory)
        from alluxio_tpu.master.integrity import (
            BlockIntegrityChecker, LostFileDetector, UfsCleaner,
        )

        self.lost_file_detector = LostFileDetector(self.fs_master,
                                                   self.block_master)
        self.block_integrity_checker = BlockIntegrityChecker(
            self.fs_master, self.block_master)
        self.ufs_cleaner = UfsCleaner(
            self.fs_master.mount_table, self.fs_master._ufs,
            ttl_ms=conf.get_ms(Keys.MASTER_PERSISTENCE_TEMP_TTL))
        self.config_checker = ConfigurationChecker()
        self.config_checker.register(
            "master", {k: str(v) for k, v in conf.to_map().items()})
        self._root_ufs_uri = root_ufs_uri or \
            conf.get(Keys.MASTER_MOUNT_TABLE_ROOT_UFS) or \
            conf.get(Keys.HOME) + "/underFSStorage"
        self.rpc_server: Optional[RpcServer] = None
        self.metrics_master = None
        self.health_monitor = None
        self.remediation = None
        self.admission = None
        self._worker_lost_listener_installed = False
        self.web_server = None
        self.update_checker = None
        self.web_port: Optional[int] = None
        self._threads: List[HeartbeatThread] = []
        self.cluster_id = str(uuid.uuid4())
        self.start_time_ms = 0
        self._safe_mode_until = float("inf")
        self.rpc_port: Optional[int] = None
        from alluxio_tpu.journal.ha import MasterRegistry

        #: shared-journal presence registry behind `fsadmin report
        #: masters` and the quorum-degraded health sampling (docs/ha.md)
        self.master_registry = MasterRegistry(
            str(conf.get(Keys.MASTER_JOURNAL_FOLDER)))
        #: expected quorum size: the configured master list (client
        #: addresses, falling back to the raft member list); 0 = not HA
        self._ha_expected = max(
            len(self._conf_address_list(Keys.MASTER_RPC_ADDRESSES)),
            len(self._conf_address_list(
                Keys.MASTER_EMBEDDED_JOURNAL_ADDRESSES)))
        #: last quorum-liveness sample (health tick) — served as gauges
        #: and ingested as `master` history series (docs/ha.md)
        self._ha_live_sample = 1.0
        self._ha_lag_sample = 0.0
        #: (address-or-None, monotonic expiry) — bounds the registry
        #: directory scan leader_address costs on the standby read path
        self._leader_cache: "Optional[tuple]" = None
        #: publishes registry rows / runs the publish heartbeat: multi-
        #: master deployments only (FaultTolerantMasterProcess forces
        #: True — the file-lock flavor can run without a configured
        #: master list).  A plain single master must not grow a masters/
        #: dir it rewrites every second for nobody.
        self._ha_member = self._ha_expected > 1
        #: last metastore_stats() pull (refreshed on the health tick) —
        #: gauges must not take the store lock on every scrape
        self._metastore_sample: dict = {}
        reg = metrics()
        reg.register_gauge("Master.MetastoreInodes", lambda: float(
            self._metastore_sample.get("inodes", 0) or 0))
        reg.register_gauge("Master.MetastoreMemtableBytes", lambda: float(
            self._metastore_sample.get("memtable_bytes", 0) or 0))
        reg.register_gauge("Master.MetastoreRuns", lambda: float(
            self._metastore_sample.get("runs", 0) or 0))
        reg.register_gauge("Master.MetastoreCompactionBytes", lambda: float(
            self._metastore_sample.get("compaction_bytes", 0) or 0))
        reg.register_gauge("Master.MetastoreCacheHitRatio", lambda: float(
            self._metastore_sample.get("cache_hit_ratio", 0.0) or 0.0))
        if self._ha_expected > 1:
            reg.register_gauge("Master.HaQuorumExpected",
                               lambda: float(self._ha_expected))
            reg.register_gauge("Master.HaQuorumLive",
                               lambda: self._ha_live_sample)
            reg.register_gauge("Master.HaStandbyLagEntries",
                               lambda: self._ha_lag_sample)

    def _conf_address_list(self, key) -> List[str]:
        return [a.strip() for a in str(self._conf.get(key) or "").split(",")
                if a.strip()]

    # -- safe mode ----------------------------------------------------------
    def _sample_metadata_history(self) -> None:
        """Push the metadata control plane's own gauges into the history
        rings as ``master``-source series on the health tick (same
        pattern as the remediation/admission samples): inode-lock wait
        p99 — what the metadata-lock-contention rule watches — plus
        journal group-commit batch/flush shape and the invalidation-log
        counter."""
        history = self.metrics_master.history \
            if self.metrics_master is not None else None
        if history is None:
            return
        reg = metrics()
        history.ingest("master", {
            "Master.MetadataInodeLockWaitTime.p99":
                reg.timer("Master.MetadataInodeLockWaitTime")
                .percentile(0.99),
            "Master.MetadataJournalBatchSize.p50":
                reg.timer("Master.MetadataJournalBatchSize")
                .percentile(0.50),
            "Master.MetadataJournalFlushTime.p99":
                reg.timer("Master.MetadataJournalFlushTime")
                .percentile(0.99),
            "Master.MetadataCacheInvalidations": float(
                reg.counter("Master.MetadataCacheInvalidations").count),
        })
        # metastore shape: inode population, LSM memtable/run debt and
        # hot-set hit ratio — what the metastore-compaction-debt rule
        # watches.  HEAP/SQLITE backends report zeros for the LSM-only
        # series, which keeps the rule inert on those backends.
        try:
            self._metastore_sample = dict(
                self.fs_master.metastore_stats())
        except Exception:
            LOG.debug("metastore stats sample failed", exc_info=True)
        stats = self._metastore_sample
        history.ingest("master", {
            "Master.MetastoreInodes": float(stats.get("inodes", 0) or 0),
            "Master.MetastoreMemtableBytes":
                float(stats.get("memtable_bytes", 0) or 0),
            "Master.MetastoreRuns": float(stats.get("runs", 0) or 0),
            "Master.MetastoreCompactionBytes":
                float(stats.get("compaction_bytes", 0) or 0),
            "Master.MetastoreCacheHitRatio":
                float(stats.get("cache_hit_ratio", 0.0) or 0.0),
        })

    def in_safe_mode(self) -> bool:
        return time.monotonic() < self._safe_mode_until

    # -- HA quorum view ------------------------------------------------------
    #: a registry row older than this is counted dead by the quorum-
    #: degraded sampling (3 missed refresh ticks, floor 3s for jittery
    #: test hosts).  Standbys refresh their row on the journal-tailer
    #: tick, not the publish heartbeat, so the threshold must cover the
    #: SLOWER of the two cadences — else an operator raising the tail
    #: interval makes every healthy standby read as dead and latches
    #: the master-quorum-degraded alert on a healthy quorum.
    def _ha_live_threshold_s(self) -> float:
        return max(3.0,
                   3 * self._conf.get_duration_s(
                       Keys.MASTER_HA_PUBLISH_INTERVAL),
                   3 * self._conf.get_duration_s(
                       Keys.MASTER_STANDBY_TAIL_INTERVAL))

    @property
    def client_address(self) -> str:
        """The address clients reach THIS master at (conf hostname +
        the actually-bound RPC/standby port)."""
        port = self.rpc_port or getattr(self, "standby_rpc_port", None) or \
            self._conf.get_int(Keys.MASTER_RPC_PORT)
        return f"{self._conf.get(Keys.MASTER_HOSTNAME)}:{port}"

    def _raft_to_client_address(self, raft_addr: str) -> Optional[str]:
        """Map a raft member address to its client RPC address by list
        position (``atpu.master.rpc.addresses`` zipped with
        ``atpu.master.embedded.journal.addresses``, the reference's
        convention)."""
        rpc = self._conf_address_list(Keys.MASTER_RPC_ADDRESSES)
        raft = self._conf_address_list(
            Keys.MASTER_EMBEDDED_JOURNAL_ADDRESSES)
        if raft_addr in raft and len(rpc) == len(raft):
            return rpc[raft.index(raft_addr)]
        return None

    def leader_address(self) -> Optional[str]:
        """Best-known current primary (client address) — the hint a
        standby's NotPrimaryError carries.  None when unknown.  A bound
        primary RPC port plus live journal primacy IS primacy here: the
        FT ``serving`` flag flips only after ``_start_serving`` returns,
        and the registry must not publish a freshly-promoted master as a
        standby in between.  The primacy check matters on the way DOWN
        too: a deposed leader whose RPC server has not stopped yet must
        hint the NEW leader (or nothing), never itself — a self-hint
        would spin redirected clients on the deposed master."""
        if self.rpc_port and self.journal.is_primary():
            return self.client_address
        node = getattr(self.journal, "node", None)
        if node is not None:  # EMBEDDED: raft leader, mapped to rpc addr
            leader_id = node.leader_id
            if leader_id and leader_id != node.node_id:
                return self._raft_to_client_address(leader_id)
            return None
        # shared-journal flavor: freshest published PRIMARY row.  The
        # scan is synchronous disk IO (listdir + per-row json) and every
        # standby-served read resolves the hint, so cache the answer for
        # a fraction of the publish interval — the rows themselves are
        # never fresher than that interval, and a wrong hint only costs
        # the client one redirect hop
        now = time.monotonic()
        cached = self._leader_cache
        if cached is not None and now < cached[1]:
            return cached[0]
        limit = self._ha_live_threshold_s()
        best = None
        for row in self.master_registry.list():
            if row.get("role") != "PRIMARY":
                continue
            if row.get("last_contact_s", limit) >= limit:
                continue
            if row.get("address") == self.client_address:
                continue  # ourselves (stale row from a previous term)
            if best is None or row["last_contact_s"] < \
                    best["last_contact_s"]:
                best = row
        addr = best["address"] if best else None
        ttl = 0.5 * self._conf.get_duration_s(
            Keys.MASTER_HA_PUBLISH_INTERVAL)
        self._leader_cache = (addr, now + ttl)
        return addr

    def _publish_registry(self) -> None:
        """One registry row for this master (role, applied sequence,
        term) — primaries publish on their own heartbeat, standbys on
        the tailer tick.  Role rides the same port+primacy signal as
        ``leader_address`` so a deposed-but-not-demoted master never
        advertises PRIMARY."""
        if not self._ha_member:
            return
        # never publish an unreachable row: before a port is bound the
        # address falls back to conf MASTER_RPC_PORT, which tests (and
        # ephemeral-port deployments) set to 0 — a ":0" row would sit in
        # the file-per-address registry forever, poisoning quorum views
        if self.client_address.endswith(":0"):
            return
        role = "PRIMARY" if self.rpc_port and self.journal.is_primary() \
            else "STANDBY"
        node = getattr(self.journal, "node", None)
        term = node.log.term if node is not None else 0
        self.master_registry.publish(
            self.client_address, role=role,
            sequence=int(getattr(self.journal, "sequence", 0)), term=term)

    def masters_report(self) -> dict:
        """The quorum view served by ``get_masters`` (`fsadmin report
        masters`, statuspage "Masters"): one row per known master,
        merged from the shared-journal registry and — under the
        EMBEDDED journal — live Raft quorum state."""
        rows: dict = {}
        for row in self.master_registry.list():
            rows[row["address"]] = dict(row)
        self._publish_registry()  # our own row, fresh
        me = rows[self.client_address] = {
            "address": self.client_address,
            "role": "PRIMARY" if self.rpc_port and
            self.journal.is_primary() else "STANDBY",
            "sequence": int(getattr(self.journal, "sequence", 0)),
            "term": 0, "last_contact_s": 0.0,
        }
        tailer = getattr(self, "_tailer", None)
        if tailer is not None and me["role"] == "STANDBY":
            me["tailer_lag_s"] = max(
                0.0, time.monotonic() - tailer.last_caught_up)
        quorum = None
        if hasattr(self.journal, "quorum_info"):
            quorum = self.journal.quorum_info()
            me["term"] = quorum.get("term", 0)
            for m in quorum.get("members", []):
                addr = self._raft_to_client_address(m["node_id"]) or \
                    m["node_id"]
                if addr == self.client_address:
                    continue
                row = rows.setdefault(addr, {"address": addr,
                                             "sequence": None})
                row["role"] = {"LEADER": "PRIMARY",
                               "FOLLOWER": "STANDBY"}.get(
                    m.get("role", ""), "UNKNOWN")
                row["term"] = quorum.get("term", 0)
                row["match_index"] = m.get("match_index")
                row["last_contact_s"] = m.get("last_contact_s")
        # lag relative to the furthest-applied member we can see; raft
        # members without a registry row still report replication
        # progress through the leader's match_index
        def _applied(r):
            return r["sequence"] if r.get("sequence") is not None \
                else r.get("match_index")

        seqs = [_applied(r) for r in rows.values()
                if _applied(r) is not None]
        head = max(seqs) if seqs else 0
        for r in rows.values():
            if _applied(r) is not None:
                r["lag_entries"] = head - _applied(r)
        out = {"leader": self.leader_address(),
               "masters": sorted(rows.values(),
                                 key=lambda r: r["address"])}
        if quorum is not None:
            out["quorum"] = quorum
        return out

    def _sample_ha_history(self) -> None:
        """Quorum liveness gauges into the history rings on the health
        tick (``master`` source): what the ``master-quorum-degraded``
        rule watches (docs/ha.md)."""
        if self._ha_expected <= 1:
            return
        history = self.metrics_master.history \
            if self.metrics_master is not None else None
        if history is None:
            return
        limit = self._ha_live_threshold_s()
        live = 1  # ourselves
        lag = 0
        node = getattr(self.journal, "node", None)
        if node is not None:
            info = node.quorum_info()
            for m in info.get("members", []):
                age = m.get("last_contact_s")
                if m.get("address") != "self" and age is not None and \
                        age < limit:
                    live += 1
            follower_match = [m.get("match_index", 0)
                              for m in info.get("members", [])
                              if m.get("address") != "self"]
            if follower_match:
                lag = max(0, info.get("commit_index", 0)
                          - min(follower_match))
        else:
            my_seq = int(getattr(self.journal, "sequence", 0))
            for row in self.master_registry.list():
                if row.get("address") == self.client_address:
                    continue
                if row.get("last_contact_s", limit) < limit:
                    live += 1
                    lag = max(lag, my_seq - int(row.get("sequence", 0)))
        self._ha_live_sample = float(live)
        self._ha_lag_sample = float(lag)
        history.ingest("master", {
            "Master.HaQuorumExpected": float(self._ha_expected),
            "Master.HaQuorumLive": float(live),
            "Master.HaStandbyLagEntries": float(lag),
        })

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> int:
        """Boot straight to primary; returns the bound RPC port."""
        from alluxio_tpu.utils.pause_monitor import ensure_process_monitor
        from alluxio_tpu.utils.tracing import (
            apply_trace_conf, set_tracing_enabled,
        )

        set_tracing_enabled(self._conf.get_bool(Keys.TRACE_ENABLED))
        apply_trace_conf(self._conf)
        from alluxio_tpu.utils.profiler import apply_profile_conf

        apply_profile_conf(self._conf)
        # stall detector (reference: JvmPauseMonitor started at
        # AlluxioMasterProcess.java:265-273): a paused master misses
        # heartbeats and trips elections — make it visible. ONE per
        # process: in-process clusters share the host stall.
        ensure_process_monitor()
        self.journal.start()
        backup = self._conf.get(Keys.MASTER_JOURNAL_INIT_FROM_BACKUP)
        if backup and hasattr(self.journal, "init_from_backup"):
            # seed an empty journal from a metadata backup (reference:
            # initFromBackup, AlluxioMasterProcess.java:173-190)
            self.journal.init_from_backup(str(backup))
        self.journal.gain_primacy()
        return self._start_serving()

    def _start_serving(self) -> int:
        """Primacy is held: start masters, heartbeats and the RPC server."""
        self.start_time_ms = self._clock.millis()
        if hasattr(self.journal, "start_group_commit"):
            # dedicated group-commit flusher: journal writes + fsyncs
            # leave the striped inode-lock critical sections
            self.journal.start_group_commit(self._conf.get_duration_s(
                Keys.MASTER_JOURNAL_FLUSH_BATCH_TIME))
        self.fs_master.start(self._root_ufs_uri)
        self._safe_mode_until = time.monotonic() + self._conf.get_duration_s(
            Keys.MASTER_SAFEMODE_WAIT)
        metrics("Master")
        from alluxio_tpu.security.audit import AsyncAuditLogWriter
        from alluxio_tpu.security.authentication import Authenticator
        from alluxio_tpu.utils import faults

        # arm the conf-gated fault hooks (atpu.debug.fault.*): the
        # rpc.reject.rate drill sheds master dispatches, so the master
        # must read the keys too, not just workers
        faults.injector().configure(self._conf)
        self.audit_writer = AsyncAuditLogWriter()
        self.audit_writer.start()
        self.admission = None
        if self._conf.get_bool(Keys.MASTER_RPC_ADMISSION_ENABLED):
            from alluxio_tpu.qos.admission import (
                AdmissionConf, AdmissionController,
            )

            # built BEFORE the metrics master so the tenant-overload
            # health rule can close over it; shed RPCs are audited
            # with allowed=False next to the permission denials
            self.admission = AdmissionController(
                AdmissionConf.from_conf(self._conf),
                audit_writer=self.audit_writer)
        self._init_metrics_master()
        self._start_heartbeats()
        authenticator = Authenticator(self._conf)
        self.rpc_server = RpcServer(
            bind_host="0.0.0.0",
            port=self._conf.get_int(Keys.MASTER_RPC_PORT),
            authenticator=authenticator,
            admission=self.admission)
        self.rpc_server.add_service(fs_master_service(
            self.fs_master, active_sync=self.active_sync,
            audit_writer=self.audit_writer))
        self.rpc_server.add_service(block_master_service(self.block_master))
        from alluxio_tpu.rpc.table_service import table_master_service

        self.rpc_server.add_service(table_master_service(
            self.table_master,
            permission_checker=self.permission_checker))
        self.rpc_server.add_service(meta_master_service(
            self._conf, cluster_id=self.cluster_id,
            start_time_ms=self.start_time_ms,
            safe_mode_fn=self.in_safe_mode, journal=self.journal,
            path_properties=self.path_properties,
            config_checker=self.config_checker,
            permission_checker=self.permission_checker,
            metrics_master=self.metrics_master,
            health_monitor=self.health_monitor,
            remediation_engine=self.remediation,
            admission=self.admission,
            invalidation_log=self.fs_master.invalidations,
            masters_fn=self.masters_report,
            metastore_stats_fn=self.fs_master.metastore_stats))
        self.rpc_port = self.rpc_server.start()
        # announce primacy to the quorum view the moment the port is
        # bound, then keep the row fresh on its own heartbeat
        from alluxio_tpu.utils.exceptions import best_effort

        if self._ha_member:
            best_effort("master registry publish",
                        self._publish_registry)
            self._threads.append(HeartbeatThread(
                HeartbeatContext.MASTER_LOST_MASTER_DETECTION,
                _Exec(self._publish_registry),
                self._conf.get_duration_s(
                    Keys.MASTER_HA_PUBLISH_INTERVAL)))
            self._threads[-1].start()
        if self._conf.get_bool(Keys.MASTER_FASTPATH_ENABLED):
            from alluxio_tpu.rpc.fastpath import (
                FastPathServer, socket_path_for,
            )

            self.fastpath_server = FastPathServer(
                socket_path_for(
                    f"localhost:{self.rpc_port}",
                    self._conf.get(Keys.MASTER_FASTPATH_DIR)),
                authenticator=authenticator,
                admission=self.admission)
            for svc in self.rpc_server._services.values():
                self.fastpath_server.add_service(svc)
            self.fastpath_server.start()
        if self._conf.get_bool(Keys.MASTER_WEB_ENABLED):
            from alluxio_tpu.master.web import MasterWebServer

            self.web_server = MasterWebServer(
                self, port=self._conf.get_int(Keys.MASTER_WEB_PORT),
                bind_host=self._conf.get(Keys.MASTER_WEB_BIND_HOST))
            self.web_port = self.web_server.start()
        return self.rpc_port

    def _init_metrics_master(self) -> None:
        """Metrics history + health-rule engine (cluster doctor),
        assembled before the heartbeats that tick them.  A lost worker
        leaves the aggregates immediately: its snapshot is cleared and
        its history series get an explicit end marker instead of
        lingering for the source TTL."""
        conf = self._conf
        from alluxio_tpu.master.metrics_master import (
            MetricsMaster, MetricsStore,
        )

        max_sources = conf.get_int(Keys.MASTER_METRICS_MAX_SOURCES)
        store = MetricsStore(max_sources=max_sources)
        history = None
        if conf.get_bool(Keys.MASTER_METRICS_HISTORY_ENABLED):
            import math

            from alluxio_tpu.metrics.history import MetricsHistory

            prefixes = tuple(
                p.strip() for p in str(conf.get(
                    Keys.MASTER_METRICS_HISTORY_ALLOW_PREFIXES)).split(",")
                if p.strip())
            # bound the offer queue by what can actually accumulate
            # between two drain ticks under the operator's conf: one
            # offer per source per report interval, over the drain
            # (health-eval) period, 2x for interval jitter — a raised
            # source cap or a slowed eval interval must not turn into
            # silent per-cycle tick drops
            report_s = max(0.001, min(
                conf.get_duration_s(Keys.WORKER_METRICS_HEARTBEAT_INTERVAL),
                conf.get_duration_s(Keys.USER_METRICS_HEARTBEAT_INTERVAL)))
            drains_behind = max(1, math.ceil(conf.get_duration_s(
                Keys.MASTER_HEALTH_EVAL_INTERVAL) / report_s))
            history = MetricsHistory(
                capacity=conf.get_int(Keys.MASTER_METRICS_HISTORY_CAPACITY),
                retention_s=conf.get_duration_s(
                    Keys.MASTER_METRICS_HISTORY_RETENTION),
                max_series=conf.get_int(
                    Keys.MASTER_METRICS_HISTORY_MAX_SERIES),
                allow_prefixes=prefixes,
                pending_max=2 * max_sources * drains_behind)
            reg = metrics()
            reg.register_gauge("Master.MetricsHistorySeries",
                               lambda: float(history.series_count()))
            reg.register_gauge("Master.MetricsHistorySamplesDropped",
                               lambda: float(history.dropped_samples))
            reg.register_gauge("Master.MetricsHistoryTicksDropped",
                               lambda: float(history.dropped_ticks))
        self.metrics_master = MetricsMaster(store=store, history=history)
        self.health_monitor = None
        if conf.get_bool(Keys.MASTER_HEALTH_ENABLED):
            from alluxio_tpu.master.health import (
                HealthMonitor, default_rules,
            )

            rules = default_rules(
                stall_threshold=conf.get_float(
                    Keys.MASTER_HEALTH_STALL_THRESHOLD),
                stall_window_s=conf.get_duration_s(
                    Keys.MASTER_HEALTH_STALL_WINDOW),
                inode_lock_wait_p99_s=conf.get_duration_s(
                    Keys.MASTER_HEALTH_METADATA_LOCK_WAIT_THRESHOLD))
            if self.admission is not None:
                from alluxio_tpu.master.health import (
                    tenant_overload_rule,
                )

                # flags a principal whose master RPCs are being shed
                # at a sustained rate — the doctor names the tenant
                # exceeding its share instead of operators diffing
                # audit logs
                rules.append(tenant_overload_rule(
                    self.admission.shed_counts))
            if self._ha_expected > 1:
                from alluxio_tpu.master.health import (
                    quorum_degraded_rule,
                )

                # a lost standby costs nothing TODAY — which is exactly
                # why it must alert: the next failure is the outage
                rules.append(quorum_degraded_rule(self._ha_expected))
            from alluxio_tpu.master.health import (
                metastore_compaction_debt_rule,
            )

            # inert on HEAP/SQLITE (they report zero runs); on LSM it
            # catches compaction losing the race with flushes before
            # read amplification turns into an outage
            rules.append(metastore_compaction_debt_rule(
                conf.get_int(Keys.MASTER_METASTORE_COMPACTION_DEBT_RUNS)))
            if history is None:
                # don't advertise rules that silently no-op without
                # the history store: the report must only list rules
                # that are genuinely watching
                dropped = [r.name for r in rules if r.needs_history]
                rules = [r for r in rules if not r.needs_history]
                LOG.warning(
                    "health enabled without metrics history "
                    "(atpu.master.metrics.history.enabled=false): "
                    "rules %s are disabled, only %s remain active",
                    dropped, [r.name for r in rules])
            def _expected_worker_sources():
                # LIVE registered workers only (a lost worker is the
                # worker-lost rule's business) with time since their
                # LAST registration (stamped by the listener below) —
                # NOT start_time_ms, which survives loss/recovery and
                # would false-fire the missing-source staleness alert
                # for the whole grace window after every routine
                # worker re-registration.  Unknown sources read as
                # age 0 (alert suppressed): conservative until their
                # registration is observed.
                now = time.time()
                reg = self._worker_registered_at
                out = []
                for i in self.block_master.get_worker_infos():
                    src = f"worker-{i.address.host}:" \
                          f"{i.address.rpc_port}"
                    at = reg.get(src)
                    out.append((src, max(0.0, now - at)
                                if at is not None else 0.0))
                return out

            self.health_monitor = HealthMonitor(
                self.metrics_master,
                rules=rules,
                fire_after_s=conf.get_duration_s(
                    Keys.MASTER_HEALTH_FIRE_AFTER),
                resolve_after_s=conf.get_duration_s(
                    Keys.MASTER_HEALTH_RESOLVE_AFTER),
                eval_interval_s=conf.get_duration_s(
                    Keys.MASTER_HEALTH_EVAL_INTERVAL),
                worker_sources_fn=_expected_worker_sources)

        self.remediation = None
        if self.health_monitor is not None and \
                conf.get_bool(Keys.MASTER_REMEDIATION_ENABLED):
            from alluxio_tpu.master.remediation import RemediationEngine

            # default-off: with the key false this block never runs —
            # no engine object, no alert listener, no overlay in the
            # heartbeat response, no remediation in get_health
            self.remediation = RemediationEngine(
                self.block_master,
                metrics_master=self.metrics_master,
                dry_run=conf.get_bool(Keys.MASTER_REMEDIATION_DRY_RUN),
                max_actions_per_window=conf.get_int(
                    Keys.MASTER_REMEDIATION_MAX_ACTIONS_PER_WINDOW),
                window_s=conf.get_duration_s(
                    Keys.MASTER_REMEDIATION_WINDOW),
                cooldown_s=conf.get_duration_s(
                    Keys.MASTER_REMEDIATION_COOLDOWN),
                probation_s=conf.get_duration_s(
                    Keys.MASTER_REMEDIATION_PROBATION),
                rereplicate_blocks=conf.get_int(
                    Keys.MASTER_REMEDIATION_REREPLICATE_BLOCKS),
                quarantine_max_fraction=conf.get_float(
                    Keys.MASTER_REMEDIATION_QUARANTINE_MAX_FRACTION),
                hedge_quantile_base=conf.get_float(
                    Keys.USER_REMOTE_READ_HEDGE_QUANTILE),
                remote_concurrency_base=conf.get_int(
                    Keys.USER_REMOTE_READ_CONCURRENCY),
                prefetch_budget_base=conf.get_bytes(
                    Keys.PREFETCH_BUDGET_BYTES))
            self.health_monitor.alert_listeners.append(
                self.remediation.on_alerts)

        # source -> wall time of its last full registration; reset on
        # (re-)init conservatively — ages restart at 0, which only
        # delays the missing-source staleness alert by its grace
        self._worker_registered_at = {}

        def _on_worker_lost(info) -> None:
            source = f"worker-{info.address.host}:{info.address.rpc_port}"
            self._worker_registered_at.pop(source, None)
            # block=True: a lost-but-chatty worker's metrics heartbeats
            # must not re-admit its snapshot into Cluster.* aggregates
            self.metrics_master.store.clear_source(source, block=True)
            if self.metrics_master.history is not None:
                # fold still-queued offers first so a pre-death
                # heartbeat drained later cannot clear the end marker
                self.metrics_master.drain_history()
                self.metrics_master.history.end_source(source)

        def _on_worker_registered(info) -> None:
            # full block-list re-registration is the only revival
            # signal: metrics heartbeats alone must not clear the end
            # marker or unblock the store (a lost worker with a wedged
            # block-sync thread still ships metrics while serving
            # nothing)
            source = f"worker-{info.address.host}:{info.address.rpc_port}"
            self._worker_registered_at[source] = time.time()
            self.metrics_master.store.unblock_source(source)
            if self.metrics_master.history is not None:
                self.metrics_master.history.revive_source(source)

        def _on_location_drift(block_ids) -> None:
            """Block-location drift (worker loss/quarantine/release,
            re-replication) -> journaled ``INVALIDATE_PATH`` entries:
            client caches repair their location-derived fields on the
            next heartbeat instead of waiting out the cache TTL, and —
            because the invalidation log only ever advances at journal
            apply — tailing standbys count the same md_version the
            primary stamps (docs/ha.md).  A mass event (whole worker's
            residents) collapses to one root invalidation — full cache
            drop beats flooding the bounded ring off its horizon one
            path at a time."""
            from alluxio_tpu.utils import ids as _ids

            if len(block_ids) > 1024:
                self.fs_master.journal_invalidations(["/"])
                return
            tree = self.fs_master.inode_tree
            paths = set()
            with tree.lock.read_locked():
                for fid in {_ids.file_id_for_block(b) for b in block_ids}:
                    uri = tree.path_of_id(fid)
                    if uri is not None:
                        paths.add(uri.path)
            self.fs_master.journal_invalidations(sorted(paths))

        # once per process: _start_serving re-runs on every HA
        # re-promotion, and the closures resolve self.metrics_master at
        # call time, so a second registration would only duplicate work
        if not self._worker_lost_listener_installed:
            self.block_master.lost_worker_listeners.append(_on_worker_lost)
            self.block_master.registered_worker_listeners.append(
                _on_worker_registered)
            self.block_master.location_change_listeners.append(
                _on_location_drift)
            self._worker_lost_listener_installed = True

    def _start_heartbeats(self) -> None:
        conf = self._conf
        self._threads = [
            HeartbeatThread(
                HeartbeatContext.MASTER_LOST_WORKER_DETECTION,
                _Exec(self.block_master.detect_lost_workers),
                conf.get_duration_s(Keys.MASTER_LOST_WORKER_DETECTION_INTERVAL)),
            HeartbeatThread(
                HeartbeatContext.MASTER_TTL_CHECK,
                _Exec(self.fs_master.check_ttl_expired),
                conf.get_duration_s(Keys.MASTER_TTL_CHECK_INTERVAL)),
            HeartbeatThread(
                HeartbeatContext.MASTER_ACTIVE_SYNC,
                _Exec(self.active_sync.heartbeat),
                conf.get_duration_s(Keys.MASTER_ACTIVE_SYNC_INTERVAL)),
            HeartbeatThread(
                HeartbeatContext.MASTER_TABLE_TRANSFORM_MONITOR,
                _Exec(self.table_master.heartbeat),
                conf.get_duration_s(Keys.TABLE_TRANSFORM_MONITOR_INTERVAL)),
            HeartbeatThread(
                HeartbeatContext.MASTER_LOST_FILES_DETECTION,
                _Exec(self.lost_file_detector.heartbeat),
                conf.get_duration_s(
                    Keys.MASTER_LOST_FILES_DETECTION_INTERVAL)),
            HeartbeatThread(
                HeartbeatContext.MASTER_BLOCK_INTEGRITY_CHECK,
                _Exec(self.block_integrity_checker.heartbeat),
                conf.get_duration_s(
                    Keys.MASTER_BLOCK_INTEGRITY_CHECK_INTERVAL)),
            HeartbeatThread(
                HeartbeatContext.MASTER_UFS_CLEANUP,
                _Exec(self.ufs_cleaner.heartbeat),
                conf.get_duration_s(Keys.MASTER_UFS_CLEANUP_INTERVAL)),
        ]
        def _health_tick() -> None:
            if self.health_monitor is not None:
                self.health_monitor.evaluate()
            elif self.metrics_master.history is not None:
                # health disabled but history on: evaluate() normally
                # drains the pending offers, so tick the drain directly
                # or the bounded pending queue overflows between queries
                self.metrics_master.drain_history()
            if self.admission is not None:
                # Master.RpcAdmission* series ride the same tick the
                # remediation samples do: flood shapes stay visible in
                # `fsadmin report history` after the flood is gone
                self.admission.sample_history(self.metrics_master.history)
            self._sample_metadata_history()
            self._sample_ha_history()

        if self.health_monitor is not None or \
                self.metrics_master.history is not None:
            self._threads.append(HeartbeatThread(
                HeartbeatContext.MASTER_HEALTH_CHECK, _Exec(_health_tick),
                conf.get_duration_s(Keys.MASTER_HEALTH_EVAL_INTERVAL)))
        if conf.get_bool(Keys.MASTER_UPDATE_CHECK_ENABLED):
            url = conf.get(Keys.MASTER_UPDATE_CHECK_URL) or ""
            if not url:
                LOG.warning(
                    "%s is enabled but %s is unset — update checking "
                    "is a no-op", Keys.MASTER_UPDATE_CHECK_ENABLED,
                    Keys.MASTER_UPDATE_CHECK_URL)
            else:
                from alluxio_tpu.master.update_check import UpdateChecker

                self.update_checker = UpdateChecker(url)
                self._threads.append(HeartbeatThread(
                    HeartbeatContext.MASTER_UPDATE_CHECK,
                    self.update_checker,
                    conf.get_duration_s(
                        Keys.MASTER_UPDATE_CHECK_INTERVAL)))
        if conf.get_bool(Keys.MASTER_DAILY_BACKUP_ENABLED):
            from alluxio_tpu.master.backup import ScheduledBackup

            self.scheduled_backup = ScheduledBackup(
                self.journal, conf.get(Keys.MASTER_BACKUP_DIR),
                interval_s=conf.get_duration_s(
                    Keys.MASTER_DAILY_BACKUP_INTERVAL),
                retention=conf.get_int(Keys.MASTER_DAILY_BACKUP_RETENTION))
            # ticked well under the backup interval so a missed beat
            # only delays, never skips, a due backup
            self._threads.append(HeartbeatThread(
                HeartbeatContext.MASTER_DAILY_BACKUP,
                _Exec(self.scheduled_backup.heartbeat),
                min(60.0, conf.get_duration_s(
                    Keys.MASTER_DAILY_BACKUP_INTERVAL))))
        from alluxio_tpu.metrics import metrics as _metrics
        from alluxio_tpu.metrics.sinks import SinkManager

        self.sink_manager = SinkManager(conf, _metrics())
        if self.sink_manager.sinks:
            # the manager itself is the executor (heartbeat + close), so
            # sinks are closed on thread shutdown — same shape as the
            # worker side
            self._threads.append(HeartbeatThread(
                HeartbeatContext.MASTER_METRICS_SINKS, self.sink_manager,
                conf.get_duration_s(Keys.METRICS_SINK_INTERVAL)))
        for t in self._threads:
            t.start()

    def attach_replication_checker(self, job_client,
                                   interval_s: Optional[float] = None) -> None:
        """Start the replication-control loop once a job service exists
        (reference: ``ReplicationChecker.java:57`` registered as an FSM
        heartbeat; here the job master boots after the metadata master, so
        the checker attaches late)."""
        from alluxio_tpu.heartbeat import HeartbeatContext as HC
        from alluxio_tpu.master.replication import ReplicationChecker

        checker = ReplicationChecker(
            self.fs_master, self.block_master, job_client,
            max_inflight=self._conf.get_int(
                Keys.MASTER_REPLICATION_MAX_INFLIGHT))
        self.replication_checker = checker
        if self.remediation is not None:
            # the re-replication action needs the job service; like the
            # checker itself it binds late, once one exists
            self.remediation.bind_replication(checker)
        t = HeartbeatThread(
            HC.MASTER_REPLICATION_CHECK, _Exec(checker.heartbeat),
            interval_s if interval_s is not None else
            self._conf.get_duration_s(
                Keys.MASTER_REPLICATION_CHECK_INTERVAL))
        t.start()
        self._threads.append(t)

    def attach_persistence_scheduler(self, job_client,
                                     interval_s: Optional[float] = None
                                     ) -> "PersistenceScheduler":
        """Start the async-persist scheduling loop once a job service
        exists (reference: the PersistenceScheduler heartbeat,
        ``DefaultFileSystemMaster.java:3810`` — attaches late here for the
        same reason as the replication checker)."""
        from alluxio_tpu.heartbeat import HeartbeatContext as HC
        from alluxio_tpu.master.persistence import PersistenceScheduler

        scheduler = PersistenceScheduler(self.fs_master, job_client)
        t = HeartbeatThread(
            HC.MASTER_PERSISTENCE_SCHEDULER, _Exec(scheduler.heartbeat),
            interval_s if interval_s is not None else
            self._conf.get_duration_s(
                Keys.MASTER_PERSISTENCE_SCHEDULER_INTERVAL))
        t.start()
        self._threads.append(t)
        return scheduler

    def stop(self) -> None:
        for t in self._threads:
            t.stop()
        if getattr(self, "web_server", None) is not None:
            self.web_server.stop()
        if getattr(self, "fastpath_server", None) is not None:
            self.fastpath_server.stop()
        if self.rpc_server is not None:
            self.rpc_server.stop()
        if getattr(self, "audit_writer", None) is not None:
            self.audit_writer.stop()
        self.fs_master.stop()
        self.journal.stop()
        from alluxio_tpu.utils.exceptions import best_effort

        best_effort("master registry withdraw",
                    self.master_registry.withdraw, self.client_address)

    @property
    def address(self) -> str:
        return f"localhost:{self.rpc_port}"


class FaultTolerantMasterProcess(MasterProcess):
    """HA master: boots as a journal-tailing standby and starts serving
    when the primary selector grants primacy (reference:
    ``FaultTolerantAlluxioMasterProcess`` + standby tailing)."""

    def __init__(self, conf: Configuration, *, selector=None, **kwargs
                 ) -> None:
        super().__init__(conf, **kwargs)
        from alluxio_tpu.journal.ha import (
            FileLockPrimarySelector, JournalTailer,
        )

        # standby-serving torn-read exclusion: the standby apply paths
        # (tailer tick, raft apply loop) hold no inode-path locks, so a
        # concurrently served read could observe a half-applied
        # rename/delete — a state no journal version ever contained,
        # which would break the advertised staleness contract.  Holding
        # the tree-wide WRITE lock around each apply batch excludes the
        # read handlers (which hold it in read mode via lock_path); it
        # is acquired OUTSIDE the journal/node locks, the same
        # tree-first canonical order the primary's RPC paths use
        # (docs/ha.md).
        def _apply_exclusion():
            return self.fs_master.inode_tree.lock.write_locked()

        if selector is not None:
            self.selector = selector
        else:
            from alluxio_tpu.journal.raft import (
                EmbeddedJournalSystem, RaftPrimarySelector,
            )

            if isinstance(self.journal, EmbeddedJournalSystem):
                # embedded journal: Raft election IS primary election, and
                # followers apply continuously (no tailer needed)
                self.selector = RaftPrimarySelector(self.journal)
                self.journal.node.on_step_down(self._on_deposed)
            else:
                self.selector = FileLockPrimarySelector(
                    conf.get(Keys.MASTER_JOURNAL_FOLDER))
        node = getattr(self.journal, "node", None)
        if node is not None:  # EMBEDDED (any selector): raft apply loop
            node.apply_exclusion = _apply_exclusion
        import threading

        self._tailer = JournalTailer(
            self.journal,
            interval_s=conf.get_duration_s(
                Keys.MASTER_STANDBY_TAIL_INTERVAL),
            node=self.client_address,
            on_tick=self._publish_registry,
            apply_exclusion=_apply_exclusion)
        self._promote_thread = None
        self._promote_lock = threading.Lock()
        self._stopped = False
        self.serving = False
        # an FT master is an HA member even without a configured master
        # list (the file-lock flavor discovers peers via the shared
        # journal dir alone): always publish registry rows
        self._ha_member = True
        #: read-only RPC server while standby (atpu.master.ha.standby.
        #: reads.enabled): GetStatus/ListStatus/Exists off the tailing
        #: apply, everything else a NotPrimaryError redirect
        self._standby_server = None
        self.standby_rpc_port: Optional[int] = None

    def _init_from_backup_if_configured(self) -> None:
        backup = self._conf.get(Keys.MASTER_JOURNAL_INIT_FROM_BACKUP)
        if backup and hasattr(self.journal, "init_from_backup"):
            self.journal.init_from_backup(str(backup))

    def start(self) -> int:  # type: ignore[override]
        """Standby boot: tail the journal; a background thread waits for
        primacy and promotes. Returns 0 (no RPC port while standby) —
        callers poll ``rpc_port``/``serving``."""
        import threading

        from alluxio_tpu.utils.pause_monitor import ensure_process_monitor
        from alluxio_tpu.utils.tracing import (
            apply_trace_conf, set_tracing_enabled,
        )

        set_tracing_enabled(self._conf.get_bool(Keys.TRACE_ENABLED))
        apply_trace_conf(self._conf)
        from alluxio_tpu.utils.profiler import apply_profile_conf

        apply_profile_conf(self._conf)
        # the HA master is the one whose elections stall detection
        # protects — it must not be the one path without it
        ensure_process_monitor()
        self.selector.start()
        self.journal.start()
        self._init_from_backup_if_configured()
        if self.selector.try_acquire():
            # under _promote_lock: a Raft step-down firing _on_deposed
            # mid-boot must not demote half-initialized serving state
            with self._promote_lock:
                self.journal.gain_primacy()
                port = self._start_serving()
                self.serving = True
            return port
        self.journal.standby_start()
        # standby endpoint FIRST: the tailer's on_tick publishes this
        # master's registry row, and publishing before the read port is
        # bound advertises the configured (possibly ephemeral :0) port —
        # a stale row the file-per-address registry then keeps forever
        self._start_standby_serving()
        self._tailer.start()
        self._promote_thread = threading.Thread(
            target=self._wait_and_promote, name="primacy-waiter",
            daemon=True)
        self._promote_thread.start()
        return 0

    def _start_standby_serving(self) -> None:
        """Open the read-only RPC endpoint on the configured master
        port: reads are served off the tailed state, stamped with this
        standby's journal-deterministic md_version; every other RPC is
        a typed NotPrimaryError redirect (docs/ha.md)."""
        if not self._conf.get_bool(Keys.MASTER_HA_STANDBY_READS_ENABLED):
            return
        from alluxio_tpu.rpc.master_service import (
            standby_block_service, standby_fs_service,
            standby_meta_service,
        )
        from alluxio_tpu.security.authentication import Authenticator

        server = RpcServer(
            bind_host="0.0.0.0",
            port=self._conf.get_int(Keys.MASTER_RPC_PORT),
            authenticator=Authenticator(self._conf))
        server.add_service(standby_fs_service(
            self.fs_master, self.leader_address,
            active_sync=self.active_sync))
        server.add_service(standby_block_service(
            self.block_master, self.leader_address))
        server.add_service(standby_meta_service(
            self._conf, leader_fn=self.leader_address,
            cluster_id=self.cluster_id,
            start_time_ms=self.start_time_ms, journal=self.journal,
            masters_fn=self.masters_report,
            permission_checker=self.permission_checker))
        self.standby_rpc_port = server.start()
        self._standby_server = server
        LOG.info("standby master serving reads on port %d",
                 self.standby_rpc_port)

    def _stop_standby_serving(self) -> None:
        if self._standby_server is not None:
            self._standby_server.stop()
            self._standby_server = None
            self.standby_rpc_port = None

    def _start_serving(self) -> int:
        port = super()._start_serving()
        self._fence_primary_reads()
        return port

    def _fence_primary_reads(self) -> None:
        """Primacy-gate the serving FS reads: a deposed leader demotes
        asynchronously (``_on_deposed`` runs on its own thread), and
        until its RPC server actually stops it would keep serving reads
        from state that now LAGS the new leader — without the standby
        marker, so a strong client would trust them.  Checking live
        primacy per read closes that window the moment the node learns
        it stepped down.  (A partitioned leader that has not yet heard
        the higher term can still serve briefly-stale reads — the
        classic lease-read gap; terms fence every write. docs/ha.md.)"""
        from alluxio_tpu.rpc.master_service import (
            FS_SERVICE, STANDBY_FS_READS,
        )

        svc = self.rpc_server.service(FS_SERVICE)
        if svc is None:
            return
        journal = self.journal

        def gate(fn):
            def handler(r):
                if not journal.is_primary():
                    from alluxio_tpu.utils.exceptions import (
                        NotPrimaryError,
                    )

                    raise NotPrimaryError(
                        "this master was deposed",
                        leader=self.leader_address() or None)
                return fn(r)

            return handler

        for name, (fn, kind) in list(svc.methods.items()):
            if name in STANDBY_FS_READS:
                svc.methods[name] = (gate(fn), kind)

    def _wait_and_promote(self) -> None:
        while not self._stopped:
            if self.selector.wait_for_primacy(timeout_s=0.5):
                with self._promote_lock:
                    if self._stopped:
                        # stop() raced our acquisition: hand the lock back
                        # so another master can promote
                        self.selector.release()
                        return
                    self.promote()
                return

    def _on_deposed(self) -> None:
        """Raft step-down while serving: stop client RPCs and rejoin the
        election loop as a standby. Journal writes already fail fast
        (propose raises when not leader), so this is availability hygiene,
        not the fence — terms are the fence. Runs on its own thread: the
        raft node invokes callbacks under its lock."""
        import threading

        def demote():
            with self._promote_lock:
                if self._stopped or not self.serving:
                    return
                self.serving = False
                for t in self._threads:
                    t.stop()
                self._threads = []
                if getattr(self, "fastpath_server", None) is not None:
                    # a deposed master must not keep serving local
                    # clients over the Unix socket either
                    self.fastpath_server.stop()
                    self.fastpath_server = None
                if self.rpc_server is not None:
                    self.rpc_server.stop()
                    self.rpc_server = None
                self.rpc_port = None
                if getattr(self, "audit_writer", None) is not None:
                    self.audit_writer.stop()
                    self.audit_writer = None
                # rejoin the quorum as a standby: resume tailing (a
                # no-op tick under raft, but it publishes our STANDBY
                # registry row) and re-open the read-only endpoint
                self._tailer.start()
                self._start_standby_serving()
                self._promote_thread = threading.Thread(
                    target=self._wait_and_promote, name="primacy-waiter",
                    daemon=True)
                self._promote_thread.start()

        threading.Thread(target=demote, name="raft-demote",
                         daemon=True).start()

    def promote(self) -> int:
        """Standby -> primary: stop tailing, finish the tail in place (no
        state reset — the standby is already caught up), open the write
        log, start serving.  The standby read server is stopped FIRST so
        ``_start_serving`` can bind the same configured port."""
        self._tailer.stop()
        self._stop_standby_serving()
        if hasattr(self.journal, "gain_primacy_from_standby"):
            self.journal.gain_primacy_from_standby()
        else:
            self.journal.gain_primacy()
        port = self._start_serving()
        self.serving = True
        return port

    def stop(self) -> None:
        with self._promote_lock:
            self._stopped = True
        if self._promote_thread is not None:
            self._promote_thread.join(timeout=10)
            self._promote_thread = None
        self._tailer.stop()
        self._stop_standby_serving()
        was_serving = self.serving
        self.serving = False
        if was_serving:
            super().stop()
        else:
            self.journal.stop()
            from alluxio_tpu.utils.exceptions import best_effort

            best_effort("master registry withdraw",
                        self.master_registry.withdraw,
                        self.client_address)
        self.selector.release()
