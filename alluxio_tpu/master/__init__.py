"""Master control plane (reference: ``core/server/master``)."""

from alluxio_tpu.master.block_master import BlockMaster, WorkerCommand  # noqa: F401
from alluxio_tpu.master.file_master import FileSystemMaster  # noqa: F401
from alluxio_tpu.master.inode import Inode, PersistenceState, TtlAction  # noqa: F401
from alluxio_tpu.master.inode_tree import InodeTree  # noqa: F401
from alluxio_tpu.master.mount_table import MountInfo, MountTable  # noqa: F401
