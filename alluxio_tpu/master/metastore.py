"""Pluggable inode/block metadata stores.

Re-design of ``core/server/master/.../metastore/``: the reference offers
HEAP (on-heap maps, ``heap/HeapInodeStore.java:46``), ROCKS (off-heap JNI,
``rocks/RocksInodeStore.java:60``) and rocks+write-back-cache
(``caching/CachingInodeStore.java:91``). Here:

- **HeapInodeStore** — dicts; fastest, bounded by RAM.
- **SqliteInodeStore** — stdlib ``sqlite3`` as the spill-to-disk store
  (the RocksDB role: metadata larger than RAM, cheap restart), WAL mode.
- **CachingInodeStore** — LRU write-back cache in front of any backing
  store, flushing evicted dirty entries.

Edges (parent_id, child_name) -> child_id are first-class, as in the
reference's ``InodeStore#getChild``.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

import msgpack

from alluxio_tpu.master.inode import Inode


class InodeStore:
    def get(self, inode_id: int) -> Optional[Inode]:
        raise NotImplementedError

    def put(self, inode: Inode) -> None:
        raise NotImplementedError

    def remove(self, inode_id: int) -> None:
        raise NotImplementedError

    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        raise NotImplementedError

    def remove_child(self, parent_id: int, name: str) -> None:
        raise NotImplementedError

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        raise NotImplementedError

    def child_names(self, parent_id: int) -> List[str]:
        raise NotImplementedError

    def child_count(self, parent_id: int) -> int:
        return len(self.child_names(parent_id))

    def all_ids(self) -> Iterator[int]:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def flush(self) -> None:
        pass

    def estimated_size(self) -> int:
        raise NotImplementedError


class HeapInodeStore(InodeStore):
    def __init__(self) -> None:
        self._inodes: Dict[int, Inode] = {}
        self._edges: Dict[Tuple[int, str], int] = {}
        self._children: Dict[int, Dict[str, int]] = {}
        self._lock = threading.RLock()

    def get(self, inode_id: int) -> Optional[Inode]:
        with self._lock:
            return self._inodes.get(inode_id)

    def put(self, inode: Inode) -> None:
        with self._lock:
            self._inodes[inode.id] = inode

    def remove(self, inode_id: int) -> None:
        with self._lock:
            self._inodes.pop(inode_id, None)

    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        with self._lock:
            self._edges[(parent_id, name)] = child_id
            self._children.setdefault(parent_id, {})[name] = child_id

    def remove_child(self, parent_id: int, name: str) -> None:
        with self._lock:
            self._edges.pop((parent_id, name), None)
            kids = self._children.get(parent_id)
            if kids is not None:
                kids.pop(name, None)
                if not kids:
                    del self._children[parent_id]

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        with self._lock:
            return self._edges.get((parent_id, name))

    def child_names(self, parent_id: int) -> List[str]:
        with self._lock:
            return sorted(self._children.get(parent_id, {}).keys())

    def all_ids(self) -> Iterator[int]:
        with self._lock:
            return iter(list(self._inodes.keys()))

    def clear(self) -> None:
        with self._lock:
            self._inodes.clear()
            self._edges.clear()
            self._children.clear()

    def estimated_size(self) -> int:
        with self._lock:
            return len(self._inodes)


class SqliteInodeStore(InodeStore):
    """Disk-backed store in the RocksDB role (metadata > RAM, fast restart)."""

    def __init__(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "inodes.db")
        self._conn = sqlite3.connect(self._path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS inodes "
                "(id INTEGER PRIMARY KEY, data BLOB NOT NULL)")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS edges "
                "(parent_id INTEGER NOT NULL, name TEXT NOT NULL, "
                "child_id INTEGER NOT NULL, PRIMARY KEY (parent_id, name))")
            self._conn.commit()

    def get(self, inode_id: int) -> Optional[Inode]:
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM inodes WHERE id=?", (inode_id,)).fetchone()
        if row is None:
            return None
        return Inode.from_wire_dict(msgpack.unpackb(row[0], raw=False))

    def put(self, inode: Inode) -> None:
        blob = msgpack.packb(inode.to_wire_dict(), use_bin_type=True)
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO inodes (id, data) VALUES (?, ?)",
                (inode.id, blob))
            self._conn.commit()

    def remove(self, inode_id: int) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM inodes WHERE id=?", (inode_id,))
            self._conn.commit()

    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO edges (parent_id, name, child_id) "
                "VALUES (?, ?, ?)", (parent_id, name, child_id))
            self._conn.commit()

    def remove_child(self, parent_id: int, name: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM edges WHERE parent_id=? AND name=?",
                (parent_id, name))
            self._conn.commit()

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        with self._lock:
            row = self._conn.execute(
                "SELECT child_id FROM edges WHERE parent_id=? AND name=?",
                (parent_id, name)).fetchone()
        return row[0] if row else None

    def child_names(self, parent_id: int) -> List[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT name FROM edges WHERE parent_id=? ORDER BY name",
                (parent_id,)).fetchall()
        return [r[0] for r in rows]

    def all_ids(self) -> Iterator[int]:
        with self._lock:
            rows = self._conn.execute("SELECT id FROM inodes").fetchall()
        return iter([r[0] for r in rows])

    def clear(self) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM inodes")
            self._conn.execute("DELETE FROM edges")
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def estimated_size(self) -> int:
        with self._lock:
            return self._conn.execute("SELECT COUNT(*) FROM inodes").fetchone()[0]


class CachingInodeStore(InodeStore):
    """Write-back LRU cache over a backing store
    (reference: ``metastore/caching/CachingInodeStore.java:91``)."""

    def __init__(self, backing: InodeStore, max_size: int = 100_000) -> None:
        self._backing = backing
        self._max = max_size
        self._cache: "OrderedDict[int, Inode]" = OrderedDict()
        self._dirty: set = set()
        self._lock = threading.RLock()

    def get(self, inode_id: int) -> Optional[Inode]:
        with self._lock:
            if inode_id in self._cache:
                self._cache.move_to_end(inode_id)
                return self._cache[inode_id]
        inode = self._backing.get(inode_id)
        if inode is not None:
            with self._lock:
                self._cache[inode_id] = inode
                self._evict_locked()
        return inode

    def put(self, inode: Inode) -> None:
        with self._lock:
            self._cache[inode.id] = inode
            self._cache.move_to_end(inode.id)
            self._dirty.add(inode.id)
            self._evict_locked()

    def _evict_locked(self) -> None:
        while len(self._cache) > self._max:
            victim_id, victim = self._cache.popitem(last=False)
            if victim_id in self._dirty:
                self._backing.put(victim)
                self._dirty.discard(victim_id)

    def remove(self, inode_id: int) -> None:
        with self._lock:
            self._cache.pop(inode_id, None)
            self._dirty.discard(inode_id)
        self._backing.remove(inode_id)

    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        self._backing.add_child(parent_id, name, child_id)

    def remove_child(self, parent_id: int, name: str) -> None:
        self._backing.remove_child(parent_id, name)

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        return self._backing.get_child_id(parent_id, name)

    def child_names(self, parent_id: int) -> List[str]:
        return self._backing.child_names(parent_id)

    def all_ids(self) -> Iterator[int]:
        self.flush()
        return self._backing.all_ids()

    def flush(self) -> None:
        with self._lock:
            for iid in list(self._dirty):
                inode = self._cache.get(iid)
                if inode is not None:
                    self._backing.put(inode)
            self._dirty.clear()

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()
            self._dirty.clear()
        self._backing.clear()

    def close(self) -> None:
        self.flush()
        self._backing.close()

    def estimated_size(self) -> int:
        self.flush()
        return self._backing.estimated_size()


def create_inode_store(kind: str, directory: str,
                       cache_size: int = 100_000) -> InodeStore:
    """Factory keyed by ``atpu.master.metastore``."""
    k = kind.upper()
    if k == "HEAP":
        return HeapInodeStore()
    if k == "SQLITE":
        return SqliteInodeStore(directory)
    if k == "CACHING":
        return CachingInodeStore(SqliteInodeStore(directory), cache_size)
    raise ValueError(f"unknown metastore kind {kind}")
