"""Scheduled metadata backups.

Re-design of ``core/server/master/src/main/java/alluxio/master/meta/
DailyMetadataBackup.java:49`` (+ the delegated flavor in
``master/backup/BackupLeaderRole.java:62``): a master heartbeat that
periodically lands a full metadata backup in the configured backup
directory and prunes old copies down to a retention count.

Departures from the reference, on purpose:
* interval-based rather than fixed time-of-day (a TPU cluster has no
  natural "daily quiet hour"; the interval default is still 24h);
* runs on the primary — ``write_backup`` snapshots component state
  under the journal lock in one pass (Python dict snapshot, no
  stop-the-world serialization like the reference's rocks iteration),
  so the delegated-to-standby machinery (dedicated messaging transport,
  ``BackupWorkerRole``) is not worth its complexity here. The snapshot
  pause is the same one a periodic checkpoint already takes.
"""

from __future__ import annotations

import logging
import os
import re
import time
from typing import List, Optional

LOG = logging.getLogger(__name__)

_BACKUP_RE = re.compile(r"^atpu-backup-.*\.bak$")


class ScheduledBackup:
    """Heartbeat executor: back up when due, then prune.

    ``clock``: monotonic-seconds fn (injectable for deterministic
    tests). The first tick after start does NOT back up (the reference
    waits for the first scheduled time too) unless the directory has no
    backup at all.
    """

    def __init__(self, journal, backup_dir: str, *,
                 interval_s: float = 24 * 3600.0, retention: int = 3,
                 clock=time.monotonic) -> None:
        self._journal = journal
        self._dir = backup_dir
        self._interval_s = interval_s
        self._retention = max(1, retention)
        self._clock = clock
        self._last: Optional[float] = None
        self.backups_taken = 0
        self.last_backup_path: Optional[str] = None
        self.last_error: Optional[str] = None

    # -- heartbeat ----------------------------------------------------------
    def heartbeat(self) -> Optional[str]:
        """One tick: returns the new backup path when one was taken."""
        now = self._clock()
        if self._last is None:
            # fresh process: take an immediate backup only if none exist
            # (a restart must not produce a backup storm)
            if self._existing():
                self._last = now
                return None
        elif now - self._last < self._interval_s:
            return None
        try:
            path = self._journal.write_backup(self._dir)
        except Exception as e:  # noqa: BLE001 keep the heartbeat alive
            self.last_error = f"{type(e).__name__}: {e}"
            LOG.warning("scheduled backup failed: %s", self.last_error)
            return None
        self._last = now
        self.backups_taken += 1
        self.last_backup_path = path
        self.last_error = None
        self._prune()
        return path

    # -- retention ----------------------------------------------------------
    @staticmethod
    def _age_key(name: str):
        # atpu-backup-<YYYYMMDD-HHMMSS>-<seq>[.<n>].bak — the sequence and
        # uniquifier are NOT zero-padded, so lexical order misranks two
        # backups in the same wall-clock second (seq 10 < seq 9 lexically)
        m = re.match(
            r"^atpu-backup-(\d{8}-\d{6})-(\d+)(?:\.(\d+))?\.bak$", name)
        if m is None:
            return (name, 0, 0)
        return (m.group(1), int(m.group(2)), int(m.group(3) or 0))

    def _existing(self) -> List[str]:
        try:
            return sorted((f for f in os.listdir(self._dir)
                           if _BACKUP_RE.match(f)), key=self._age_key)
        except FileNotFoundError:
            return []

    def _prune(self) -> None:
        """Keep the newest ``retention`` backups (names embed a sortable
        UTC stamp, reference ``DailyMetadataBackup.deleteStaleBackups``)."""
        names = self._existing()
        for name in names[:-self._retention]:
            try:
                os.unlink(os.path.join(self._dir, name))
            except OSError as e:
                LOG.warning("could not prune backup %s: %s", name, e)
