"""FileSystemMaster: the namespace (create/complete/delete/rename/mount/free/
setAttr), TTL, persist scheduling, UFS metadata sync.

Re-design of ``core/server/master/.../file/DefaultFileSystemMaster.java``
(4487 LoC; createFile ``:1463``, completeFile ``:1295``,
getNewBlockIdForFile ``:1538``, delete ``:1621``, rename ``:2174``, mount
``:2736``, free ``:2503``, setAttribute ``:3087``, scheduleAsyncPersistence
``:3209``) composed with the journaled ``InodeTree``, ``MountTable`` and
``BlockMaster``.

Concurrency: hot metadata operations hold the tree lock in READ mode plus
a per-inode lock list along their path (``InodeTree.lock_path`` — read
locks on ancestors, write lock on the terminal), so independent subtrees
no longer serialize; heavyweight multi-phase operations (mount/unmount,
UFS metadata load, commit_persist) still take the tree-level WRITE lock,
which excludes all path-locked operations.  Journal application is the
only state mutator (see ``inode_tree.py`` rationale), and every mutation
appends the affected path to the :class:`MetadataInvalidationLog` that
keeps client metadata caches coherent (docs/metadata.md).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional, Set

from alluxio_tpu.journal.format import EntryType
from alluxio_tpu.journal.system import JournalSystem
from alluxio_tpu.master.block_master import BlockMaster
from alluxio_tpu.master.inode import (
    Inode, PersistenceState, TtlAction,
)
from alluxio_tpu.master.inode_tree import InodeTree, PathLookup
from alluxio_tpu.master.metastore import InodeStore
from alluxio_tpu.master.mount_table import MountInfo, MountTable, Resolution
from alluxio_tpu.underfs.base import CreateOptions as UfsCreateOptions
from alluxio_tpu.underfs.base import DeleteOptions as UfsDeleteOptions
from alluxio_tpu.underfs.registry import UfsManager
from alluxio_tpu.utils import ids
from alluxio_tpu.utils.clock import Clock, SystemClock
from alluxio_tpu.utils.exceptions import (
    DirectoryNotEmptyError, FileAlreadyCompletedError, FileAlreadyExistsError,
    FileDoesNotExistError, FileIncompleteError, InvalidArgumentError,
    InvalidPathError, NotFoundError, PermissionDeniedError, UnavailableError,
    register_wire_error,
)
from alluxio_tpu.utils.fingerprint import Fingerprint
from alluxio_tpu.utils.uri import AlluxioURI
from alluxio_tpu.utils.wire import (
    BlockInfo, FileBlockInfo, FileInfo, MountPointInfo,
)

LOG = logging.getLogger(__name__)

ROOT_MOUNT_ID = 1
#: fallback for "fast tier" classification before any worker registers
#: its topology (the live answer comes from BlockMaster.top_tiers())
_DEFAULT_DEVICE_TIERS = frozenset(("HBM", "MEM"))


def _transpose(rows: "List[dict]") -> dict:
    """Row wire-dicts -> struct-of-arrays listing payload. Every row
    comes from ``_file_info_dict`` so the field set is uniform."""
    if not rows:
        return {"n": 0, "cols": {}}
    return {"n": len(rows),
            "cols": {k: [r[k] for r in rows] for k in rows[0]}}


class FileSystemMaster:
    def __init__(self, block_master: BlockMaster, journal: JournalSystem,
                 ufs_manager: Optional[UfsManager] = None,
                 inode_store: Optional[InodeStore] = None,
                 clock: Optional[Clock] = None,
                 default_block_size: int = 64 << 20,
                 permission_checker=None,
                 umask: int = 0o022,
                 ufs_path_cache_capacity: int = 10_000,
                 coarse_locking: bool = False,
                 edge_locking: bool = True) -> None:
        self._block_master = block_master
        self._journal = journal
        self._ufs = ufs_manager or UfsManager()
        self._clock = clock or SystemClock()
        self._default_block_size = default_block_size
        if permission_checker is None:
            from alluxio_tpu.security.authorization import PermissionChecker
            from alluxio_tpu.security.user import get_os_user

            # the process user is the superuser (reference: the master's
            # login user bypasses permission checks)
            permission_checker = PermissionChecker(superuser=get_os_user())
        self._perm = permission_checker
        self._umask = umask
        self.inode_tree = InodeTree(inode_store,
                                    coarse_locking=coarse_locking,
                                    edge_locking=edge_locking)
        self.mount_table = MountTable()
        from alluxio_tpu.master.invalidation import MetadataInvalidationLog

        #: versioned push-invalidation log for client metadata caches;
        #: GetStatus/ListStatus stamps and the metrics-heartbeat
        #: piggyback both read it (docs/metadata.md).  Fed from the
        #: JOURNAL APPLY path (inode-tree + mount-table sinks below),
        #: never from the RPC methods, so a tailing standby counts the
        #: exact md_version sequence the primary stamps and standby-
        #: served reads stay inside the cache coherence contract
        #: (docs/ha.md).
        self.invalidations = MetadataInvalidationLog()
        self.inode_tree.invalidation_sink = self.invalidations.append
        # the tree also carries the log's version through checkpoint
        # snapshot/restore: a bootstrap-from-checkpoint must not restart
        # the count the skipped entries already advanced
        self.inode_tree.invalidation_log = self.invalidations
        journal.register(self.inode_tree)
        journal.register(_MountTableJournal(
            self.mount_table, invalidation_sink=self.invalidations.append))
        #: paths with in-flight async persist (file id -> alluxio path)
        self._persist_requests: "set[int]" = set()
        # serializes persist commits' UFS IO (see commit_persist)
        self._persist_mutex = threading.Lock()
        from alluxio_tpu.master.sync import AbsentPathCache, UfsSyncPathCache

        #: last-sync bookkeeping (reference: UfsSyncPathCache)
        self._sync_cache = UfsSyncPathCache()
        #: UFS paths known absent (reference: AsyncUfsAbsentPathCache)
        self._absent_cache = AbsentPathCache(
            max_size=max(1, ufs_path_cache_capacity))
        #: dir inode id -> (tree_version, location_version, wire dicts).
        #: Directory listing is the #1 metadata op for training-data
        #: discovery and re-lists the same (unchanged) dirs constantly;
        #: entries are valid while BOTH coarse versions stand — every
        #: namespace mutation takes the tree write lock (bumping
        #: ``RWLock.version``) and every residency change bumps
        #: ``BlockMaster.location_version`` (reference streams ListStatus
        #: partials instead, ``file_system_master.proto:475-590``; a
        #: version-guarded server cache is the cheaper design when the
        #: whole tree sits in one process)
        self._listing_cache: Dict[int, tuple] = {}
        self._listing_cache_lock = threading.Lock()

    # -------------------------------------------------------------- startup
    def start(self, root_ufs_uri: Optional[str] = None,
              root_ufs_properties: Optional[Dict[str, str]] = None) -> None:
        """Create the root inode + root mount on first boot."""
        with self.inode_tree.lock.write_locked():
            if self.inode_tree.root is None:
                now = self._clock.millis()
                cid = self._block_master.new_container_id()
                from alluxio_tpu.security.user import get_os_user

                # root is owned by the master's login user (reference:
                # InodeTree.initializeRoot uses the server login user)
                root = Inode.new_directory(
                    ids.file_id_from_container(cid), -1, "", mode=0o755,
                    owner=get_os_user(), now_ms=now)
                root.persistence_state = PersistenceState.PERSISTED
                with self._journal.create_context() as ctx:
                    ctx.append(EntryType.INODE_DIRECTORY, root.to_wire_dict())
                    if root_ufs_uri:
                        ctx.append(EntryType.ADD_MOUNT_POINT, MountInfo(
                            ROOT_MOUNT_ID, "/", root_ufs_uri, False, False,
                            root_ufs_properties or {}).to_wire())
            # (re)wire UFS instances for every mount (also after replay)
            for info in self.mount_table.mount_points():
                if not self._ufs.has(info.mount_id):
                    self._ufs.add_mount(info.mount_id, info.ufs_uri,
                                        info.properties)

    def stop(self) -> None:
        self._ufs.close()
        # disk-backed metastores own background work (LSM compactor,
        # sqlite connection) that must not outlive the master
        self.inode_tree._store.close()

    # ------------------------------------------------------------ factories
    @property
    def ufs_manager(self) -> UfsManager:
        return self._ufs

    def _now(self) -> int:
        return self._clock.millis()

    # ---------------------------------------------------------- permissions
    def _auth_user(self):
        from alluxio_tpu.security.user import authenticated_user

        return authenticated_user()

    def _check_access(self, lookup: PathLookup, bits: int) -> None:
        """traverse + ``bits`` on the target inode."""
        user = self._auth_user()
        self._perm.check_traverse(user, lookup.inodes[:-1])
        self._perm.check(user, lookup.inode, bits, path=lookup.uri.path)

    def _check_parent_write(self, lookup: PathLookup) -> None:
        """traverse + WRITE on the deepest existing ancestor (create) or
        the parent (delete/rename)."""
        from alluxio_tpu.security.authorization import WRITE

        user = self._auth_user()
        self._perm.check_traverse(user, lookup.inodes[:-1])
        self._perm.check(user, lookup.deepest, WRITE, path=lookup.uri.path)

    def _check_delete(self, lookup: PathLookup) -> None:
        """traverse + WRITE on the parent of an existing target."""
        from alluxio_tpu.security.authorization import WRITE

        user = self._auth_user()
        self._perm.check_traverse(user, lookup.inodes[:-2])
        if len(lookup.inodes) >= 2:
            self._perm.check(user, lookup.inodes[-2], WRITE,
                             path=lookup.uri.path)

    def _fill_owner(self, owner: str, group: str) -> "tuple[str, str]":
        """Create-time defaults from the authenticated user
        (reference: inodes inherit the RPC caller's identity)."""
        user = self._auth_user()
        if user is not None:
            owner = owner or user.name
            group = group or (user.groups[0] if user.groups else user.name)
        return owner, group

    def _inherit_default_acl(self, parent: Inode, inode: Inode) -> None:
        """A directory's default ACL becomes new children's access ACL
        (and stays the default on child directories) — reference:
        DefaultAccessControlList inheritance."""
        default = parent.xattr.get(self.DEFAULT_ACL_XATTR, "")
        if not default:
            return
        inode.xattr = dict(inode.xattr)
        inode.xattr[self.ACL_XATTR] = default
        if inode.is_directory:
            inode.xattr[self.DEFAULT_ACL_XATTR] = default

    # ---------------------------------------------------------------- reads
    def get_status(self, path: "str | AlluxioURI",
                   sync_interval_ms: int = -1) -> FileInfo:
        uri = AlluxioURI(path)
        self._maybe_sync(uri, sync_interval_ms)
        with self.inode_tree.lock_path(uri) as lip:
            lookup = lip.lookup
            # POSIX stat semantics: EXECUTE on every ancestor (no READ on
            # the target itself) — without this, stat leaks metadata of
            # paths under 0700 directories
            self._perm.check_traverse(self._auth_user(),
                                      lookup.inodes[:-1] if lookup.exists
                                      else lookup.inodes)
            if not lookup.exists:
                loaded = None
            else:
                return self._file_info(lookup.inode, uri)
        # path absent: try loading metadata from UFS (on-access sync)
        loaded = self._load_metadata_if_exists(uri)
        if loaded is None:
            raise FileDoesNotExistError(f"path {uri} does not exist")
        return loaded

    def exists(self, path: "str | AlluxioURI") -> bool:
        try:
            self.get_status(path)
            return True
        except FileDoesNotExistError:
            return False

    def list_status(self, path: "str | AlluxioURI", *, recursive: bool = False,
                    load_direct_children: bool = True,
                    sync_interval_ms: int = -1,
                    wire: bool = False,
                    columnar: bool = False) -> "List[FileInfo] | dict":
        """``wire=True``: entries are returned as wire DICTS (what the
        RPC handler ships) — N dataclass constructions skipped.
        ``columnar=True`` (implies wire, non-recursive only): the listing
        comes back struct-of-arrays, ``{"n": N, "cols": {field: [N
        values]}}`` — one msgpack map of 30 arrays instead of N 30-key
        maps, cutting encode+decode cost ~in half at listing fan-out
        (the reference streams ListStatus partials instead,
        ``file_system_master.proto:475-590``). Transposed once per
        directory version and memoized in the listing cache."""
        uri = AlluxioURI(path)
        wire = wire or columnar
        synced = self._maybe_sync(uri, sync_interval_ms)
        status = self.get_status(uri)  # loads the inode itself if needed
        if not status.folder:
            if columnar:
                return _transpose([status.to_wire()])
            return [status.to_wire()] if wire else [status]
        if load_direct_children:
            self._load_children_if_needed(uri, force=synced)
            if recursive:
                # DescendantType.ALL semantics (reference
                # ``InodeSyncStream``): a recursive listing must surface
                # UNLOADED UFS subtrees too — walk each directory's
                # children before the locked emit (UFS IO cannot run
                # under the tree lock). The child inode's
                # ``direct_children_loaded`` flag is read in the same
                # lock pass as the traversal, so a warm subtree costs
                # one lookup per directory and zero load calls.
                queue = [uri]
                while queue:
                    d = queue.pop()
                    with self.inode_tree.lock.read_locked():
                        lk = self.inode_tree.lookup(d)
                        if not lk.exists or not lk.inode.is_directory:
                            continue
                        subdirs = [(c.name, c.direct_children_loaded)
                                   for c in
                                   self.inode_tree.children(lk.inode)
                                   if c.is_directory]
                    for name, loaded in subdirs:
                        child = d.join(name)
                        if synced or not loaded:
                            self._load_children_if_needed(child,
                                                          force=synced)
                        queue.append(child)
        info = self._file_info_dict if wire else self._file_info
        out: List[FileInfo] = []
        with self.inode_tree.lock_path(uri) as lip:
            lookup = lip.lookup
            if not lookup.exists:
                raise FileDoesNotExistError(f"path {uri} does not exist")
            from alluxio_tpu.security.authorization import READ

            self._check_access(lookup, READ)
            if wire and not recursive:
                # per-caller access check done above; the emitted child
                # entries themselves are caller-independent.  The cache
                # stamp is the namespace-wide change_version: with
                # striped locking the tree lock's own version no longer
                # sees path-locked mutations, but every mutation still
                # bumps change_version at journal-apply time.
                dir_id = lookup.inode.id
                tree_ver = self.inode_tree.change_version
                loc_ver = self._block_master.location_version
                hit = self._listing_cache.get(dir_id)
                if hit is not None and hit[0] == tree_ver and \
                        hit[1] == loc_ver:
                    if not columnar:
                        return hit[2]
                    if hit[3] is None:
                        hit = hit[:3] + (_transpose(hit[2]),)
                        with self._listing_cache_lock:
                            self._listing_cache[dir_id] = hit
                    return hit[3]

            def emit(dir_inode: Inode, dir_uri: AlluxioURI) -> None:
                # resolve the directory's mount ONCE; children extend it
                # by name. Only a child that is itself a mount point (a
                # nested mount lands exactly one level down) needs its
                # own resolution — the rest skip the per-child mount
                # walk + URI construction that dominated listing CPU.
                try:
                    dres = self.mount_table.resolve(dir_uri)
                    d_ufs = dres.ufs_path.rstrip("/")
                    d_mount = dres.mount_id
                except (NotFoundError, InvalidPathError):
                    d_ufs, d_mount = "", 0  # unmounted region
                d_path = dir_uri.path if dir_uri.path != "/" else ""
                for child in self.inode_tree.children(dir_inode):
                    child_path = f"{d_path}/{child.name}"
                    if self.mount_table.is_mount_path(child_path):
                        child_uri = dir_uri.join(child.name)
                        out.append(info(child, child_uri))
                    else:
                        mount = (f"{d_ufs}/{child.name}" if d_ufs else "",
                                 d_mount)
                        out.append(info(child, child_path, mount=mount))
                    if recursive and child.is_directory:
                        emit(child, dir_uri.join(child.name))

            emit(lookup.inode, uri)
            if wire and not recursive and \
                    self.inode_tree.change_version == tree_ver and \
                    self._block_master.location_version == loc_ver:
                # a mutation anywhere (version moved) or a location
                # change mid-emit makes this listing uncacheable —
                # serve it, but don't memoize a potentially torn view
                cols = _transpose(out) if columnar else None
                with self._listing_cache_lock:
                    # multiple listing threads share the tree READ lock;
                    # dict iteration for eviction needs its own mutex
                    if len(self._listing_cache) >= 1024:
                        self._listing_cache.pop(
                            next(iter(self._listing_cache)), None)
                    self._listing_cache[lookup.inode.id] = (
                        tree_ver, loc_ver, out, cols)
                if columnar:
                    return cols
        return _transpose(out) if columnar else out

    def list_status_page(self, path: "str | AlluxioURI", *,
                         start_after: Optional[str] = None,
                         limit: int = 500) -> dict:
        """One PAGE of a directory listing: up to ``limit`` children in
        name order strictly after ``start_after``, as wire dicts, plus
        the resume cursor.  Each page takes (and drops) its own path
        lock and streams straight off the store's ``iter_edges`` range
        scan — a million-entry LSM directory is never materialized in
        master memory, which is what the streamed-listing RPC rides for
        big directories.  Pages compose a weakly-consistent listing
        (entries created/deleted between pages may or may not appear —
        same contract as the reference's partial ListStatus); each page
        carries ``md_version`` so clients can detect drift."""
        uri = AlluxioURI(path)
        limit = max(1, limit)
        with self.inode_tree.lock_path(uri) as lip:
            lookup = lip.lookup
            if not lookup.exists:
                raise FileDoesNotExistError(f"path {uri} does not exist")
            from alluxio_tpu.security.authorization import READ

            self._check_access(lookup, READ)
            inode = lookup.inode
            if not inode.is_directory:
                entry = [] if start_after else \
                    [self._file_info_dict(inode, uri)]
                return {"infos": entry, "next": None,
                        "md_version": self.invalidations.version}
            try:
                dres = self.mount_table.resolve(uri)
                d_ufs = dres.ufs_path.rstrip("/")
                d_mount = dres.mount_id
            except (NotFoundError, InvalidPathError):
                d_ufs, d_mount = "", 0
            d_path = uri.path if uri.path != "/" else ""
            infos: List[dict] = []
            last_name: Optional[str] = None
            for child in self.inode_tree.children(inode,
                                                  start_after=start_after):
                child_path = f"{d_path}/{child.name}"
                if self.mount_table.is_mount_path(child_path):
                    infos.append(self._file_info_dict(
                        child, uri.join(child.name)))
                else:
                    mount = (f"{d_ufs}/{child.name}" if d_ufs else "",
                             d_mount)
                    infos.append(self._file_info_dict(
                        child, child_path, mount=mount))
                last_name = child.name
                if len(infos) >= limit:
                    break
            return {"infos": infos,
                    "next": last_name if len(infos) >= limit else None,
                    "md_version": self.invalidations.version}

    def metastore_stats(self) -> dict:
        """The inode store's own counters (kind, memtable/run/compaction
        gauges, cache hit ratio) — fsadmin report, the status page and
        the ``Master.Metastore*`` metrics all read this."""
        return self.inode_tree._store.stats()

    def get_file_block_info_list(self, path: "str | AlluxioURI") -> List[FileBlockInfo]:
        uri = AlluxioURI(path)
        with self.inode_tree.lock_path(uri) as lip:
            lookup = lip.lookup
            inode = lookup.inode
            from alluxio_tpu.security.authorization import READ

            self._check_access(lookup, READ)
            if inode.is_directory:
                raise InvalidArgumentError(f"{uri} is a directory")
            return self._file_block_infos(inode)

    def _file_block_infos(self, inode: Inode) -> List[FileBlockInfo]:
        infos = self._block_master.get_block_infos(inode.block_ids)
        by_id = {b.block_id: b for b in infos}
        out = []
        for i, bid in enumerate(inode.block_ids):
            bi = by_id.get(bid, BlockInfo(block_id=bid, length=0))
            out.append(FileBlockInfo(block_info=bi,
                                     offset=i * inode.block_size_bytes))
        return out

    def _file_info(self, inode: Inode, uri: "AlluxioURI | str",
                   mount: Optional[tuple] = None) -> FileInfo:
        return FileInfo.from_wire(self._file_info_dict(inode, uri, mount))

    def _file_info_dict(self, inode: Inode, uri: "AlluxioURI | str",
                        mount: Optional[tuple] = None) -> dict:
        """FileInfo in WIRE-DICT form — the RPC handlers ship this
        straight into msgpack without materializing a FileInfo (a
        listing of N entries skips N dataclass constructions + N
        ``to_wire`` copies; in-process callers get objects via
        ``_file_info``). ``mount``: precomputed ``(ufs_path, mount_id)``
        from a listing loop that resolved the parent once (the child
        then cannot be a mount point — the caller checked); ``uri`` may
        then be a plain path string, skipping per-child URI
        construction."""
        in_mem = 0
        fbi: List[FileBlockInfo] = []
        if not inode.is_directory and inode.block_ids:
            fbi = self._file_block_infos(inode)
            fast = self._block_master.top_tiers() or \
                _DEFAULT_DEVICE_TIERS
            mem_bytes = 0
            for f in fbi:
                if any(loc.tier_alias in fast
                       for loc in f.block_info.locations):
                    mem_bytes += f.block_info.length
            in_mem = int(100 * mem_bytes / inode.length) if inode.length else (
                100 if fbi else 0)
        if mount is not None:
            ufs_path, mount_id = mount
            is_mp = False
            path = uri if isinstance(uri, str) else uri.path
        else:
            if isinstance(uri, str):
                uri = AlluxioURI(uri)
            path = uri.path
            try:
                resolution = self.mount_table.resolve(uri)
                ufs_path = resolution.ufs_path
                mount_id = resolution.mount_id
            except (NotFoundError, InvalidPathError):
                ufs_path, mount_id = "", 0  # unmounted: no UFS path
            is_mp = self.mount_table.is_mount_point(uri)
        return {
            "file_id": inode.id, "name": inode.name or "/", "path": path,
            "ufs_path": ufs_path, "length": inode.length,
            "block_size_bytes": inode.block_size_bytes,
            "creation_time_ms": inode.creation_time_ms,
            "last_modification_time_ms": inode.last_modification_time_ms,
            "last_access_time_ms": inode.last_access_time_ms,
            "completed": inode.completed or inode.is_directory,
            "folder": inode.is_directory, "pinned": inode.pinned,
            "pinned_media": list(inode.pinned_media),
            "cacheable": inode.cacheable,
            "persisted":
                inode.persistence_state == PersistenceState.PERSISTED,
            "persistence_state": inode.persistence_state,
            "block_ids": list(inode.block_ids),
            "in_memory_percentage": in_mem,
            "ttl": inode.ttl, "ttl_action": inode.ttl_action,
            "owner": inode.owner, "group": inode.group, "mode": inode.mode,
            "mount_point": is_mp, "mount_id": mount_id,
            "replication_min": inode.replication_min,
            "replication_max": inode.replication_max,
            "file_block_infos": [f.to_wire() for f in fbi],
            "xattr": dict(inode.xattr)}

    # --------------------------------------------------------------- create
    def create_file(self, path: "str | AlluxioURI", *,
                    block_size_bytes: Optional[int] = None,
                    recursive: bool = True, ttl: int = -1,
                    ttl_action: str = TtlAction.DELETE,
                    mode: Optional[int] = None,
                    owner: str = "", group: str = "",
                    replication_min: int = 0, replication_max: int = -1,
                    cacheable: bool = True,
                    persist_on_complete: bool = False,
                    overwrite: bool = False) -> FileInfo:
        """Reference: ``DefaultFileSystemMaster.createFile:1463``.
        ``overwrite=True`` atomically replaces an existing FILE (delete +
        create under one tree write lock — the POSIX/fsspec 'wb'
        truncate contract, server-side so no client delete/create race);
        an existing directory still raises."""
        uri = AlluxioURI(path)
        if uri.is_root():
            raise InvalidPathError("cannot create root")
        self._check_reserved_name(uri)
        block_size = block_size_bytes or self._default_block_size
        # overwrite also write-locks the PARENT: the replace must stay
        # atomic across the inner delete (which unlinks the terminal
        # whose lock would otherwise be our only exclusion)
        with self.inode_tree.lock_path(uri, write=True,
                                       write_parent=overwrite) as lip:
            lookup = lip.lookup
            if lookup.exists and overwrite and not \
                    lookup.inode.is_directory:
                # atomic replace under the HELD parent+terminal write
                # locks (no nested lock_path — the canonical order
                # audit would flag re-entering the tree lock)
                self._delete_locked(uri, lookup)
                lookup = self.inode_tree.lookup(uri)
            if lookup.exists:
                raise FileAlreadyExistsError(f"{uri} already exists")
            self._check_parent_write(lookup)
            owner, group = self._fill_owner(owner, group)
            # umask shapes the DEFAULT mode only; explicit modes are kept
            # (reference: ModeUtils.applyFileUMask on option defaults)
            mode = (0o666 & ~self._umask) if mode is None else mode
            parents = self._prepare_parents(lookup, recursive)
            now = self._now()
            cid = self._block_master.new_container_id()
            inode = Inode.new_file(
                cid, 0, uri.name, block_size_bytes=block_size, owner=owner,
                group=group, mode=mode, ttl=ttl, ttl_action=ttl_action,
                replication_min=replication_min,
                replication_max=replication_max, now_ms=now)
            inode.cacheable = cacheable
            if persist_on_complete:
                inode.persistence_state = PersistenceState.TO_BE_PERSISTED
            with self._journal.create_context() as ctx:
                prev = lookup.deepest
                for p in parents:
                    p.parent_id = prev.id
                    # intermediate dirs inherit identity + default ACL so
                    # children created under them later inherit correctly
                    p.owner, p.group = owner, group
                    p.mode = 0o777 & ~self._umask
                    self._inherit_default_acl(prev, p)
                    ctx.append(EntryType.INODE_DIRECTORY, p.to_wire_dict())
                    prev = p
                inode.parent_id = prev.id
                self._inherit_default_acl(prev, inode)
                ctx.append(EntryType.INODE_FILE, inode.to_wire_dict())
            self._absent_cache.remove(uri.path)
            return self._file_info(self.inode_tree.get_inode(inode.id), uri)

    def create_directory(self, path: "str | AlluxioURI", *,
                         recursive: bool = True, allow_exists: bool = False,
                         mode: Optional[int] = None,
                         owner: str = "", group: str = "",
                         persisted: bool = False) -> FileInfo:
        uri = AlluxioURI(path)
        if uri.is_root():
            raise InvalidPathError("cannot create root")
        self._check_reserved_name(uri)
        with self.inode_tree.lock_path(uri, write=True) as lip:
            lookup = lip.lookup
            if lookup.exists:
                if allow_exists and lookup.inode.is_directory:
                    return self._file_info(lookup.inode, uri)
                raise FileAlreadyExistsError(f"{uri} already exists")
            self._check_parent_write(lookup)
            owner, group = self._fill_owner(owner, group)
            mode = (0o777 & ~self._umask) if mode is None else mode
            parents = self._prepare_parents(lookup, recursive)
            now = self._now()
            cid = self._block_master.new_container_id()
            inode = Inode.new_directory(
                ids.file_id_from_container(cid), 0, uri.name, owner=owner,
                group=group, mode=mode, now_ms=now)
            if persisted:
                inode.persistence_state = PersistenceState.PERSISTED
            with self._journal.create_context() as ctx:
                prev = lookup.deepest
                for p in parents:
                    p.parent_id = prev.id
                    # intermediate dirs inherit identity + default ACL so
                    # children created under them later inherit correctly
                    p.owner, p.group = owner, group
                    p.mode = 0o777 & ~self._umask
                    self._inherit_default_acl(prev, p)
                    ctx.append(EntryType.INODE_DIRECTORY, p.to_wire_dict())
                    prev = p
                inode.parent_id = prev.id
                self._inherit_default_acl(prev, inode)
                ctx.append(EntryType.INODE_DIRECTORY, inode.to_wire_dict())
            self._absent_cache.remove(uri.path)
            return self._file_info(self.inode_tree.get_inode(inode.id), uri)

    def _prepare_parents(self, lookup: PathLookup,
                         recursive: bool) -> List[Inode]:
        """Build inodes for missing intermediate directories (ids assigned,
        parent ids patched at journal time)."""
        missing = lookup.missing_components[:-1]
        if missing and not recursive:
            raise FileDoesNotExistError(
                f"parent of {lookup.uri} does not exist (non-recursive)")
        if not lookup.deepest.is_directory:
            raise InvalidPathError(
                f"ancestor {lookup.deepest.name!r} of {lookup.uri} is a file")
        out: List[Inode] = []
        now = self._now()
        for name in missing:
            cid = self._block_master.new_container_id()
            d = Inode.new_directory(ids.file_id_from_container(cid), 0, name,
                                    now_ms=now)
            # inherit persistence from the fact the parent chain is persisted
            out.append(d)
        return out

    # --------------------------------------------------------------- blocks
    def get_new_block_id_for_file(self, path: "str | AlluxioURI") -> int:
        """Reference: ``getNewBlockIdForFile:1538``."""
        uri = AlluxioURI(path)
        with self.inode_tree.lock_path(uri, write=True) as lip:
            from alluxio_tpu.security.authorization import WRITE

            self._check_access(lip.lookup, WRITE)
            inode = self._existing_inode(lip.lookup, uri)
            if inode.completed:
                raise FileAlreadyCompletedError(f"{uri} is completed")
            bid = inode.next_block_id()
            with self._journal.create_context() as ctx:
                ctx.append(EntryType.NEW_BLOCK,
                           {"file_id": inode.id, "block_id": bid})
            return bid

    def complete_file(self, path: "str | AlluxioURI", *,
                      length: Optional[int] = None,
                      ufs_fingerprint: str = "") -> None:
        """Reference: ``completeFile:1295``.

        Striped fast path: the terminal's write lock suffices while the
        parent chain is already PERSISTED (steady state).  When a
        fingerprinted complete must also flip unpersisted ANCESTOR
        directories — inodes this path list only read-holds — it falls
        back to the exclusive tree lock (rare: first persist under a
        fresh directory).  Phase 2 re-derives EVERYTHING — access check,
        target inode, length, ancestor chain — because nothing captured
        under the released phase-1 locks is trustworthy (the same rule
        ``mark_persisted``/``rename`` follow for their fallbacks)."""
        uri = AlluxioURI(path)
        with self.inode_tree.lock_path(uri, write=True) as lip:
            if self._complete_locked(uri, lip.lookup, length,
                                     ufs_fingerprint, anc_held=False):
                return
        with self.inode_tree.lock.write_locked():
            self._complete_locked(uri, self.inode_tree.lookup(uri),
                                  length, ufs_fingerprint, anc_held=True)

    def _complete_locked(self, uri: AlluxioURI, lookup: PathLookup,
                         length: "Optional[int]", ufs_fingerprint: str, *,
                         anc_held: bool) -> bool:
        """Validate + journal a complete under the caller's locks;
        ``anc_held=False`` returns False — nothing journaled — when
        unpersisted ancestors must flip (only the exclusive tree lock
        covers those)."""
        from alluxio_tpu.security.authorization import WRITE

        self._check_access(lookup, WRITE)
        inode = self._existing_inode(lookup, uri)
        if inode.completed:
            raise FileAlreadyCompletedError(f"{uri} already completed")
        if length is None:
            infos = self._block_master.get_block_infos(inode.block_ids)
            length = sum(b.length for b in infos)
        anc = self._unpersisted_chain(
            self.inode_tree.parent_of(inode), uri) if ufs_fingerprint else []
        if not anc_held and anc:
            return False  # caller retries under the exclusive tree lock
        if anc:
            # breadcrumbs BEFORE the durable flip: a crash after the
            # journal fsync must not leave PERSISTED dirs that exist
            # only as implicit object prefixes
            self._ensure_ufs_parent_dirs(uri)
        with self._journal.create_context() as ctx:
            ctx.append(EntryType.COMPLETE_FILE, {
                "file_id": inode.id, "length": length,
                "op_time_ms": self._now()})
            if ufs_fingerprint:
                self._journal_persisted(ctx, inode, ufs_fingerprint,
                                        ancestors=anc)
        if inode.persistence_state == PersistenceState.TO_BE_PERSISTED:
            self._persist_requests.add(inode.id)
        return True

    def _existing_file(self, uri: AlluxioURI) -> Inode:
        return self._existing_inode(self.inode_tree.lookup(uri), uri)

    @staticmethod
    def _existing_inode(lookup: PathLookup, uri: AlluxioURI) -> Inode:
        inode = lookup.inode
        if inode.is_directory:
            raise InvalidPathError(f"{uri} is a directory")
        return inode

    # --------------------------------------------------------------- delete
    def delete(self, path: "str | AlluxioURI", *, recursive: bool = False,
               alluxio_only: bool = False) -> None:
        """Reference: ``delete:1621``. Removes inodes bottom-up, drops block
        metadata, and (unless ``alluxio_only``) deletes in the UFS."""
        uri = AlluxioURI(path)
        if uri.is_root():
            raise InvalidPathError("cannot delete root")
        with self.inode_tree.lock_path(uri, write=True) as lip:
            self._delete_locked(uri, lip.lookup, recursive=recursive,
                                alluxio_only=alluxio_only)

    def _delete_locked(self, uri: AlluxioURI, lookup: PathLookup, *,
                       recursive: bool = False,
                       alluxio_only: bool = False) -> None:
        """Delete under the caller's locks (terminal write-held):
        ``delete`` proper and ``create_file(overwrite=True)``'s atomic
        replace both land here."""
        inode = lookup.inode
        self._check_delete(lookup)
        if self.mount_table.is_mount_point(uri):
            raise InvalidPathError(
                f"{uri} is a mount point; unmount it instead")
        victims: List[Inode] = []
        if inode.is_directory:
            # emptiness probe, not a materialized name list — a
            # millions-wide directory answers from its first edge
            if not recursive and self.inode_tree.has_children(inode):
                raise DirectoryNotEmptyError(
                    f"{uri} is non-empty; need recursive")
            if self.mount_table.contains_mount_below(uri):
                raise InvalidPathError(
                    f"{uri} contains nested mount points")
            victims.extend(self.inode_tree.descendants(inode))
        victims.append(inode)
        block_ids: List[int] = []
        persisted_paths: List[Inode] = []
        for v in victims:
            block_ids.extend(v.block_ids)
            if v.persistence_state == PersistenceState.PERSISTED:
                persisted_paths.append(v)
        if not alluxio_only and persisted_paths:
            # fail fast BEFORE journaling: a read-only mount must leave
            # both Alluxio and UFS state untouched
            self._check_ufs_writable(uri)
        now = self._now()
        with self._journal.create_context() as ctx:
            for v in victims:
                payload = {"id": v.id, "op_time_ms": now}
                if v is not inode:
                    # the delete ROOT's entry invalidates the whole
                    # subtree by client-side prefix semantics; marking
                    # descendants "covered" keeps a recursive delete
                    # from flooding the bounded invalidation ring into
                    # a cluster-wide cache reset
                    payload["covered"] = True
                ctx.append(EntryType.DELETE_FILE, payload)
        if block_ids:
            self._block_master.remove_blocks(block_ids,
                                             delete_metadata=True)
        if not alluxio_only and persisted_paths:
            self._delete_in_ufs(uri, persisted_paths)

    def _check_reserved_name(self, uri: AlluxioURI) -> None:
        """Framework temp prefixes are reserved: a user file named like
        one would be hidden from metadata sync and swept from the UFS by
        the UfsCleaner after the TTL — silent data loss."""
        from alluxio_tpu.master.integrity import is_infra_temp

        if is_infra_temp(uri.name):
            raise InvalidPathError(
                f"{uri.name!r} uses a reserved framework temp prefix")

    def _check_ufs_writable(self, uri: AlluxioURI) -> None:
        try:
            resolution = self.mount_table.resolve(uri)
        except (NotFoundError, InvalidPathError):
            return
        if resolution.mount_info.read_only:
            raise PermissionDeniedError(
                f"mount {resolution.mount_info.alluxio_path} is read-only")

    def _delete_in_ufs(self, base_uri: AlluxioURI, inodes: List[Inode]) -> None:
        try:
            resolution = self.mount_table.resolve(base_uri)
        except (NotFoundError, InvalidPathError):
            return
        ufs = self._ufs.get(resolution.mount_id)
        # deepest-first ufs delete; base last
        if len(inodes) == 1 and not inodes[0].is_directory:
            ufs.delete_file(resolution.ufs_path)
        else:
            ufs.delete_directory(resolution.ufs_path,
                                 UfsDeleteOptions(recursive=True))

    # --------------------------------------------------------------- rename
    def rename(self, src: "str | AlluxioURI", dst: "str | AlluxioURI") -> None:
        """Reference: ``rename:2174``.

        Striped fast path: two per-inode lock lists acquired in
        lexicographic path order (see ``InodeTree.lock_path_pair``) —
        write on the src terminal, write on dst's deepest existing inode
        (the parent gaining the edge).  When the rename must also flip
        unpersisted ancestors ABOVE dst's parent to PERSISTED (inodes
        the lists only read-hold), it falls back to the exclusive tree
        lock — rare: persisted file renamed under a fresh dir chain."""
        src_uri, dst_uri = AlluxioURI(src), AlluxioURI(dst)
        if src_uri.is_root() or dst_uri.is_root():
            raise InvalidPathError("cannot rename to/from root")
        if src_uri.is_ancestor_of(dst_uri):
            raise InvalidPathError(f"cannot rename {src_uri} under itself")
        self._check_reserved_name(dst_uri)
        with self.inode_tree.lock_path_pair(src_uri, dst_uri) as (
                src_lip, dst_lip):
            if self._rename_locked(src_uri, dst_uri, src_lip.lookup,
                                   dst_lip.lookup, anc_held=False):
                return
        with self.inode_tree.lock.write_locked():
            self._rename_locked(src_uri, dst_uri,
                                self.inode_tree.lookup(src_uri),
                                self.inode_tree.lookup(dst_uri),
                                anc_held=True)

    def _rename_locked(self, src_uri: AlluxioURI, dst_uri: AlluxioURI,
                       src_lookup: PathLookup, dst_lookup: PathLookup, *,
                       anc_held: bool) -> bool:
        """Validate + journal a rename under the caller's locks.
        ``anc_held=False`` (striped): returns False — nothing journaled
        — when the op needs PERSISTED flips above dst's parent, which
        only the exclusive tree lock covers."""
        inode = src_lookup.inode
        self._check_delete(src_lookup)
        if self.mount_table.is_mount_point(src_uri):
            raise InvalidPathError(f"{src_uri} is a mount point")
        # cross-mount renames are unsupported (reference behavior)
        src_mp = self.mount_table.get_mount_point(src_uri)
        dst_mp = self.mount_table.get_mount_point(dst_uri)
        if src_mp != dst_mp:
            raise InvalidPathError("rename across mount points")
        if dst_lookup.exists:
            raise FileAlreadyExistsError(f"{dst_uri} already exists")
        self._check_parent_write(dst_lookup)
        if len(dst_lookup.missing_components) > 1:
            raise FileDoesNotExistError(
                f"parent of {dst_uri} does not exist")
        new_parent = dst_lookup.deepest
        if not new_parent.is_directory:
            raise InvalidPathError(f"parent of {dst_uri} is a file")
        now = self._now()
        persisted = inode.persistence_state == PersistenceState.PERSISTED
        if persisted:
            self._check_ufs_writable(src_uri)
        dst_anc = self._unpersisted_chain(new_parent, dst_uri) \
            if persisted else []
        if not anc_held and any(a.id != new_parent.id for a in dst_anc):
            return False  # caller retries under the exclusive tree lock
        if dst_anc:
            # the UFS rename will implicitly create dst's parent
            # chain; those inodes flip PERSISTED in the SAME journal
            # context as the RENAME (a second context would leave a
            # crash window replaying the rename with NOT_PERSISTED
            # dst parents — re-opening the ghost-tree bug), and
            # breadcrumbs land first
            self._ensure_ufs_parent_dirs(dst_uri)
        with self._journal.create_context() as ctx:
            ctx.append(EntryType.RENAME, {
                "id": inode.id, "new_parent_id": new_parent.id,
                "new_name": dst_uri.name, "op_time_ms": now})
            for cur in dst_anc:
                ctx.append(EntryType.PERSIST_FILE, {"id": cur.id})
        if persisted:
            self._rename_in_ufs(src_uri, dst_uri, inode.is_directory)
        self._absent_cache.remove(dst_uri.path)
        return True

    def _rename_in_ufs(self, src_uri: AlluxioURI, dst_uri: AlluxioURI,
                       is_dir: bool) -> None:
        try:
            src_res = self.mount_table.resolve(src_uri)
            dst_res = self.mount_table.resolve(dst_uri)
        except (NotFoundError, InvalidPathError):
            return
        ufs = self._ufs.get(src_res.mount_id)
        if is_dir:
            ufs.rename_directory(src_res.ufs_path, dst_res.ufs_path)
        else:
            ufs.rename_file(src_res.ufs_path, dst_res.ufs_path)

    # ----------------------------------------------------------------- free
    def journal_invalidations(self, paths: "List[str]") -> None:
        """Journal client-cache invalidations that have no metadata
        entry of their own (block-location drift: worker loss,
        quarantine/release, re-replication).  Routed through an
        ``INVALIDATE_PATH`` entry — never straight into the log — so the
        invalidation version stays a pure function of the applied
        journal and tailing standbys stamp the exact sequence the
        primary does (docs/ha.md)."""
        if not paths:
            return
        with self._journal.create_context() as ctx:
            for p in paths:
                ctx.append(EntryType.INVALIDATE_PATH, {"path": p})

    def free(self, path: "str | AlluxioURI", *, recursive: bool = False,
             forced: bool = False) -> List[int]:
        """Evict cached replicas; keep metadata + UFS copy
        (reference: ``free:2503``). Returns freed block ids."""
        uri = AlluxioURI(path)
        with self.inode_tree.lock_path(uri, write=True) as lip:
            lookup = lip.lookup
            inode = lookup.inode
            from alluxio_tpu.security.authorization import WRITE

            self._check_access(lookup, WRITE)
            targets: List[Inode] = []
            if inode.is_directory:
                if not recursive and self.inode_tree.has_children(inode):
                    raise DirectoryNotEmptyError(
                        f"{uri} is non-empty; need recursive")
                targets.extend(self.inode_tree.descendants(inode))
            targets.append(inode)
            block_ids: List[int] = []
            for t in targets:
                if t.is_directory:
                    continue
                if t.pinned and not forced:
                    raise InvalidArgumentError(
                        f"{self.inode_tree.get_path(t)} is pinned; "
                        "use forced free")
                if t.persistence_state != PersistenceState.PERSISTED:
                    raise FailedToFreeNonPersistedError(
                        f"{self.inode_tree.get_path(t)} is not persisted")
                block_ids.extend(t.block_ids)
            if forced or block_ids:
                with self._journal.create_context() as ctx:
                    if forced:
                        for t in targets:
                            if not t.is_directory and t.pinned:
                                ctx.append(EntryType.SET_ATTRIBUTE,
                                           {"id": t.id, "pinned": False})
                    if block_ids:
                        # freed replicas change location-derived fields
                        # (in-Alluxio state) under untouched inodes, so
                        # no other entry pushes the invalidation; one
                        # prefix covers the whole freed subtree
                        ctx.append(EntryType.INVALIDATE_PATH,
                                   {"path": uri.path})
        if block_ids:
            self._block_master.remove_blocks(block_ids, delete_metadata=False)
        return block_ids

    # ---------------------------------------------------------------- mount
    def mount(self, path: "str | AlluxioURI", ufs_uri: str, *,
              read_only: bool = False, shared: bool = False,
              properties: Optional[Dict[str, str]] = None) -> None:
        """Reference: ``mount:2736``."""
        uri = AlluxioURI(path)
        if uri.is_root():
            raise InvalidPathError("root mount is set at startup")
        # Validate the UFS BEFORE taking the tree lock: get_status is a
        # backing-store round trip (seconds against a cold object store)
        # and holding the global write lock across it would stall every
        # metadata operation cluster-wide.  The fresh mount_id is not
        # routable until ADD_MOUNT_POINT applies, so the early
        # UfsManager registration is invisible to readers; any failure
        # from here on removes it.
        mount_id = ids.create_mount_id()
        ufs = self._ufs.add_mount(mount_id, ufs_uri, properties)
        try:
            status = ufs.get_status(ufs_uri)
            if status is None or not status.is_directory:
                raise InvalidArgumentError(
                    f"UFS path {ufs_uri} is not an existing directory")
            with self.inode_tree.lock.write_locked():
                lookup = self.inode_tree.lookup(uri)
                if lookup.exists:
                    raise FileAlreadyExistsError(f"{uri} already exists")
                if len(lookup.missing_components) > 1:
                    raise FileDoesNotExistError(f"parent of {uri} must exist")
                self._check_parent_write(lookup)
                info = MountInfo(mount_id, uri.path, ufs_uri, read_only,
                                 shared, dict(properties or {}))
                now = self._now()
                cid = self._block_master.new_container_id()
                dir_inode = Inode.new_directory(
                    ids.file_id_from_container(cid), lookup.deepest.id,
                    uri.name, now_ms=now)
                dir_inode.mount_point = True
                dir_inode.persistence_state = PersistenceState.PERSISTED
                with self._journal.create_context() as ctx:
                    ctx.append(EntryType.INODE_DIRECTORY,
                               dir_inode.to_wire_dict())
                    ctx.append(EntryType.ADD_MOUNT_POINT, info.to_wire())
                # a new mount can reveal paths previously recorded absent
                self._absent_cache.clear()
        except Exception:
            self._ufs.remove_mount(mount_id)
            raise

    def unmount(self, path: "str | AlluxioURI") -> None:
        uri = AlluxioURI(path)
        with self.inode_tree.lock.write_locked():
            if not self.mount_table.is_mount_point(uri):
                raise InvalidPathError(f"{uri} is not a mount point")
            self._check_delete(self.inode_tree.lookup(uri))
            info = next(i for i in self.mount_table.mount_points()
                        if i.alluxio_path == uri.path)
            lookup = self.inode_tree.lookup(uri)
            victims = list(self.inode_tree.descendants(lookup.inode))
            victims.append(lookup.inode)
            block_ids = [b for v in victims for b in v.block_ids]
            now = self._now()
            with self._journal.create_context() as ctx:
                ctx.append(EntryType.DELETE_MOUNT_POINT, {"path": uri.path})
                for v in victims:
                    payload = {"id": v.id, "op_time_ms": now}
                    if v is not lookup.inode:
                        # unmount root's entry covers the subtree by
                        # prefix; see _delete_locked
                        payload["covered"] = True
                    ctx.append(EntryType.DELETE_FILE, payload)
            if block_ids:
                self._block_master.remove_blocks(block_ids,
                                                 delete_metadata=True)
            self._ufs.remove_mount(info.mount_id)

    def get_mount_points(self) -> List[MountPointInfo]:
        out = []
        for info in self.mount_table.mount_points():
            ufs_type = ""
            total = used = -1
            if self._ufs.has(info.mount_id):
                ufs = self._ufs.get(info.mount_id)
                ufs_type = ufs.get_underfs_type()
                total, used = ufs.get_space_total(), ufs.get_space_used()
            out.append(MountPointInfo(
                alluxio_path=info.alluxio_path,
                ufs_uri=info.ufs_uri, ufs_type=ufs_type,
                ufs_capacity_bytes=total, ufs_used_bytes=used,
                read_only=info.read_only, shared=info.shared,
                mount_id=info.mount_id, properties=dict(info.properties)))
        return out

    # --------------------------------------------------------- setAttribute
    def set_attribute(self, path: "str | AlluxioURI", *,
                      pinned: Optional[bool] = None,
                      pinned_media: Optional[List[str]] = None,
                      ttl: Optional[int] = None,
                      ttl_action: Optional[str] = None,
                      mode: Optional[int] = None,
                      owner: Optional[str] = None,
                      group: Optional[str] = None,
                      replication_min: Optional[int] = None,
                      replication_max: Optional[int] = None,
                      recursive: bool = False,
                      xattr: Optional[Dict[str, str]] = None) -> None:
        """Reference: ``setAttribute:3087``."""
        uri = AlluxioURI(path)
        if replication_min is not None and replication_max is not None and \
                0 <= replication_max < replication_min:
            raise InvalidArgumentError("replication_max < replication_min")
        with self.inode_tree.lock_path(uri, write=True) as lip:
            lookup = lip.lookup
            inode = lookup.inode
            user = self._auth_user()
            self._perm.check_traverse(user, lookup.inodes[:-1])
            if owner is not None:
                # chown is superuser-only (reference parity)
                self._perm.check_superuser(user)
            elif mode is not None or group is not None:
                self._perm.check_owner(user, inode, path=uri.path)
            else:
                from alluxio_tpu.security.authorization import WRITE

                self._perm.check(user, inode, WRITE, path=uri.path)
            if xattr is not None and any(k.startswith("system.")
                                         for k in xattr):
                # ACLs are managed via set_acl (owner-checked); letting a
                # WRITE-only caller plant system.* xattrs would forge ACLs
                raise InvalidArgumentError(
                    "system.* xattr keys cannot be set via set_attribute")
            targets = [inode]
            if recursive and inode.is_directory:
                targets.extend(self.inode_tree.descendants(inode))
            now = self._now()
            with self._journal.create_context() as ctx:
                for t in targets:
                    payload = {"id": t.id, "op_time_ms": now}
                    if pinned is not None:
                        payload["pinned"] = pinned
                        payload["pinned_media"] = pinned_media or []
                    if ttl is not None:
                        payload["ttl"] = ttl
                        payload["ttl_action"] = ttl_action or TtlAction.DELETE
                    if mode is not None:
                        payload["mode"] = mode
                    if owner is not None:
                        payload["owner"] = owner
                    if group is not None:
                        payload["group"] = group
                    if replication_min is not None:
                        payload["replication_min"] = replication_min
                    if replication_max is not None:
                        payload["replication_max"] = replication_max
                    if xattr is not None:
                        payload["xattr"] = xattr
                    ctx.append(EntryType.SET_ATTRIBUTE, payload)

    # -------------------------------------------------------------- ACLs
    from alluxio_tpu.security.authorization import (
        ACL_XATTR, DEFAULT_ACL_XATTR,
    )

    def set_acl(self, path: "str | AlluxioURI", entries: List[str], *,
                default: bool = False, recursive: bool = False) -> None:
        """Replace the extended ACL (reference: ``setAcl`` +
        ``SET_ACL`` journal entry). ``entries``: ``user:name:rwx`` strings;
        empty list removes the ACL. ``default=True`` sets the default ACL
        inherited by new children (directories only)."""
        from alluxio_tpu.security.authorization import AccessControlList

        AccessControlList.from_entries(entries)  # validate
        uri = AlluxioURI(path)
        with self.inode_tree.lock_path(uri, write=True) as lip:
            lookup = lip.lookup
            inode = lookup.inode
            user = self._auth_user()
            self._perm.check_traverse(user, lookup.inodes[:-1])
            self._perm.check_owner(user, inode, path=uri.path)
            if default and not inode.is_directory:
                raise InvalidArgumentError(
                    "default ACLs apply to directories only")
            key = self.DEFAULT_ACL_XATTR if default else self.ACL_XATTR
            targets = [inode]
            if recursive and inode.is_directory:
                targets.extend(
                    d for d in self.inode_tree.descendants(inode)
                    # default ACLs exist only on directories
                    if d.is_directory or not default)
            now = self._now()
            with self._journal.create_context() as ctx:
                for t in targets:
                    xattr = dict(t.xattr)
                    if entries:
                        xattr[key] = ",".join(entries)
                    else:
                        xattr.pop(key, None)
                    ctx.append(EntryType.SET_ACL, {
                        "id": t.id, "xattr": xattr, "op_time_ms": now})

    def get_acl(self, path: "str | AlluxioURI") -> Dict[str, List[str]]:
        """Owner/group/mode base entries + extended + default entries
        (reference: ``getAcl`` wire shape)."""
        from alluxio_tpu.security.authorization import bits_to_string

        uri = AlluxioURI(path)
        with self.inode_tree.lock_path(uri) as lip:
            lookup = lip.lookup
            inode = lookup.inode
            from alluxio_tpu.security.authorization import READ

            self._check_access(lookup, READ)
            base = [
                f"user:{inode.owner}:{bits_to_string((inode.mode >> 6) & 7)}",
                f"group:{inode.group}:{bits_to_string((inode.mode >> 3) & 7)}",
                f"other::{bits_to_string(inode.mode & 7)}",
            ]
            extended = inode.xattr.get(self.ACL_XATTR, "")
            default = inode.xattr.get(self.DEFAULT_ACL_XATTR, "")
            return {
                "owner": inode.owner, "group": inode.group,
                "mode": inode.mode,
                "entries": base + ([e for e in extended.split(",") if e]),
                "default_entries":
                    [e for e in default.split(",") if e],
            }

    def get_pinned_file_ids(self) -> Set[int]:
        # registry_lock, not the tree lock: striped mutations update the
        # pinned set at journal-apply time without holding the tree lock
        with self.inode_tree.registry_lock:
            return set(self.inode_tree.pinned_ids)

    def files_with_replication_constraints(self) -> List[Inode]:
        """Completed files whose replication is bounded — the
        ReplicationChecker's work list (reference:
        ``ReplicationChecker.java:57`` walks the replication-limited
        inode registry)."""
        with self.inode_tree.registry_lock:
            ids = list(self.inode_tree.replication_limited_ids)
        out = []
        for iid in ids:
            inode = self.inode_tree.get_inode(iid)
            if inode is not None and inode.completed:
                out.append(inode)
        return out

    # ------------------------------------------------------ persist control
    def schedule_async_persistence(self, path: "str | AlluxioURI") -> None:
        """Reference: ``scheduleAsyncPersistence:3209``."""
        uri = AlluxioURI(path)
        with self.inode_tree.lock_path(uri, write=True) as lip:
            from alluxio_tpu.security.authorization import WRITE

            self._check_access(lip.lookup, WRITE)
            inode = self._existing_inode(lip.lookup, uri)
            if not inode.completed:
                raise FileIncompleteError(f"{uri} is not completed")
            if inode.persistence_state == PersistenceState.PERSISTED:
                return
            with self._journal.create_context() as ctx:
                ctx.append(EntryType.SET_ATTRIBUTE, {
                    "id": inode.id,
                    "persistence_state": PersistenceState.TO_BE_PERSISTED})
            self._persist_requests.add(inode.id)

    def pop_persist_requests(self) -> "set[int]":
        """Drain scheduled persist work as inode IDS (consumed by the
        persistence scheduler heartbeat). Paths are deliberately NOT
        stored here — a stored path is stale-by-design after a rename;
        the scheduler re-resolves via ``current_path_of``."""
        out = set(self._persist_requests)
        self._persist_requests.clear()
        return out

    def _unpersisted_chain(self, start, mount_uri: AlluxioURI) -> list:
        """``start`` and its ancestors (nearest first) that are not yet
        PERSISTED, stopping at ``mount_uri``'s mount point: an OUTER
        mount's directories live in a different UFS namespace — a
        persist inside a nested mount must never flip them (their UFS
        has no such dir and breadcrumbs cannot be written there).
        Callers hold the tree lock."""
        mp = self.mount_table.get_mount_point(mount_uri)
        out = []
        cur = start
        while cur is not None and \
                cur.persistence_state != PersistenceState.PERSISTED:
            if self.mount_table.get_mount_point(
                    self.inode_tree.get_path(cur)) != mp:
                break
            out.append(cur)
            cur = self.inode_tree.parent_of(cur)
        return out

    def _journal_persisted(self, ctx, inode, ufs_fingerprint: str = "",
                           ancestors: "Optional[list]" = None) -> None:
        """Journal PERSIST_FILE for ``inode`` AND every not-yet-persisted
        ancestor directory within the same mount. The UFS write that
        made the file durable also created its parent directories in
        the UFS, so their inodes must say PERSISTED — otherwise
        renaming such a directory skips the UFS-side rename (``rename``
        gates on the DIR's state) and the old UFS tree gets resurrected
        by metadata sync (observed: ghost ``/cp`` after ``mv /cp
        /moved`` once ``/cp/f`` had persisted). Callers that computed
        the chain already (to order breadcrumbs before this durable
        flip) pass it via ``ancestors``."""
        ctx.append(EntryType.PERSIST_FILE, {
            "id": inode.id, "ufs_fingerprint": ufs_fingerprint})
        if ancestors is None:
            ancestors = self._unpersisted_chain(
                self.inode_tree.parent_of(inode),
                self.inode_tree.get_path(inode))
        for cur in ancestors:
            ctx.append(EntryType.PERSIST_FILE, {"id": cur.id})

    def _ensure_ufs_parent_dirs(self, uri: AlluxioURI) -> None:
        """Make the UFS parent chain of ``uri`` explicit (breadcrumb
        objects on object stores, real dirs elsewhere; idempotent). A
        directory inode marked PERSISTED must exist in the UFS in its
        own right — implicit-prefix-only existence means metadata sync
        would delete the directory (and its cache-only children) as
        soon as its last persisted file is removed."""
        parent = uri.parent()
        if parent is None:
            return
        try:
            res = self.mount_table.resolve(parent)
            self._ufs.get(res.mount_id).mkdirs(res.ufs_path)
        except Exception:  # noqa: BLE001 best-effort; sync self-heals
            LOG.debug("breadcrumb mkdirs for %s failed", parent,
                      exc_info=True)

    def current_path_of(self, inode_id: int) -> "Optional[str]":
        """Re-resolve an inode id to its CURRENT path (None when the
        inode no longer exists). Persistence tracks files by id so a
        rename between scheduling and submission keeps durability at
        the new path (reference: fileId-keyed ``PersistJob``)."""
        with self.inode_tree.lock.read_locked():
            uri = self.inode_tree.path_of_id(inode_id)
        return str(uri) if uri is not None else None

    def mark_persisted(self, path: "str | AlluxioURI",
                       ufs_fingerprint: str = "") -> None:
        """A worker/job reports the file durable in the UFS.  Same
        striped-fast-path / coarse-ancestor-flip split as
        :meth:`complete_file`."""
        uri = AlluxioURI(path)
        with self.inode_tree.lock_path(uri, write=True) as lip:
            inode = self._existing_inode(lip.lookup, uri)
            anc = self._unpersisted_chain(
                self.inode_tree.parent_of(inode), uri)
            if not anc:
                with self._journal.create_context() as ctx:
                    self._journal_persisted(ctx, inode, ufs_fingerprint,
                                            ancestors=anc)
                return
        with self.inode_tree.lock.write_locked():
            inode = self._existing_inode(self.inode_tree.lookup(uri), uri)
            anc = self._unpersisted_chain(
                self.inode_tree.parent_of(inode), uri)
            if anc:  # breadcrumbs BEFORE the durable flip
                self._ensure_ufs_parent_dirs(uri)
            with self._journal.create_context() as ctx:
                self._journal_persisted(ctx, inode, ufs_fingerprint,
                                        ancestors=anc)

    def commit_persist(self, path: "str | AlluxioURI",
                       temp_ufs_path: str, *,
                       expected_id: int = 0) -> str:
        """Atomically promote a temp UFS persist file written by a worker.

        The async-persist race (reference solves it the same way —
        persists go to a temporary UFS path and a master-side commit
        renames into place, ``DefaultFileSystemMaster`` persist jobs +
        ``UfsCleaner`` for abandoned temps): a worker finishing a persist
        AFTER the file was deleted must not leave a zombie UFS file that
        metadata sync would resurrect.

        ``expected_id`` pins the commit to the inode the persist was
        scheduled for: a delete+recreate at the same path must NOT get the
        old file's bytes renamed over its data. ``temp_ufs_path=""`` means
        a zero-block file — the final UFS file is created empty (without
        it, a later metadata sync would see a PERSISTED inode with no UFS
        object and remove the file).

        Three phases so the slow UFS rename doesn't stall the whole
        namespace behind the tree write lock: (1) validate under the
        lock, (2) rename with the tree lock RELEASED, (3) re-validate
        under the lock and journal — if the inode vanished or changed
        during (2), the just-renamed file is deleted, never journaled.
        A master-wide persist mutex serializes phase 2 across commits:
        without it, a commit for a RECREATED inode at the same path could
        land inside another commit's rename window and have its freshly
        committed UFS file overwritten/cleaned by the stale one. Every
        persist path (async, sync CACHE_THROUGH, zero-block) flows
        through this method, so the mutex covers all final-file writes."""
        uri = AlluxioURI(path)

        def _validated_inode():
            inode = self._existing_file(uri)
            if expected_id and inode.id != expected_id:
                raise FileDoesNotExistError(
                    f"{uri} was recreated (inode {inode.id} != persist "
                    f"target {expected_id})")
            return inode

        with self._persist_mutex:
            with self.inode_tree.lock.write_locked():
                try:
                    inode = _validated_inode()
                except (FileDoesNotExistError, InvalidPathError):
                    self._discard_temp(uri, temp_ufs_path)
                    raise
                resolution = self.mount_table.resolve(uri)
                anc_ids = [a.id for a in self._unpersisted_chain(
                    self.inode_tree.parent_of(inode), uri)]
            ufs = self._ufs.get(resolution.mount_id)
            # phase 2: UFS IO outside the tree lock (can be a
            # multi-second server-side copy on object stores).
            # Parent-chain breadcrumbs FIRST: the ancestors are about
            # to be journaled PERSISTED and must exist explicitly
            # (steady state — chain already persisted — skips the RPC)
            if anc_ids:
                self._ensure_ufs_parent_dirs(uri)
            if temp_ufs_path:
                if not ufs.rename_file(temp_ufs_path, resolution.ufs_path):
                    raise UnavailableError(
                        f"rename {temp_ufs_path} -> {resolution.ufs_path} "
                        "failed in the UFS")
            else:  # zero-block file: create the empty UFS object
                ufs.create(resolution.ufs_path).close()
            fp = ufs.get_fingerprint(resolution.ufs_path)
            fingerprint = fp.serialize() if fp is not None else ""
            with self.inode_tree.lock.write_locked():
                try:
                    inode = _validated_inode()
                except (FileDoesNotExistError, InvalidPathError):
                    # deleted/recreated during the rename: the delete's
                    # own UFS cleanup has already swept the directory —
                    # remove the file if it survived (no other persist
                    # can have committed here: we hold the mutex)
                    try:
                        ufs.delete_file(resolution.ufs_path)
                    except Exception:  # noqa: BLE001 best-effort
                        LOG.debug("post-rename cleanup failed for %s",
                                  resolution.ufs_path, exc_info=True)
                    raise
                with self._journal.create_context() as ctx:
                    self._journal_persisted(ctx, inode, fingerprint)
                return fingerprint

    def _discard_temp(self, uri: AlluxioURI, temp_ufs_path: str) -> None:
        if not temp_ufs_path:
            return
        try:
            resolution = self.mount_table.resolve(uri)
            ufs = self._ufs.get(resolution.mount_id)
            ufs.delete_file(temp_ufs_path)
            # the worker's temp write mkdirs'd the final file's parent
            # chain in the UFS (temps live next to their final files
            # for same-dir rename atomicity). When this commit failed
            # because the file MOVED (rename raced the persist), those
            # directories are namespace orphans now — metadata sync
            # would resurrect them as ghost paths (observed: /rp back
            # after `mv /rp /rp-moved` raced an async persist). Prune
            # empty orphaned parents bottom-up, stopping at the first
            # directory the namespace still knows, a non-empty one, or
            # the mount root.
            parent = uri.parent()
            ufs_dir = temp_ufs_path.rsplit("/", 1)[0]
            mount_root = resolution.mount_info.ufs_uri.rstrip("/")
            while parent is not None and parent.path not in ("", "/") \
                    and ufs_dir.rstrip("/") != mount_root:
                lookup = self.inode_tree.lookup(parent)
                if len(lookup.inodes) == \
                        1 + len(parent.path_components()):
                    break  # dir still exists in the namespace: owned
                if ufs.list_status(ufs_dir):
                    break  # not empty: someone else's contents
                if not ufs.delete_directory(ufs_dir):
                    break
                parent = parent.parent()
                ufs_dir = ufs_dir.rsplit("/", 1)[0]
        except Exception:  # noqa: BLE001 UfsCleaner sweeps later
            LOG.debug("temp persist cleanup failed for %s",
                      temp_ufs_path, exc_info=True)

    def file_system_heartbeat(self, worker_id: int,
                              persisted_files: List[int]) -> None:
        """Worker-reported persist completions
        (reference: FileSystemMasterWorkerService.FileSystemHeartbeat)."""
        for fid in persisted_files:
            inode = self.inode_tree.get_inode(fid)
            if inode is None:
                continue
            uri = self.inode_tree.get_path(inode)
            try:
                self.mark_persisted(uri)
            except FileDoesNotExistError:
                pass

    # ------------------------------------------------------- UFS metadata sync
    def _maybe_sync(self, uri: AlluxioURI, sync_interval_ms: int) -> bool:
        """On-access sync gate (reference: ``InodeSyncStream.java:115`` +
        ``UfsSyncPathCache``): -1 never, 0 always, >0 min interval. A
        recursive sync of an ancestor freshens this path too. Returns
        True when a sync actually ran — listings use that to force a
        UFS child re-list past ``direct_children_loaded``."""
        if not self._sync_cache.should_sync(uri.path, self._now(),
                                            sync_interval_ms):
            return False
        self.sync_metadata(uri)
        return True

    def sync_metadata(self, path: "str | AlluxioURI", *,
                      recursive: bool = False) -> bool:
        """Diff UFS vs inode state via fingerprints; reload on change.
        ``recursive`` extends the diff to the whole subtree (the
        ``DescendantType.ALL`` mode of ``InodeSyncStream``). Returns True
        if anything changed.

        Reconciliation runs with master privileges (auth user rebound to
        None, trusted in-process), matching the reference where
        ``InodeSyncStream`` performs internal deletes/loads as the master —
        a read-only caller's on-access sync must not fail permission checks
        for namespace repair it did not itself request."""
        from alluxio_tpu.security.user import (
            reset_authenticated_user, set_authenticated_user,
        )
        token = set_authenticated_user(None)
        try:
            uri = AlluxioURI(path)
            changed = self._sync_one(uri)
            if recursive:
                changed = self._sync_children(uri) or changed
            self._sync_cache.notify_synced(uri.path, self._now(),
                                           recursive=recursive)
            return changed
        finally:
            reset_authenticated_user(token)

    def _sync_one(self, uri: AlluxioURI, *,
                  status: "UfsStatus | None" = None,
                  status_known: bool = False) -> bool:
        """``status_known=True`` means the caller already holds the UFS
        status (e.g. from a directory listing) — skip the per-path probe."""
        try:
            resolution = self.mount_table.resolve(uri)
        except (NotFoundError, InvalidPathError):
            return False
        ufs = self._ufs.get(resolution.mount_id)
        if not status_known:
            status = ufs.get_status(resolution.ufs_path)
        with self.inode_tree.lock.read_locked():
            lookup = self.inode_tree.lookup(uri)
            exists = lookup.exists
            inode = lookup.inode if exists else None
        if status is None:
            self._absent_cache.add(uri.path)
            if exists and inode.persistence_state == PersistenceState.PERSISTED:
                # UFS deleted it out-of-band
                self.delete(uri, recursive=True, alluxio_only=True)
                return True
            return False
        self._absent_cache.remove(uri.path)
        new_fp = Fingerprint.from_status(status)
        if not exists:
            self._load_metadata_if_exists(uri, status=status)
            return True
        if inode.is_directory != status.is_directory:
            self.delete(uri, recursive=True, alluxio_only=True)
            self._load_metadata_if_exists(uri, status=status)
            return True
        old_fp = Fingerprint.parse(inode.ufs_fingerprint)
        if not inode.is_directory and not new_fp.matches_content(old_fp) and \
                inode.persistence_state == PersistenceState.PERSISTED:
            # content changed under us: drop cached blocks + metadata, reload
            self.delete(uri, recursive=False, alluxio_only=True)
            self._load_metadata_if_exists(uri, status=status)
            return True
        return False

    def _sync_children(self, uri: AlluxioURI) -> bool:
        """Recursive UFS-vs-tree diff below ``uri``: load new UFS entries,
        re-check known ones, drop persisted inodes the UFS lost."""
        try:
            resolution = self.mount_table.resolve(uri)
        except (NotFoundError, InvalidPathError):
            return False
        if not self._ufs.has(resolution.mount_id):
            return False
        ufs = self._ufs.get(resolution.mount_id)
        listing = ufs.list_status(resolution.ufs_path)
        if listing is None:
            return False
        from alluxio_tpu.master.integrity import is_infra_temp

        # in-flight/abandoned framework temps (persist temps, atomic-
        # create temps) are infrastructure, not data: loading one would
        # surface it as a file and break when the rename removes it
        ufs_names = {st.name: st for st in listing
                     if not is_infra_temp(st.name)}
        changed = False
        with self.inode_tree.lock.read_locked():
            lookup = self.inode_tree.lookup(uri)
            if not lookup.exists or not lookup.inode.is_directory:
                return False
            known = {c.name: c for c in
                     self.inode_tree.children(lookup.inode)}
        # UFS entries unknown to the tree -> load; the listing already
        # carries each child's status, so no per-child UFS probe is needed
        for name, st in ufs_names.items():
            child = uri.join(name)
            if name not in known:
                self._load_metadata_if_exists(child, status=st)
                changed = True
            else:
                changed = self._sync_one(child, status=st,
                                         status_known=True) or changed
            if st.is_directory:
                changed = self._sync_children(child) or changed
        # persisted inodes gone from the UFS -> drop (cache-only stays)
        for name, inode in known.items():
            if name not in ufs_names and \
                    inode.persistence_state == PersistenceState.PERSISTED:
                self.delete(uri.join(name), recursive=True,
                            alluxio_only=True)
                changed = True
        return changed

    def _load_metadata_if_exists(self, uri: AlluxioURI, *,
                                 status: "UfsStatus | None" = None
                                 ) -> Optional[FileInfo]:
        """Create inodes mirroring an existing UFS path (metadata load on
        access — reference: ``InodeSyncStream`` loadMetadata). A caller
        that already holds the UFS status passes it to skip the probe."""
        from alluxio_tpu.master.integrity import is_infra_temp

        if is_infra_temp(uri.name):
            return None  # framework temps never enter the namespace
        if status is None and self._absent_cache.is_absent(uri.path):
            return None
        try:
            resolution = self.mount_table.resolve(uri)
        except (NotFoundError, InvalidPathError):
            return None
        if not self._ufs.has(resolution.mount_id):
            return None
        ufs = self._ufs.get(resolution.mount_id)
        if status is None:
            status = ufs.get_status(resolution.ufs_path)
        if status is None:
            self._absent_cache.add(uri.path)
            return None
        with self.inode_tree.lock.write_locked():
            lookup = self.inode_tree.lookup(uri)
            if lookup.exists:
                return self._file_info(lookup.inode, uri)
            # ensure ancestors exist (each may itself be a UFS dir)
            now = self._now()
            parent_id = lookup.deepest.id
            with self._journal.create_context() as ctx:
                for name in lookup.missing_components[:-1]:
                    cid = self._block_master.new_container_id()
                    d = Inode.new_directory(
                        ids.file_id_from_container(cid), parent_id, name,
                        now_ms=now)
                    d.persistence_state = PersistenceState.PERSISTED
                    ctx.append(EntryType.INODE_DIRECTORY, d.to_wire_dict())
                    parent_id = d.id
                cid = self._block_master.new_container_id()
                if status.is_directory:
                    inode = Inode.new_directory(
                        ids.file_id_from_container(cid), parent_id, uri.name,
                        now_ms=now)
                else:
                    inode = Inode.new_file(
                        cid, parent_id, uri.name,
                        block_size_bytes=self._default_block_size, now_ms=now)
                    inode.length = status.length
                    inode.completed = True
                    n_blocks = ((status.length + self._default_block_size - 1)
                                // self._default_block_size)
                    inode.block_ids = [ids.block_id(cid, i)
                                       for i in range(n_blocks)]
                inode.persistence_state = PersistenceState.PERSISTED
                inode.ufs_fingerprint = Fingerprint.from_status(
                    status).serialize()
                if status.mode is not None:
                    inode.mode = status.mode
                ctx.append(EntryType.INODE_FILE if not status.is_directory
                           else EntryType.INODE_DIRECTORY,
                           inode.to_wire_dict())
            # register block lengths so reads can size them
            if not status.is_directory:
                fresh = self.inode_tree.get_inode(inode.id)
                remaining = status.length
                for bid in fresh.block_ids:
                    self._block_master.commit_block_in_ufs(
                        bid, min(self._default_block_size, remaining))
                    remaining -= self._default_block_size
            return self._file_info(self.inode_tree.get_inode(inode.id), uri)

    def _load_children_if_needed(self, uri: AlluxioURI,
                                 force: bool = False) -> None:
        """List the UFS dir and load any children absent from the tree —
        ONCE per directory: ``direct_children_loaded`` marks a dir whose
        UFS children are in the tree, and subsequent listings skip the
        UFS round trip entirely. A listing whose sync-interval fired
        passes ``force=True`` to re-list past the flag (that is HOW
        external UFS changes surface — reference:
        ``InodeDirectory.isDirectChildrenLoaded`` +
        ``DefaultFileSystemMaster.listStatus`` descendant sync)."""
        if not force:
            with self.inode_tree.lock.read_locked():
                lookup = self.inode_tree.lookup(uri)
                if lookup.exists and lookup.inode.direct_children_loaded:
                    return
        try:
            resolution = self.mount_table.resolve(uri)
        except (NotFoundError, InvalidPathError):
            return
        if not self._ufs.has(resolution.mount_id):
            return
        ufs = self._ufs.get(resolution.mount_id)
        children = ufs.list_status(resolution.ufs_path)
        if children is None:
            # could not list (UFS dir gone/unreadable) — the once-only
            # flag must NOT latch on this outcome or the children would
            # be hidden forever once the dir reappears
            return
        with self.inode_tree.lock.read_locked():
            lookup = self.inode_tree.lookup(uri)
            if not lookup.exists:
                return
            known = set(self.inode_tree.child_names(lookup.inode))
        for st in children:
            if st.name not in known:
                self._load_metadata_if_exists(uri.join(st.name))
        self._mark_children_loaded(uri)

    def _mark_children_loaded(self, uri: AlluxioURI) -> None:
        """Journal ``direct_children_loaded`` so the once-only contract
        survives failover (the flag rides the same INODE_DIRECTORY
        upsert entries create_file journals for implicit parents)."""
        with self.inode_tree.lock.write_locked():
            lookup = self.inode_tree.lookup(uri)
            if not lookup.exists or not lookup.inode.is_directory or \
                    lookup.inode.direct_children_loaded:
                return
            with self._journal.create_context() as ctx:
                ctx.append(EntryType.UPDATE_INODE,
                           {"id": lookup.inode.id,
                            "direct_children_loaded": True})

    # --------------------------------------------------------------- TTL
    def check_ttl_expired(self) -> List[str]:
        """One TTL-checker tick (reference: ``InodeTtlChecker.java``):
        apply DELETE/FREE actions to expired inodes. Returns acted paths."""
        now = self._now()
        expired = self.inode_tree.ttl_buckets.poll_expired(now)
        acted: List[str] = []
        for iid in expired:
            inode = self.inode_tree.get_inode(iid)
            if inode is None:
                self.inode_tree.ttl_buckets.remove(iid)
                continue
            uri = self.inode_tree.get_path(inode)
            try:
                if inode.ttl_action == TtlAction.FREE:
                    self.free(uri, recursive=True, forced=True)
                    self.set_attribute(uri, ttl=-1)
                else:
                    self.delete(uri, recursive=True, alluxio_only=not (
                        inode.persistence_state == PersistenceState.PERSISTED))
                acted.append(uri.path)
            except Exception as e:  # noqa: BLE001 - retried next tick
                LOG.warning("TTL action %s on %s failed (retrying next "
                            "tick): %s", inode.ttl_action, uri, e)
                continue
            self.inode_tree.ttl_buckets.remove(iid)
        return acted


@register_wire_error
class FailedToFreeNonPersistedError(InvalidArgumentError):
    pass


class _MountTableJournal:
    """Adapter making MountTable a Journaled component."""

    journal_name = "MountTable"

    def __init__(self, table: MountTable, *,
                 invalidation_sink=None) -> None:
        self._table = table
        self._invalidation_sink = invalidation_sink

    def process_entry(self, entry) -> bool:
        if entry.type == EntryType.ADD_MOUNT_POINT:
            info = MountInfo.from_wire(entry.payload)
            self._table.add(info)
            if self._invalidation_sink is not None:
                self._invalidation_sink(info.alluxio_path)
            return True
        if entry.type == EntryType.DELETE_MOUNT_POINT:
            self._table.delete(entry.payload["path"])
            if self._invalidation_sink is not None:
                self._invalidation_sink(entry.payload["path"])
            return True
        return False

    def snapshot(self) -> dict:
        return {"mounts": self._table.snapshot()}

    def restore(self, snap: dict) -> None:
        self._table.restore(snap.get("mounts", []))

    def reset_state(self) -> None:
        self._table.clear()
