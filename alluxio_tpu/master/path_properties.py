"""Per-path configuration defaults + cluster config consistency check.

Re-designs of ``core/server/master/.../meta/PathProperties.java`` (journaled
path -> {property: value} map distributed to clients, longest-prefix wins)
and ``meta/checkconf/ServerConfigurationChecker.java`` (compare the configs
registered by cluster nodes and report conflicts on keys that must agree).
"""

from __future__ import annotations

import hashlib
import threading
from typing import Dict, List, Optional, Tuple

from alluxio_tpu.conf import REGISTRY
from alluxio_tpu.journal.format import EntryType
from alluxio_tpu.utils.exceptions import InvalidArgumentError
from alluxio_tpu.utils.uri import AlluxioURI


def resolve_path_property(props: Dict[str, Dict[str, str]], path: str,
                          key: str) -> Optional[str]:
    """Longest-prefix match over a path->properties map (reference:
    PathPropertiesView + PrefixPathMatcher); shared by master and the
    client-side cached view."""
    path = AlluxioURI(path).path
    best: Tuple[int, Optional[str]] = (-1, None)
    for prefix, kv in props.items():
        if key not in kv:
            continue
        if path == prefix or path.startswith(
                prefix.rstrip("/") + "/") or prefix == "/":
            if len(prefix) > best[0]:
                best = (len(prefix), kv[key])
    return best[1]


class PathProperties:
    """Journaled path-prefix -> {key: value} (reference: PathProperties)."""

    journal_name = "PathProperties"

    def __init__(self, journal) -> None:
        self._journal = journal
        self._props: Dict[str, Dict[str, str]] = {}
        self._lock = threading.Lock()
        # serializes add/remove: each journals the FULL merged map, so two
        # concurrent mutators reading the same pre-state would lose one
        # caller's keys (read-modify-write race). Separate from self._lock
        # because journal application re-enters process_entry -> self._lock.
        self._mutate_lock = threading.Lock()
        journal.register(self)

    # -- API -----------------------------------------------------------------
    def add(self, path: str, properties: Dict[str, str]) -> None:
        path = AlluxioURI(path).path
        for k in properties:
            if not REGISTRY.is_valid(k):
                raise InvalidArgumentError(f"unknown property key: {k}")
        with self._mutate_lock:
            with self._lock:
                merged = dict(self._props.get(path, {}))
            merged.update({k: str(v) for k, v in properties.items()})
            with self._journal.create_context() as ctx:
                ctx.append(EntryType.PATH_PROPERTIES,
                           {"path": path, "properties": merged})

    def remove(self, path: str, keys: Optional[List[str]] = None) -> None:
        path = AlluxioURI(path).path
        with self._mutate_lock:
            with self._lock:
                if path not in self._props:
                    return
                if keys:
                    remaining = {k: v for k, v in self._props[path].items()
                                 if k not in keys}
                else:
                    remaining = {}
            if remaining:
                with self._journal.create_context() as ctx:
                    ctx.append(EntryType.PATH_PROPERTIES,
                               {"path": path, "properties": remaining})
            else:
                with self._journal.create_context() as ctx:
                    ctx.append(EntryType.REMOVE_PATH_PROPERTIES,
                               {"path": path})

    def get_all(self) -> Dict[str, Dict[str, str]]:
        with self._lock:
            return {p: dict(kv) for p, kv in self._props.items()}

    def hash(self) -> str:
        h = hashlib.md5()
        with self._lock:
            for p in sorted(self._props):
                for k in sorted(self._props[p]):
                    h.update(f"{p}|{k}={self._props[p][k]};".encode())
        return h.hexdigest()

    def resolve(self, path: str, key: str) -> Optional[str]:
        return resolve_path_property(self.get_all(), path, key)

    # -- journal contract ----------------------------------------------------
    def process_entry(self, entry) -> bool:
        if entry.type == EntryType.PATH_PROPERTIES:
            with self._lock:
                self._props[entry.payload["path"]] = dict(
                    entry.payload.get("properties", {}))
            return True
        if entry.type == EntryType.REMOVE_PATH_PROPERTIES:
            with self._lock:
                self._props.pop(entry.payload["path"], None)
            return True
        return False

    def snapshot(self) -> dict:
        return {"props": self.get_all()}

    def restore(self, snap: dict) -> None:
        with self._lock:
            self._props = {p: dict(kv)
                           for p, kv in snap.get("props", {}).items()}

    def reset_state(self) -> None:
        with self._lock:
            self._props.clear()


class ConfigurationChecker:
    """Cross-node config consistency (reference:
    ServerConfigurationChecker): nodes report their config at registration;
    keys marked ENFORCE must agree everywhere, WARN keys produce warnings."""

    def __init__(self) -> None:
        self._reports: Dict[str, Dict[str, str]] = {}  # node id -> config
        self._lock = threading.Lock()

    def register(self, node_id: str, config: Dict[str, str]) -> None:
        with self._lock:
            self._reports[node_id] = {str(k): str(v)
                                      for k, v in config.items()}

    def forget(self, node_id: str) -> None:
        with self._lock:
            self._reports.pop(node_id, None)

    def report(self) -> dict:
        """{'status': PASSED|WARN|FAILED, 'errors': [...], 'warns': [...]}"""
        from alluxio_tpu.conf.property_key import ConsistencyLevel

        with self._lock:
            reports = {n: dict(c) for n, c in self._reports.items()}
        keys = set()
        for c in reports.values():
            keys.update(c)
        errors: List[str] = []
        warns: List[str] = []
        for key in sorted(keys):
            values: Dict[str, List[str]] = {}
            for node, c in reports.items():
                if key in c:
                    values.setdefault(c[key], []).append(node)
            if len(values) <= 1:
                continue
            pk = REGISTRY.get(key)
            level = getattr(pk, "consistency", None) if pk else None
            desc = ", ".join(f"{v!r} on [{', '.join(sorted(ns))}]"
                             for v, ns in sorted(values.items()))
            if level == ConsistencyLevel.ENFORCE:
                errors.append(f"{key}: {desc}")
            else:
                warns.append(f"{key}: {desc}")
        status = "FAILED" if errors else ("WARN" if warns else "PASSED")
        return {"status": status, "errors": errors, "warns": warns}
