"""Key/value codecs for the LSM metastore.

One flat, ordered byte-keyspace holds both record families (reference:
``rocks/RocksInodeStore.java`` keeps inodes and edges in two column
families; a single prefixed keyspace gives the same separation with one
set of runs):

- inode records:  ``b'i' + be64(inode_id)``          -> msgpack wire dict
- edge records:   ``b'e' + be64(parent_id) + name``  -> be64(child_id)

Big-endian fixed-width ids make byte order == numeric order, so every
edge of one directory is CONTIGUOUS and sorted by child name: the
``children()`` call the list paths hammer is a single range scan over
``edge_prefix(parent_id)``.  (``b'e' < b'i'``, so the two families never
interleave.)
"""

from __future__ import annotations

import struct
from typing import Tuple

_BE64 = struct.Struct(">Q")

INODE_PREFIX = b"i"
EDGE_PREFIX = b"e"


def inode_key(inode_id: int) -> bytes:
    return INODE_PREFIX + _BE64.pack(inode_id)


def decode_inode_key(key: bytes) -> int:
    return _BE64.unpack_from(key, 1)[0]


def edge_key(parent_id: int, name: str) -> bytes:
    return EDGE_PREFIX + _BE64.pack(parent_id) + name.encode("utf-8")


def edge_prefix(parent_id: int) -> bytes:
    return EDGE_PREFIX + _BE64.pack(parent_id)


def decode_edge_key(key: bytes) -> Tuple[int, str]:
    return _BE64.unpack_from(key, 1)[0], key[9:].decode("utf-8")


def edge_value(child_id: int) -> bytes:
    return _BE64.pack(child_id)


def decode_edge_value(value: bytes) -> int:
    return _BE64.unpack(value)[0]
