"""Write-back LRU cache over a backing store
(reference: ``metastore/caching/CachingInodeStore.java:91``).

Over the LSM store this is the "hot set" layer: the working set of a
training job (the shard directories being listed and the files being
opened) stays heap-speed while the cold namespace lives in the runs.
``stats()`` surfaces hit/miss counters — the
``Master.MetastoreCacheHitRatio`` gauge — merged over the backing
store's own stats.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Tuple

from alluxio_tpu.master.inode import Inode
from alluxio_tpu.master.metastore.base import InodeStore


class CachingInodeStore(InodeStore):
    def __init__(self, backing: InodeStore, max_size: int = 100_000) -> None:
        self._backing = backing
        self._max = max_size
        self._cache: "OrderedDict[int, Inode]" = OrderedDict()
        self._dirty: set = set()
        self._lock = threading.RLock()
        self._hits = 0
        self._misses = 0

    @property
    def backing(self) -> InodeStore:
        return self._backing

    def get(self, inode_id: int) -> Optional[Inode]:
        with self._lock:
            if inode_id in self._cache:
                self._hits += 1
                self._cache.move_to_end(inode_id)
                return self._cache[inode_id]
            self._misses += 1
        inode = self._backing.get(inode_id)
        if inode is not None:
            with self._lock:
                self._cache[inode_id] = inode
                self._evict_locked()
        return inode

    def put(self, inode: Inode) -> None:
        with self._lock:
            self._cache[inode.id] = inode
            self._cache.move_to_end(inode.id)
            self._dirty.add(inode.id)
            self._evict_locked()

    def _evict_locked(self) -> None:
        while len(self._cache) > self._max:
            victim_id, victim = self._cache.popitem(last=False)
            if victim_id in self._dirty:
                self._backing.put(victim)
                self._dirty.discard(victim_id)

    def remove(self, inode_id: int) -> None:
        with self._lock:
            self._cache.pop(inode_id, None)
            self._dirty.discard(inode_id)
        self._backing.remove(inode_id)

    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        self._backing.add_child(parent_id, name, child_id)

    def remove_child(self, parent_id: int, name: str) -> None:
        self._backing.remove_child(parent_id, name)

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        return self._backing.get_child_id(parent_id, name)

    def child_names(self, parent_id: int) -> List[str]:
        return self._backing.child_names(parent_id)

    def child_count(self, parent_id: int) -> int:
        return self._backing.child_count(parent_id)

    def iter_edges(self, parent_id: int,
                   start_after: Optional[str] = None) \
            -> Iterator[Tuple[str, int]]:
        # edges write through, so the backing store's scan is authoritative
        return self._backing.iter_edges(parent_id, start_after)

    def has_children(self, parent_id: int) -> bool:
        return self._backing.has_children(parent_id)

    def iter_inodes(self) -> Iterator[Inode]:
        self.flush()
        return self._backing.iter_inodes()

    def all_ids(self) -> Iterator[int]:
        self.flush()
        return self._backing.all_ids()

    def flush(self) -> None:
        with self._lock:
            for iid in list(self._dirty):
                inode = self._cache.get(iid)
                if inode is not None:
                    self._backing.put(inode)
            self._dirty.clear()

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()
            self._dirty.clear()
        self._backing.clear()

    def close(self) -> None:
        self.flush()
        self._backing.close()

    def estimated_size(self) -> int:
        self.flush()
        return self._backing.estimated_size()

    def stats(self) -> Dict[str, object]:
        # Write-back means the backing inode count excludes dirty
        # cache residents; flush so the reported counts are truthful.
        self.flush()
        out = dict(self._backing.stats())
        with self._lock:
            hits, misses = self._hits, self._misses
            out["cache_entries"] = len(self._cache)
        out["cache_hits"] = hits
        out["cache_misses"] = misses
        out["cache_hit_ratio"] = round(hits / (hits + misses), 4) \
            if hits + misses else 0.0
        out["kind"] = f"CACHING:{out.get('kind', '?')}"
        return out

    def checkpoint_state(self) -> Optional[dict]:
        self.flush()
        return self._backing.checkpoint_state()

    def restore_state(self, state: dict) -> None:
        with self._lock:
            self._cache.clear()
            self._dirty.clear()
        self._backing.restore_state(state)
