"""``InodeStore`` — the contract every metastore backend implements.

Beyond the original point ops, the contract now carries an ITERATOR
surface (``iter_edges`` / ``iter_inodes`` / ``has_children``) so list
paths can stream a directory page-by-page instead of materializing it:
``InodeTree.children()`` and the ListStatus paged path ride
``iter_edges``, which LSM serves as a single range scan and SQLite as an
ordered SELECT.  The base-class defaults keep third-party stores working
unchanged (they synthesize the iterators from ``child_names`` +
``get_child_id``).

Stores that can snapshot themselves faster than an inode-by-inode dump
(LSM: sealed runs + WAL position) override ``checkpoint_state`` /
``restore_state``; ``InodeTree.snapshot`` delegates when available.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from alluxio_tpu.master.inode import Inode


class InodeStore:
    def get(self, inode_id: int) -> Optional[Inode]:
        raise NotImplementedError

    def put(self, inode: Inode) -> None:
        raise NotImplementedError

    def remove(self, inode_id: int) -> None:
        raise NotImplementedError

    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        raise NotImplementedError

    def remove_child(self, parent_id: int, name: str) -> None:
        raise NotImplementedError

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        raise NotImplementedError

    def child_names(self, parent_id: int) -> List[str]:
        raise NotImplementedError

    def child_count(self, parent_id: int) -> int:
        return len(self.child_names(parent_id))

    def all_ids(self) -> Iterator[int]:
        raise NotImplementedError

    def clear(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    def flush(self) -> None:
        pass

    def estimated_size(self) -> int:
        raise NotImplementedError

    # -------------------------------------------------- iterator contract
    def iter_edges(self, parent_id: int,
                   start_after: Optional[str] = None) \
            -> Iterator[Tuple[str, int]]:
        """Children of ``parent_id`` as ``(name, child_id)`` in name
        order, starting strictly after ``start_after`` — the resumable
        cursor paged listings hand back to the client."""
        for name in self.child_names(parent_id):
            if start_after is not None and name <= start_after:
                continue
            child_id = self.get_child_id(parent_id, name)
            if child_id is not None:
                yield name, child_id

    def iter_inodes(self) -> Iterator[Inode]:
        for inode_id in self.all_ids():
            inode = self.get(inode_id)
            if inode is not None:
                yield inode

    def has_children(self, parent_id: int) -> bool:
        """Cheap emptiness probe — delete paths need "any child at all?",
        not the full (possibly millions-long) name list."""
        return next(self.iter_edges(parent_id), None) is not None

    # ------------------------------------------------------ observability
    def stats(self) -> Dict[str, object]:
        return {"kind": type(self).__name__, "inodes": self.estimated_size()}

    # ------------------------------------------------- native checkpoints
    def checkpoint_state(self) -> Optional[dict]:
        """Store-native checkpoint payload, or ``None`` if the store has
        no cheaper representation than an inode-by-inode dump."""
        return None

    def restore_state(self, state: dict) -> None:
        raise NotImplementedError(
            f"{type(self).__name__} has no native checkpoint format")
