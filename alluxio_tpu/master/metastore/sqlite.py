"""Disk-backed store on stdlib ``sqlite3`` in the RocksDB role
(metadata larger than RAM, cheap restart), WAL mode."""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Dict, Iterator, List, Optional, Tuple

import msgpack

from alluxio_tpu.master.inode import Inode
from alluxio_tpu.master.metastore.base import InodeStore


class SqliteInodeStore(InodeStore):
    """Disk-backed store in the RocksDB role (metadata > RAM, fast
    restart)."""

    def __init__(self, directory: str) -> None:
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "inodes.db")
        self._conn = sqlite3.connect(self._path, check_same_thread=False)
        self._lock = threading.RLock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS inodes "
                "(id INTEGER PRIMARY KEY, data BLOB NOT NULL)")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS edges "
                "(parent_id INTEGER NOT NULL, name TEXT NOT NULL, "
                "child_id INTEGER NOT NULL, PRIMARY KEY (parent_id, name))")
            self._conn.commit()

    def get(self, inode_id: int) -> Optional[Inode]:
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM inodes WHERE id=?", (inode_id,)).fetchone()
        if row is None:
            return None
        return Inode.from_wire_dict(msgpack.unpackb(row[0], raw=False))

    def put(self, inode: Inode) -> None:
        blob = msgpack.packb(inode.to_wire_dict(), use_bin_type=True)
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO inodes (id, data) VALUES (?, ?)",
                (inode.id, blob))
            self._conn.commit()

    def remove(self, inode_id: int) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM inodes WHERE id=?", (inode_id,))
            self._conn.commit()

    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO edges (parent_id, name, child_id) "
                "VALUES (?, ?, ?)", (parent_id, name, child_id))
            self._conn.commit()

    def remove_child(self, parent_id: int, name: str) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM edges WHERE parent_id=? AND name=?",
                (parent_id, name))
            self._conn.commit()

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        with self._lock:
            row = self._conn.execute(
                "SELECT child_id FROM edges WHERE parent_id=? AND name=?",
                (parent_id, name)).fetchone()
        return row[0] if row else None

    def child_names(self, parent_id: int) -> List[str]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT name FROM edges WHERE parent_id=? ORDER BY name",
                (parent_id,)).fetchall()
        return [r[0] for r in rows]

    def iter_edges(self, parent_id: int,
                   start_after: Optional[str] = None) \
            -> Iterator[Tuple[str, int]]:
        # paged SELECTs (resumed by name cursor) instead of one giant
        # fetchall: the connection lock is only held per page
        cursor = start_after
        while True:
            with self._lock:
                if cursor is None:
                    rows = self._conn.execute(
                        "SELECT name, child_id FROM edges WHERE parent_id=? "
                        "ORDER BY name LIMIT 1024", (parent_id,)).fetchall()
                else:
                    rows = self._conn.execute(
                        "SELECT name, child_id FROM edges WHERE parent_id=? "
                        "AND name>? ORDER BY name LIMIT 1024",
                        (parent_id, cursor)).fetchall()
            if not rows:
                return
            for name, child_id in rows:
                yield name, child_id
            cursor = rows[-1][0]

    def has_children(self, parent_id: int) -> bool:
        with self._lock:
            row = self._conn.execute(
                "SELECT 1 FROM edges WHERE parent_id=? LIMIT 1",
                (parent_id,)).fetchone()
        return row is not None

    def child_count(self, parent_id: int) -> int:
        with self._lock:
            return self._conn.execute(
                "SELECT COUNT(*) FROM edges WHERE parent_id=?",
                (parent_id,)).fetchone()[0]

    def all_ids(self) -> Iterator[int]:
        with self._lock:
            rows = self._conn.execute("SELECT id FROM inodes").fetchall()
        return iter([r[0] for r in rows])

    def clear(self) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM inodes")
            self._conn.execute("DELETE FROM edges")
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()

    def estimated_size(self) -> int:
        with self._lock:
            return self._conn.execute(
                "SELECT COUNT(*) FROM inodes").fetchone()[0]

    def stats(self) -> Dict[str, object]:
        with self._lock:
            inodes = self._conn.execute(
                "SELECT COUNT(*) FROM inodes").fetchone()[0]
            edges = self._conn.execute(
                "SELECT COUNT(*) FROM edges").fetchone()[0]
        return {"kind": "SQLITE", "inodes": inodes, "edges": edges}
