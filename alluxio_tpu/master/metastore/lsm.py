"""``LsmInodeStore`` — log-structured merge-tree inode + edge store.

The capacity backend (reference: ``rocks/RocksInodeStore.java`` — the
reference gets a billion-inode namespace by putting metadata behind
RocksDB; this is the same shape built on the stdlib):

- every mutation appends to a CRC-framed WAL (``wal.py``) and lands in a
  sorted in-memory **memtable** (a dict; sorted once, at seal time);
- when the memtable passes ``memtable_bytes`` it is sealed into an
  immutable **sorted run** (``sstable.py``: sparse index + bloom filter)
  and the WAL truncated;
- a background thread runs **size-tiered compaction**: ≥
  ``max_runs_per_tier`` adjacent runs of the same size tier merge
  (streaming) into one; newest value wins, tombstones dropped only when
  the oldest run is in the merge (else deletes would resurrect);
- reads check memtable → runs newest-first, bloom filters short-circuit
  the runs that can't hold the key; ``children()`` is a k-way merge of
  range scans over the ``(parent_id, name)``-ordered edge keyspace.

RAM cost is memtable + per-run index/bloom — the namespace itself lives
on disk under ``atpu.master.metastore.dir`` (the
``metadata-lsm-capacity`` bench row walks 10M inodes under an RSS cap
that OOMs the heap store).

Run ordering is held in a ``MANIFEST`` (atomic tmp+rename, newest
first); recovery = read manifest, open runs, replay the WAL tail into
the memtable.  ``checkpoint_state`` seals the memtable and captures the
run set, so a journal checkpoint of an LSM namespace is "sealed runs +
WAL position (empty)" rather than a million-entry inode dump.

Concurrency: point ops serialize on one RLock (cheap — they are dict
hits or single preads).  Range scans snapshot the memtable slice + run
list up front and then stream OUTSIDE the lock; each scanned run carries
a refcount so a compaction can retire it safely mid-scan (the file is
unlinked, the fd stays open until the last scan finishes).
"""

from __future__ import annotations

import heapq
import os
import threading
from typing import Dict, Iterator, List, Optional, Tuple

import msgpack

from alluxio_tpu.master.inode import Inode
from alluxio_tpu.master.metastore import encoding as enc
from alluxio_tpu.master.metastore.base import InodeStore
from alluxio_tpu.master.metastore.sstable import (MISSING, SortedRun,
                                                  write_run)
from alluxio_tpu.master.metastore.wal import WriteAheadLog

_MANIFEST = "MANIFEST"
_WAL = "wal.log"
_INODE_SCAN_END = enc.INODE_PREFIX + b"\xff" * 9


class LsmInodeStore(InodeStore):
    def __init__(self, directory: str, *,
                 memtable_bytes: int = 8 << 20,
                 max_runs_per_tier: int = 4,
                 bloom_bits_per_key: int = 10,
                 wal_sync: bool = False,
                 compaction: bool = True,
                 compaction_poll_s: float = 0.05) -> None:
        os.makedirs(directory, exist_ok=True)
        self._dir = directory
        # floor keeps a misconfigured limit from flushing every write,
        # while staying small enough that tests can force real flushes
        self._memtable_limit = max(1 << 12, memtable_bytes)
        self._max_runs_per_tier = max(2, max_runs_per_tier)
        self._bits_per_key = bloom_bits_per_key
        self._lock = threading.RLock()
        self._compact_mutex = threading.Lock()
        self._memtable: Dict[bytes, Optional[bytes]] = {}
        self._memtable_size = 0
        self._runs: List[SortedRun] = []  # newest first
        self._next_run_seq = 0
        self._inode_count = 0
        self._closed = False
        # counters surfaced through stats() -> Master.Metastore* gauges
        self._flushes = 0
        self._compactions = 0
        self._compaction_bytes = 0
        self._wal = WriteAheadLog(os.path.join(directory, _WAL),
                                  sync=wal_sync)
        #: WAL records replayed at open — the recovery point, asserted by
        #: the kill-and-recover property test
        self.recovered_wal_records = 0
        self._recover()
        self._stop = threading.Event()
        self._compactor: Optional[threading.Thread] = None
        if compaction:
            self._compactor = threading.Thread(
                target=self._compaction_loop, args=(compaction_poll_s,),
                name="lsm-compaction", daemon=True)
            self._compactor.start()

    # ---------------------------------------------------------- recovery
    def _manifest_path(self) -> str:
        return os.path.join(self._dir, _MANIFEST)

    def _write_manifest_locked(self) -> None:
        tmp = self._manifest_path() + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(
                [os.path.basename(r.path) for r in self._runs],
                use_bin_type=True))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path())

    @staticmethod
    def _run_seq(name: str) -> int:
        return int(name.split("-")[1].split(".")[0])

    def _recover(self) -> None:
        try:
            with open(self._manifest_path(), "rb") as f:
                names = msgpack.unpackb(f.read(), raw=False)
        except FileNotFoundError:
            names = []
        for name in names:
            path = os.path.join(self._dir, name)
            if os.path.exists(path):
                self._runs.append(SortedRun(path))
            self._next_run_seq = max(self._next_run_seq,
                                     self._run_seq(name) + 1)
        for key, value in self._wal.replay():
            self._memtable[key] = value
            self._memtable_size += len(key) + len(value or b"") + 16
            self.recovered_wal_records += 1
        if self._runs or self._memtable:
            self._inode_count = sum(
                1 for _ in self._iter_merged(enc.INODE_PREFIX,
                                             _INODE_SCAN_END))

    # ------------------------------------------------------- write path
    def _write_locked(self, key: bytes, value: Optional[bytes]) -> None:
        self._wal.append(key, value)
        self._memtable[key] = value
        self._memtable_size += len(key) + len(value or b"") + 16
        if self._memtable_size >= self._memtable_limit:
            self._flush_memtable_locked()

    def _flush_memtable_locked(self) -> None:
        if not self._memtable:
            return
        path = os.path.join(self._dir,
                            f"run-{self._next_run_seq:012d}.sst")
        self._next_run_seq += 1
        write_run(path, sorted(self._memtable.items()),
                  bits_per_key=self._bits_per_key)
        self._runs.insert(0, SortedRun(path))
        self._write_manifest_locked()
        self._memtable = {}
        self._memtable_size = 0
        self._wal.truncate()
        self._flushes += 1

    # -------------------------------------------------------- read path
    def _read(self, key: bytes):
        """Newest-wins point lookup: value bytes, or ``None`` (tombstone
        and absent collapse — callers never need the distinction)."""
        with self._lock:
            if key in self._memtable:
                return self._memtable[key]
            for run in self._runs:
                v = run.get(key)
                if v is not MISSING:
                    return v
            return None

    def _release_runs_locked(self, runs: List[SortedRun]) -> None:
        for r in runs:
            r.refs -= 1
            if r.retired and r.refs == 0:
                r.close()
                try:
                    os.unlink(r.path)
                except OSError:
                    pass

    def _iter_merged(self, start_key: bytes, end_key: bytes,
                     start_inclusive: bool = True) \
            -> Iterator[Tuple[bytes, bytes]]:
        """K-way merge of memtable + all runs over ``[start_key,
        end_key)``; newest source wins per key; tombstones skipped.

        Sources are snapshotted up front, so the scan is consistent
        against concurrent writers (their newer values land in a
        memtable this scan no longer reads) and refcounted against
        concurrent compactions."""
        with self._lock:
            mem = sorted((k, v) for k, v in self._memtable.items()
                         if start_key <= k < end_key)
            runs = list(self._runs)
            for r in runs:
                r.refs += 1
        try:
            def _bounded(it):
                for k, v in it:
                    if k >= end_key:
                        return
                    yield k, v

            sources = [iter(mem)] + [_bounded(r.iter_from(start_key))
                                     for r in runs]
            # heap entries (key, source_priority, value, iter); priority
            # 0 is the memtable (newest) — first pop for a key wins
            heap = []
            for prio, it in enumerate(sources):
                for k, v in it:
                    heap.append((k, prio, v, it))
                    break
            heapq.heapify(heap)
            last_key = None
            while heap:
                k, prio, v, it = heapq.heappop(heap)
                for nk, nv in it:
                    heapq.heappush(heap, (nk, prio, nv, it))
                    break
                if k == last_key:
                    continue
                last_key = k
                if v is None:  # tombstone shadows older runs
                    continue
                if not start_inclusive and k == start_key:
                    continue
                yield k, v
        finally:
            with self._lock:
                self._release_runs_locked(runs)

    # ------------------------------------------------------- compaction
    def _pick_compaction_locked(self) -> Optional[Tuple[int, int]]:
        """Longest adjacent same-size-tier group of >= max_runs_per_tier
        runs, as ``(start, stop)`` indices into ``self._runs``.  Only
        ADJACENT (recency-contiguous) runs may merge, or newest-wins
        ordering breaks."""
        n = len(self._runs)
        if n < self._max_runs_per_tier:
            return None

        def tier(run: SortedRun) -> int:
            size, t = max(run.file_size, 1), 0
            while size > (1 << 20):
                size >>= 2
                t += 1
            return t

        tiers = [tier(r) for r in self._runs]
        best = None
        i = 0
        while i < n:
            j = i
            while j < n and tiers[j] == tiers[i]:
                j += 1
            if j - i >= self._max_runs_per_tier and \
                    (best is None or j - i > best[1] - best[0]):
                best = (i, j)
            i = j
        if best is None and n >= 3 * self._max_runs_per_tier:
            # tier spread stalled compaction while runs pile up: fold
            # the oldest group regardless of tier to bound read fan-out
            best = (n - self._max_runs_per_tier, n)
        return best

    @staticmethod
    def _merge_runs(inputs: List[SortedRun], drop_tombstones: bool) \
            -> Iterator[Tuple[bytes, Optional[bytes]]]:
        heap = []
        for prio, it in enumerate(r.iter_from() for r in inputs):
            for k, v in it:
                heap.append((k, prio, v, it))
                break
        heapq.heapify(heap)
        last_key = None
        while heap:
            k, prio, v, it = heapq.heappop(heap)
            for nk, nv in it:
                heapq.heappush(heap, (nk, prio, nv, it))
                break
            if k == last_key:
                continue
            last_key = k
            if v is None and drop_tombstones:
                continue
            yield k, v

    def _maybe_compact(self) -> bool:
        with self._compact_mutex:
            with self._lock:
                pick = self._pick_compaction_locked()
                if pick is None:
                    return False
                start, stop = pick
                inputs = self._runs[start:stop]
                # flushes only ever insert at index 0, so this group
                # stays contiguous (and its oldest-ness stable) while
                # the merge streams outside the lock
                drop_tombstones = inputs[-1] is self._runs[-1]
                for r in inputs:
                    r.refs += 1
                out = os.path.join(
                    self._dir, f"run-{self._next_run_seq:012d}.sst")
                self._next_run_seq += 1
            write_run(out, self._merge_runs(inputs, drop_tombstones),
                      bits_per_key=self._bits_per_key)
            new_run = SortedRun(out)
            with self._lock:
                i = self._runs.index(inputs[0])
                self._runs[i:i + len(inputs)] = [new_run]
                self._write_manifest_locked()
                self._compactions += 1
                self._compaction_bytes += sum(r.file_size for r in inputs)
                for r in inputs:
                    r.retired = True
                self._release_runs_locked(inputs)
            return True

    def _compaction_loop(self, poll_s: float) -> None:
        while not self._stop.wait(poll_s):
            try:
                while self._maybe_compact():
                    pass
            except Exception:  # noqa: BLE001 — keep the store serving
                import logging
                logging.getLogger(__name__).exception(
                    "lsm compaction failed; will retry")

    def compact_now(self) -> None:
        """Run pending compactions synchronously (tests / fsadmin)."""
        while self._maybe_compact():
            pass

    # ----------------------------------------------- InodeStore: inodes
    def get(self, inode_id: int) -> Optional[Inode]:
        blob = self._read(enc.inode_key(inode_id))
        if blob is None:
            return None
        return Inode.from_wire_dict(msgpack.unpackb(blob, raw=False))

    def put(self, inode: Inode) -> None:
        key = enc.inode_key(inode.id)
        blob = msgpack.packb(inode.to_wire_dict(), use_bin_type=True)
        with self._lock:
            if self._read(key) is None:
                self._inode_count += 1
            self._write_locked(key, blob)

    def remove(self, inode_id: int) -> None:
        key = enc.inode_key(inode_id)
        with self._lock:
            if self._read(key) is not None:
                self._inode_count -= 1
                self._write_locked(key, None)

    # ------------------------------------------------ InodeStore: edges
    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        with self._lock:
            self._write_locked(enc.edge_key(parent_id, name),
                               enc.edge_value(child_id))

    def remove_child(self, parent_id: int, name: str) -> None:
        key = enc.edge_key(parent_id, name)
        with self._lock:
            if self._read(key) is not None:
                self._write_locked(key, None)

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        blob = self._read(enc.edge_key(parent_id, name))
        return None if blob is None else enc.decode_edge_value(blob)

    def child_names(self, parent_id: int) -> List[str]:
        return [name for name, _ in self.iter_edges(parent_id)]

    def child_count(self, parent_id: int) -> int:
        return sum(1 for _ in self.iter_edges(parent_id))

    def iter_edges(self, parent_id: int,
                   start_after: Optional[str] = None) \
            -> Iterator[Tuple[str, int]]:
        prefix = enc.edge_prefix(parent_id)
        start = prefix if start_after is None \
            else enc.edge_key(parent_id, start_after)
        for key, value in self._iter_merged(
                start, prefix + b"\xff",
                start_inclusive=start_after is None):
            yield key[9:].decode("utf-8"), enc.decode_edge_value(value)

    def has_children(self, parent_id: int) -> bool:
        return next(self.iter_edges(parent_id), None) is not None

    def iter_inodes(self) -> Iterator[Inode]:
        for _key, blob in self._iter_merged(enc.INODE_PREFIX,
                                            _INODE_SCAN_END):
            yield Inode.from_wire_dict(msgpack.unpackb(blob, raw=False))

    def all_ids(self) -> Iterator[int]:
        for key, _blob in self._iter_merged(enc.INODE_PREFIX,
                                            _INODE_SCAN_END):
            yield enc.decode_inode_key(key)

    # ------------------------------------------------------ maintenance
    def flush(self) -> None:
        with self._lock:
            self._wal.flush()

    def seal(self) -> None:
        """Force the memtable into a sorted run (tests / checkpoint)."""
        with self._lock:
            self._flush_memtable_locked()

    def clear(self) -> None:
        with self._lock:
            for r in self._runs:
                r.retired = True
                r.refs += 1
            self._release_runs_locked(self._runs)
            self._runs = []
            try:
                os.unlink(self._manifest_path())
            except OSError:
                pass
            self._memtable = {}
            self._memtable_size = 0
            self._wal.truncate()
            self._inode_count = 0

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        if self._compactor is not None:
            self._compactor.join(timeout=5.0)
        with self._lock:
            # seal so the next open replays nothing (fast restart); the
            # WAL still covers a kill before this point
            self._flush_memtable_locked()
            self._wal.close()
            for r in self._runs:
                r.close()

    def estimated_size(self) -> int:
        with self._lock:
            return self._inode_count

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "kind": "LSM",
                "inodes": self._inode_count,
                "memtable_bytes": self._memtable_size,
                "memtable_entries": len(self._memtable),
                "runs": len(self._runs),
                "run_bytes": sum(r.file_size for r in self._runs),
                "wal_bytes": self._wal.size_bytes(),
                "flushes": self._flushes,
                "compactions": self._compactions,
                "compaction_bytes": self._compaction_bytes,
            }

    # ----------------------------------------------- native checkpoints
    def checkpoint_state(self) -> dict:
        """Seal the memtable, then capture the run set: the checkpoint
        IS the on-disk LSM at WAL position zero."""
        with self._lock:
            self._flush_memtable_locked()
            runs = []
            for r in self._runs:
                with open(r.path, "rb") as f:
                    runs.append({"name": os.path.basename(r.path),
                                 "data": f.read()})
        return {"format": "lsm-runs", "runs": runs}

    def restore_state(self, state: dict) -> None:
        if state.get("format") != "lsm-runs":
            raise ValueError(
                f"unknown LSM checkpoint format {state.get('format')!r}")
        with self._lock:
            self.clear()
            for entry in state.get("runs", []):
                path = os.path.join(self._dir, entry["name"])
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(entry["data"])
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                self._runs.append(SortedRun(path))
                self._next_run_seq = max(
                    self._next_run_seq, self._run_seq(entry["name"]) + 1)
            self._write_manifest_locked()
            self._inode_count = sum(
                1 for _ in self._iter_merged(enc.INODE_PREFIX,
                                             _INODE_SCAN_END))
