"""On-heap dict-backed inode store (reference:
``heap/HeapInodeStore.java:46``) — fastest, bounded by RAM."""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple

from alluxio_tpu.master.inode import Inode
from alluxio_tpu.master.metastore.base import InodeStore


class HeapInodeStore(InodeStore):
    def __init__(self) -> None:
        self._inodes: Dict[int, Inode] = {}
        self._edges: Dict[Tuple[int, str], int] = {}
        self._children: Dict[int, Dict[str, int]] = {}
        self._lock = threading.RLock()

    def get(self, inode_id: int) -> Optional[Inode]:
        with self._lock:
            return self._inodes.get(inode_id)

    def put(self, inode: Inode) -> None:
        with self._lock:
            self._inodes[inode.id] = inode

    def remove(self, inode_id: int) -> None:
        with self._lock:
            self._inodes.pop(inode_id, None)

    def add_child(self, parent_id: int, name: str, child_id: int) -> None:
        with self._lock:
            self._edges[(parent_id, name)] = child_id
            self._children.setdefault(parent_id, {})[name] = child_id

    def remove_child(self, parent_id: int, name: str) -> None:
        with self._lock:
            self._edges.pop((parent_id, name), None)
            kids = self._children.get(parent_id)
            if kids is not None:
                kids.pop(name, None)
                if not kids:
                    del self._children[parent_id]

    def get_child_id(self, parent_id: int, name: str) -> Optional[int]:
        with self._lock:
            return self._edges.get((parent_id, name))

    def child_names(self, parent_id: int) -> List[str]:
        with self._lock:
            return sorted(self._children.get(parent_id, {}).keys())

    def iter_edges(self, parent_id: int,
                   start_after: Optional[str] = None) \
            -> Iterator[Tuple[str, int]]:
        with self._lock:
            kids = sorted(self._children.get(parent_id, {}).items())
        for name, child_id in kids:
            if start_after is not None and name <= start_after:
                continue
            yield name, child_id

    def has_children(self, parent_id: int) -> bool:
        with self._lock:
            return bool(self._children.get(parent_id))

    def child_count(self, parent_id: int) -> int:
        with self._lock:
            return len(self._children.get(parent_id, {}))

    def all_ids(self) -> Iterator[int]:
        with self._lock:
            return iter(list(self._inodes.keys()))

    def clear(self) -> None:
        with self._lock:
            self._inodes.clear()
            self._edges.clear()
            self._children.clear()

    def estimated_size(self) -> int:
        with self._lock:
            return len(self._inodes)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {"kind": "HEAP", "inodes": len(self._inodes),
                    "edges": len(self._edges)}
