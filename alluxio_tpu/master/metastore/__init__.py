"""Pluggable inode/block metadata stores.

Re-design of ``core/server/master/.../metastore/``: the reference offers
HEAP (on-heap maps, ``heap/HeapInodeStore.java:46``), ROCKS (off-heap
JNI, ``rocks/RocksInodeStore.java:60``) and rocks+write-back-cache
(``caching/CachingInodeStore.java:91``). Here:

- **HeapInodeStore** — dicts; fastest, bounded by RAM.
- **SqliteInodeStore** — stdlib ``sqlite3`` as a spill-to-disk store,
  WAL mode.
- **LsmInodeStore** — the capacity backend in the RocksDB role: WAL +
  memtable + bloom-filtered sorted runs + size-tiered compaction
  (``lsm.py``); RAM holds only the hot set and per-run filters, the
  namespace lives under ``atpu.master.metastore.dir``.
- **CachingInodeStore** — LRU write-back cache in front of any backing
  store, flushing evicted dirty entries.

Edges (parent_id, child_name) -> child_id are first-class, as in the
reference's ``InodeStore#getChild``; every store serves them in name
order through the ``iter_edges`` iterator contract (``base.py``).

``create_inode_store`` is keyed by ``atpu.master.metastore``: ``HEAP``,
``SQLITE``, ``LSM`` (caching-wrapped by default — the hot set is part of
the design), bare ``CACHING`` (over SQLITE, the historical meaning), or
an explicit composition ``CACHING:SQLITE`` / ``CACHING:LSM`` /
``CACHING:HEAP``.
"""

from __future__ import annotations

from typing import Optional

from alluxio_tpu.master.metastore.base import InodeStore
from alluxio_tpu.master.metastore.caching import CachingInodeStore
from alluxio_tpu.master.metastore.heap import HeapInodeStore
from alluxio_tpu.master.metastore.lsm import LsmInodeStore
from alluxio_tpu.master.metastore.sqlite import SqliteInodeStore
from alluxio_tpu.utils.exceptions import InvalidArgumentError

__all__ = [
    "InodeStore",
    "HeapInodeStore",
    "SqliteInodeStore",
    "LsmInodeStore",
    "CachingInodeStore",
    "create_inode_store",
]


def _create_base(kind: str, directory: str,
                 lsm_options: Optional[dict]) -> InodeStore:
    if kind == "HEAP":
        return HeapInodeStore()
    if kind == "SQLITE":
        return SqliteInodeStore(directory)
    if kind == "LSM":
        return LsmInodeStore(directory, **(lsm_options or {}))
    raise InvalidArgumentError(
        f"unknown metastore kind {kind!r} "
        "(expected HEAP, SQLITE, LSM, CACHING or CACHING:<backing>)")


def create_inode_store(kind: str, directory: str,
                       cache_size: int = 100_000,
                       lsm_options: Optional[dict] = None) -> InodeStore:
    """Factory keyed by ``atpu.master.metastore``.  Unknown kinds raise
    :class:`InvalidArgumentError` (a typed error the conf layer and RPC
    surfaces already translate), not a bare ``ValueError``."""
    k = (kind or "").strip().upper()
    base, _, backing = k.partition(":")
    if base == "CACHING":
        # bare CACHING keeps its historical meaning: LRU over SQLITE
        return CachingInodeStore(
            _create_base(backing or "SQLITE", directory, lsm_options),
            cache_size)
    if base == "LSM":
        # the hot set is part of the LSM design: point lookups that
        # matter (the training job's working set) stay heap-speed
        return CachingInodeStore(
            _create_base("LSM", directory, lsm_options), cache_size)
    return _create_base(base, directory, lsm_options)
