"""Immutable sorted-run files for the LSM metastore.

A run is a sealed memtable (or a compaction of older runs): records in
key order, followed by a sparse index (one pointer every
``INDEX_INTERVAL`` records), a bloom filter over every key, and a
msgpack footer.  Readers hold the index + bloom in memory — for a
billion-inode namespace that's the only per-run RAM cost — and serve

- point lookups: bloom check, binary-search the sparse index, then ONE
  ``os.pread`` of the interval (no shared file position, so concurrent
  readers never contend), and
- range scans: seek via the index, then stream in 1MB chunks — the
  ``children()`` range scan and compaction input path.

The writer is fully streaming (compaction merges can be far larger than
RAM): records are written as they arrive and the bloom filter — which
needs the exact key count to size itself — is built in a second,
sequential pass over the just-written file.

Tombstones (deleted keys) are vlen ``0xFFFFFFFF`` records; they must
survive until a compaction that includes the OLDEST run, else a deleted
key would resurrect from below.

Layout::

    "ATPUSST1" | records... | footer(msgpack) | u32 footer_len | "ATPUSST1"
    record = u32 klen | u32 vlen(-1 = tombstone) | key | value
"""

from __future__ import annotations

import bisect
import os
import struct
import zlib
from typing import Iterable, Iterator, Optional, Tuple

import msgpack

MAGIC = b"ATPUSST1"
INDEX_INTERVAL = 16
_REC = struct.Struct(">II")
_U32 = struct.Struct(">I")
_TOMBSTONE_LEN = 0xFFFFFFFF
_SCAN_CHUNK = 1 << 20
#: sentinel distinguishing "key absent from this run" from "key present
#: as a tombstone" (which must SHADOW older runs, not fall through)
MISSING = object()


class BloomFilter:
    """Double-hashed bloom over raw byte keys.  crc32 with two fixed
    seeds gives the pair of independent hashes (stable across processes,
    unlike ``hash(bytes)`` under PYTHONHASHSEED)."""

    def __init__(self, bits: int, k: int,
                 data: Optional[bytearray] = None) -> None:
        self.bits = max(8, bits)
        self.k = max(1, k)
        self.data = data if data is not None else \
            bytearray((self.bits + 7) // 8)

    @classmethod
    def sized_for(cls, count: int, bits_per_key: int) -> "BloomFilter":
        # k = ln(2) * bits_per_key minimizes the false-positive rate
        return cls(max(1, count) * bits_per_key,
                   max(1, int(0.69 * bits_per_key)))

    def _probes(self, key: bytes) -> Iterator[int]:
        h1 = zlib.crc32(key)
        h2 = zlib.crc32(key, 0x9E3779B9) | 1
        for i in range(self.k):
            yield (h1 + i * h2) % self.bits

    def add(self, key: bytes) -> None:
        for bit in self._probes(key):
            self.data[bit >> 3] |= 1 << (bit & 7)

    def __contains__(self, key: bytes) -> bool:
        return all(self.data[b >> 3] & (1 << (b & 7))
                   for b in self._probes(key))


def _parse_records(chunks: Iterable[bytes]) \
        -> Iterator[Tuple[bytes, Optional[bytes]]]:
    """Incrementally parse the record stream out of byte chunks."""
    buf = bytearray()
    pos = 0
    for chunk in chunks:
        buf += chunk
        while True:
            if len(buf) - pos < _REC.size:
                break
            klen, vlen = _REC.unpack_from(buf, pos)
            body = klen if vlen == _TOMBSTONE_LEN else klen + vlen
            if len(buf) - pos < _REC.size + body:
                break
            p = pos + _REC.size
            key = bytes(buf[p:p + klen])
            value = None if vlen == _TOMBSTONE_LEN \
                else bytes(buf[p + klen:p + klen + vlen])
            pos += _REC.size + body
            yield key, value
        if pos:
            del buf[:pos]
            pos = 0


def write_run(path: str,
              entries: Iterable[Tuple[bytes, Optional[bytes]]],
              *, bits_per_key: int = 10) -> None:
    """Seal ``entries`` (already key-sorted, values ``None`` for
    tombstones) into a run file.  ``entries`` may be a generator —
    compaction merges stream through here without materializing.
    Atomic: written to ``path + '.tmp'`` and renamed, so a crash
    mid-seal leaves no half-run behind."""
    tmp = path + ".tmp"
    index: list = []
    count = 0
    with open(tmp, "w+b") as f:
        f.write(MAGIC)
        off = len(MAGIC)
        for key, value in entries:
            if count % INDEX_INTERVAL == 0:
                index.append([key, off])
            if value is None:
                f.write(_REC.pack(len(key), _TOMBSTONE_LEN))
                f.write(key)
                off += _REC.size + len(key)
            else:
                f.write(_REC.pack(len(key), len(value)))
                f.write(key)
                f.write(value)
                off += _REC.size + len(key) + len(value)
            count += 1
        f.flush()
        # second pass: the bloom needs the exact key count to size
        # itself, and the keys just went to disk — reread sequentially
        bloom = BloomFilter.sized_for(count, bits_per_key)
        f.seek(len(MAGIC))

        def _chunks(remaining: int) -> Iterator[bytes]:
            while remaining > 0:
                chunk = f.read(min(_SCAN_CHUNK, remaining))
                if not chunk:
                    return
                remaining -= len(chunk)
                yield chunk

        for key, _value in _parse_records(_chunks(off - len(MAGIC))):
            bloom.add(key)
        f.seek(0, os.SEEK_END)
        footer = msgpack.packb({
            "count": count,
            "data_end": off,
            "index": index,
            "bloom": bytes(bloom.data),
            "bloom_bits": bloom.bits,
            "bloom_k": bloom.k,
        }, use_bin_type=True)
        f.write(footer)
        f.write(_U32.pack(len(footer)))
        f.write(MAGIC)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class SortedRun:
    """Open (immutable) run.  Holds a raw fd and reads with ``os.pread``
    — safe to share across threads, and safe to keep using after the
    path is unlinked by a compaction swap (POSIX keeps the inode alive
    while an fd is open)."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._fd = os.open(path, os.O_RDONLY)
        self.file_size = os.fstat(self._fd).st_size
        #: live-scan refcount + retirement flag, managed by LsmInodeStore
        #: under its lock (a compacted-away run is closed only when the
        #: last in-flight scan over it finishes)
        self.refs = 0
        self.retired = False
        tail = os.pread(self._fd, _U32.size + len(MAGIC),
                        self.file_size - _U32.size - len(MAGIC))
        if tail[_U32.size:] != MAGIC:
            raise IOError(f"corrupt run file {path!r}: bad trailer magic")
        footer_len = _U32.unpack(tail[:_U32.size])[0]
        footer_off = self.file_size - _U32.size - len(MAGIC) - footer_len
        footer = msgpack.unpackb(
            os.pread(self._fd, footer_len, footer_off), raw=False)
        self.count: int = footer["count"]
        self._data_end: int = footer["data_end"]
        self._index_keys = [k for k, _ in footer["index"]]
        self._index_offs = [o for _, o in footer["index"]]
        self._bloom = BloomFilter(footer["bloom_bits"], footer["bloom_k"],
                                  bytearray(footer["bloom"]))

    # ------------------------------------------------------------ reads
    def get(self, key: bytes):
        """Value bytes, ``None`` for a tombstone, or ``MISSING`` — via
        one pread of the containing index interval."""
        if self.count == 0 or key not in self._bloom:
            return MISSING
        i = bisect.bisect_right(self._index_keys, key) - 1
        if i < 0:
            return MISSING
        start = self._index_offs[i]
        stop = self._index_offs[i + 1] if i + 1 < len(self._index_offs) \
            else self._data_end
        blob = os.pread(self._fd, stop - start, start)
        for k, v in _parse_records((blob,)):
            if k == key:
                return v
            if k > key:
                return MISSING
        return MISSING

    def iter_from(self, start_key: bytes = b"") \
            -> Iterator[Tuple[bytes, Optional[bytes]]]:
        """Stream ``(key, value|None)`` — tombstones INCLUDED (the merge
        layer needs them to shadow older runs) — from the first key
        >= ``start_key``, in 1MB chunked preads."""
        if start_key:
            i = bisect.bisect_right(self._index_keys, start_key) - 1
            off = self._index_offs[i] if i >= 0 else len(MAGIC)
        else:
            off = len(MAGIC)

        def _chunks() -> Iterator[bytes]:
            pos = off
            while pos < self._data_end:
                n = min(_SCAN_CHUNK, self._data_end - pos)
                chunk = os.pread(self._fd, n, pos)
                if not chunk:
                    return
                pos += len(chunk)
                yield chunk

        for k, v in _parse_records(_chunks()):
            if k >= start_key:
                yield k, v

    def close(self) -> None:
        try:
            os.close(self._fd)
        except OSError:
            pass
