"""Append-only write-ahead log for the LSM metastore.

Every mutation is framed ``[u32 len][u32 crc32][msgpack (key, value)]``
and appended before it touches the memtable; replay on open rebuilds
exactly the un-flushed tail of the store.  A torn or corrupt tail record
(the kill-mid-write case) fails its CRC and replay stops there — the log
always recovers to a clean PREFIX of the appended operations, never to a
mix (property-tested in ``tests/test_metastore_lsm.py``).

``sync=False`` (the default wired from ``atpu.master.metastore.lsm.
wal.sync``) buffers through the OS: in the full master the JOURNAL is
the durability root and rebuilds the metastore from its own fsynced log,
so paying a second fsync per metadata op here would double the write
cost for nothing.  Standalone embedders that want the store itself to be
crash-durable turn it on.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, Optional, Tuple

import msgpack

_HDR = struct.Struct(">II")


class WriteAheadLog:
    def __init__(self, path: str, *, sync: bool = False) -> None:
        self._path = path
        self._sync = sync
        self._f = open(path, "ab")

    @property
    def path(self) -> str:
        return self._path

    def append(self, key: bytes, value: Optional[bytes]) -> None:
        payload = msgpack.packb((key, value), use_bin_type=True)
        self._f.write(_HDR.pack(len(payload), zlib.crc32(payload)))
        self._f.write(payload)
        self._f.flush()
        if self._sync:
            os.fsync(self._f.fileno())

    def replay(self) -> Iterator[Tuple[bytes, Optional[bytes]]]:
        """Yield every intact record in append order; stop (silently) at
        the first torn/corrupt frame."""
        try:
            f = open(self._path, "rb")
        except FileNotFoundError:
            return
        with f:
            while True:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return
                length, crc = _HDR.unpack(hdr)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    return
                key, value = msgpack.unpackb(payload, raw=False)
                yield key, value

    def truncate(self) -> None:
        """Drop every record — called after the memtable they rebuilt was
        sealed into a sorted run."""
        self._f.truncate(0)
        self._f.seek(0)
        self._f.flush()
        if self._sync:
            os.fsync(self._f.fileno())

    def size_bytes(self) -> int:
        try:
            return os.path.getsize(self._path)
        except OSError:
            return 0

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        self._f.close()
