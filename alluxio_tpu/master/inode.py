"""Inode records.

Re-design of ``core/server/master/.../file/meta/{MutableInodeFile,
MutableInodeDirectory}.java`` + ``InodeTreePersistentState``: plain mutable
dataclasses, fully msgpack-serializable so the same representation backs the
heap store, journal entries and checkpoints. TTL semantics mirror
``file/meta/TtlBucket``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from alluxio_tpu.utils import ids

NO_PARENT = -1
NO_TTL = -1


class PersistenceState:
    NOT_PERSISTED = "NOT_PERSISTED"
    TO_BE_PERSISTED = "TO_BE_PERSISTED"
    PERSISTED = "PERSISTED"
    LOST = "LOST"


class TtlAction:
    DELETE = "DELETE"
    FREE = "FREE"


@dataclass
class Inode:
    id: int = 0
    parent_id: int = NO_PARENT
    name: str = ""
    is_directory: bool = False
    creation_time_ms: int = 0
    last_modification_time_ms: int = 0
    last_access_time_ms: int = 0
    owner: str = ""
    group: str = ""
    mode: int = 0o755
    pinned: bool = False
    pinned_media: List[str] = field(default_factory=list)
    ttl: int = NO_TTL
    ttl_action: str = TtlAction.DELETE
    persistence_state: str = PersistenceState.NOT_PERSISTED
    #: an ASYNC_THROUGH persist was pending when the file went LOST;
    #: recovery must restore TO_BE_PERSISTED, not drop the durability
    #: request (journaled via SET_ATTRIBUTE so it replays)
    lost_pending_persist: bool = False
    ufs_fingerprint: str = ""
    xattr: Dict[str, str] = field(default_factory=dict)

    # file-only
    block_size_bytes: int = 0
    length: int = 0
    completed: bool = False
    cacheable: bool = True
    block_ids: List[int] = field(default_factory=list)
    replication_min: int = 0
    replication_max: int = -1
    temp_ufs_path: str = ""

    # directory-only
    mount_point: bool = False
    direct_children_loaded: bool = False

    @staticmethod
    def new_directory(inode_id: int, parent_id: int, name: str, *,
                      owner: str = "", group: str = "", mode: int = 0o755,
                      now_ms: Optional[int] = None) -> "Inode":
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        return Inode(id=inode_id, parent_id=parent_id, name=name,
                     is_directory=True, creation_time_ms=now,
                     last_modification_time_ms=now, last_access_time_ms=now,
                     owner=owner, group=group, mode=mode)

    @staticmethod
    def new_file(container_id: int, parent_id: int, name: str, *,
                 block_size_bytes: int, owner: str = "", group: str = "",
                 mode: int = 0o644, ttl: int = NO_TTL,
                 ttl_action: str = TtlAction.DELETE,
                 replication_min: int = 0, replication_max: int = -1,
                 now_ms: Optional[int] = None) -> "Inode":
        now = now_ms if now_ms is not None else int(time.time() * 1000)
        return Inode(id=ids.file_id_from_container(container_id),
                     parent_id=parent_id, name=name, is_directory=False,
                     creation_time_ms=now, last_modification_time_ms=now,
                     last_access_time_ms=now, owner=owner, group=group,
                     mode=mode, block_size_bytes=block_size_bytes, ttl=ttl,
                     ttl_action=ttl_action, replication_min=replication_min,
                     replication_max=replication_max)

    @property
    def container_id(self) -> int:
        return ids.container_id(self.id)

    def next_block_id(self) -> int:
        """Id for the next sequential block of this file."""
        return ids.block_id(self.container_id, len(self.block_ids))

    def to_wire_dict(self) -> Dict[str, Any]:
        # hand-rolled shallow copy: dataclasses.asdict deep-recurses
        # through every field (~29 helper calls per inode) and was the
        # third-largest CPU item in master create profiles; the only
        # mutable fields needing a copy are the three containers
        d = dict(self.__dict__)
        d["pinned_media"] = list(d["pinned_media"])
        d["xattr"] = dict(d["xattr"])
        d["block_ids"] = list(d["block_ids"])
        return d

    @staticmethod
    def from_wire_dict(d: Dict[str, Any]) -> "Inode":
        return Inode(**d)
