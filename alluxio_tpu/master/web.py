"""Read-only HTTP/JSON state endpoint for the master.

Re-design of ``core/server/master/src/main/java/alluxio/master/meta/
AlluxioMasterRestServiceHandler.java`` (the web UI's backing REST API)
as a stdlib HTTP server: everything ``fsadmin report`` prints, curl-able.

Routes:
  GET /api/v1/master/info      cluster id, uptime, safe mode, version
  GET /api/v1/master/capacity  per-tier capacity/used + worker list
  GET /api/v1/master/metrics   flat metrics snapshot (JSON)
  GET /api/v1/master/mounts    mount table
  GET /api/v1/master/catalog   table-service databases/tables
  GET /api/v1/master/browse    ?path= namespace listing w/ tier residency
  GET /api/v1/master/config    effective configuration + value sources
  GET /api/v1/master/logs      ?n=&level= recent log records (in-process
                               ring; the logserver holds the full stream)
  GET /metrics                 Prometheus text exposition
  GET /browse /config /logs    HTML pages over the routes above
                               (reference: webui/master's browse/config/
                               logs SPA pages)
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from alluxio_tpu.conf import Keys

LOG = logging.getLogger(__name__)


def _dashboard_html() -> bytes:
    """Status page over the JSON routes (stand-in for the reference's
    webui-master SPA, ``webui/master/``; shared chrome lives in
    ``utils/statuspage.py``)."""
    from alluxio_tpu.utils.statuspage import render

    return render(
        "alluxio-tpu master", "/api/v1/master",
        sections=[("Cluster", "info"), ("Masters", "masters"),
                  ("Workers", "workers"),
                  ("Metastore", "metastore"),
                  ("Mounts", "mounts"), ("Catalog", "catalog"),
                  ("Cluster health", "health"),
                  ("Self-healing", "remediation"),
                  ("Input doctor", "stall")],
        raw_routes=["/api/v1/master/info", "/masters", "/capacity",
                    "/metrics",
                    "/metrics/history", "/health", "/remediation",
                    "/metastore",
                    "/mounts", "/catalog", "/trace", "/browse",
                    "/config", "/logs"],
        js_body="""
    const info = await j('/info');
    const t = document.getElementById('info');
    for (const k of ['cluster_id','rpc_port','safe_mode','live_workers',
                     'uptime_ms'])
      row(t, [k, String(info[k])]);
    // HA quorum view: role/term/applied-seq per master (docs/ha.md)
    const ms = await j('/masters');
    const mst = document.getElementById('masters');
    row(mst, ['address','role','term','applied seq','lag','contact'], true);
    for (const x of ms.masters)
      row(mst, [x.address + (x.address === ms.leader ? ' *' : ''),
                x.role || '?', String(x.term ?? '-'),
                String(x.sequence ?? '-'),
                x.lag_entries != null ? String(x.lag_entries) : '-',
                x.last_contact_s != null
                  ? x.last_contact_s.toFixed(1) + 's' : '-']);
    const cap = await j('/capacity');
    const w = document.getElementById('workers');
    row(w, ['host','state','capacity','used'], true);
    for (const x of cap.workers)
      row(w, [x.host, x.state,
              gb(Object.values(x.capacity).reduce((a,b)=>a+b,0)),
              gb(Object.values(x.used).reduce((a,b)=>a+b,0))]);
    // inode metastore: backend kind, population, LSM write/read debt
    const meta = (await j('/metastore')).stats;
    const met2 = document.getElementById('metastore');
    row(met2, ['kind', String(meta.kind ?? '?')]);
    row(met2, ['inodes', String(meta.inodes ?? 0)]);
    if (meta.cache_hit_ratio != null)
      row(met2, ['cache hit ratio',
                 (100 * meta.cache_hit_ratio).toFixed(1) + '% (' +
                 (meta.cache_entries ?? 0) + ' entries)']);
    if (meta.memtable_bytes != null) {
      row(met2, ['memtable', gb(meta.memtable_bytes) + ' (' +
                 (meta.memtable_entries ?? 0) + ' entries)']);
      row(met2, ['sorted runs', (meta.runs ?? 0) + ' (' +
                 gb(meta.run_bytes ?? 0) + ')']);
      row(met2, ['flushes / compactions', (meta.flushes ?? 0) + ' / ' +
                 (meta.compactions ?? 0) + ' (' +
                 gb(meta.compaction_bytes ?? 0) + ' rewritten)']);
    }
    const m = await j('/mounts');
    const mt = document.getElementById('mounts');
    row(mt, ['path','ufs','read-only'], true);
    for (const x of m.mounts) row(mt, [x.path, x.ufs, x.read_only]);
    const c = await j('/catalog');
    const ct = document.getElementById('catalog');
    row(ct, ['database','tables'], true);
    for (const [db, tables] of Object.entries(c.databases))
      row(ct, [db, tables.join(', ')]);
    // cluster doctor: ranked verdicts from the health-rule engine
    const h = await j('/health');
    const ht = document.getElementById('health');
    row(ht, ['status: ' + h.status, '', '', ''], true);
    row(ht, ['severity', 'rule', 'subject', 'verdict'], true);
    for (const a of h.alerts)
      row(ht, [a.severity, a.rule, a.subject,
               a.summary + ' — ' + a.remediation]);
    if (!h.alerts.length)
      row(ht, ['(no alerts firing — ' + h.rules.length +
               ' rules watching)', '', '', '']);
    // self-healing: the remediation engine's audited timeline
    const rem = await j('/remediation');
    const rt = document.getElementById('remediation');
    if (!rem.enabled) {
      row(rt, ['(remediation disabled — ' +
               'atpu.master.remediation.enabled)', '', '', '']);
    } else {
      row(rt, ['mode: ' + (rem.dry_run ? 'DRY-RUN' : 'active') +
               ', ' + rem.actions_in_window + '/' +
               rem.max_actions_per_window + ' actions in window, ' +
               rem.quarantined.length + ' quarantined',
               '', '', ''], true);
      row(rt, ['when', 'cause', 'action', 'outcome'], true);
      for (const a of rem.audit.slice(-15).reverse())
        row(rt, [new Date(1e3 * a.at).toISOString().slice(11, 19),
                 a.rule + ' on ' + a.subject, a.action,
                 a.outcome + (a.reverted_at ? ' (reverted)' : '')]);
      if (!rem.audit.length)
        row(rt, ['(no actions taken yet)', '', '', '']);
    }
    // input doctor: rank loader input waits by serving tier
    // (Cluster.* roll-up when clients report, else this process's own)
    const met = (await j('/metrics')).metrics;
    const st = document.getElementById('stall');
    row(st, ['tier','waits','stalled (s)','share'], true);
    const buckets = {};
    for (const [k, v] of Object.entries(met)) {
      const m2 = k.match(/^(?:Cluster|Client)\\.InputStall(Us|Count)\\.(\\w+)$/);
      if (!m2) continue;
      const b = buckets[m2[2]] = buckets[m2[2]] || {us: 0, count: 0};
      if (m2[1] === 'Us') b.us = Math.max(b.us, v);
      else b.count = Math.max(b.count, v);
    }
    const totalUs = Object.values(buckets).reduce((a, b) => a + b.us, 0);
    const ranked = Object.entries(buckets).sort((a, b) => b[1].us - a[1].us);
    for (const [name, b] of ranked)
      row(st, [name, String(b.count), (b.us / 1e6).toFixed(3),
               totalUs ? (100 * b.us / totalUs).toFixed(1) + '%' : '-']);
    if (!ranked.length)
      row(st, ['(no input-stall samples recorded)', '', '', '']);
""")


def _page_html(page: str) -> bytes:
    """The browse/config/logs pages (reference: ``webui/master``'s
    Browse / Configuration / Logs SPA pages, as self-contained HTML
    over the JSON routes)."""
    from alluxio_tpu.utils.statuspage import render

    if page == "browse":
        return render(
            "alluxio-tpu browse", "/api/v1/master",
            sections=[("Namespace", "listing")],
            raw_routes=["/api/v1/master/browse?path=/"],
            js_body="""
    const params = new URLSearchParams(location.search);
    const path = params.get('path') || '/';
    const d = await j('/browse?path=' + encodeURIComponent(path));
    const t = document.getElementById('listing');
    const h = document.createElement('h3');
    // textContent only: ?path= is attacker-controlled (reflected XSS
    // via innerHTML otherwise)
    h.textContent = 'path: ' + path + (path === '/' ? '' : ' — ');
    if (path !== '/') {
      const parent = path.slice(0, path.lastIndexOf('/')) || '/';
      const up = document.createElement('a');
      up.href = '/browse?path=' + encodeURIComponent(parent);
      up.textContent = 'up';
      h.appendChild(up);
    }
    t.before(h);
    row(t, ['name','size','in-mem %','persistence','mode','owner',
            'blocks'], true);
    for (const e of d.entries) {
      const tr = row(t, ['', String(e.length), e.folder ? '-' :
                         String(e.in_memory_percentage),
                         e.persistence_state, e.mode, e.owner,
                         String(e.block_count)]);
      const cell = tr.cells[0];
      if (e.folder) {
        const a = document.createElement('a');
        a.href = '/browse?path=' + encodeURIComponent(e.path);
        a.textContent = e.name + '/';
        cell.appendChild(a);
      } else cell.textContent = e.name;
    }
""")
    if page == "config":
        return render(
            "alluxio-tpu configuration", "/api/v1/master",
            sections=[("Effective configuration", "conf")],
            raw_routes=["/api/v1/master/config"],
            js_body="""
    const d = await j('/config');
    const t = document.getElementById('conf');
    row(t, ['property','value','source'], true);
    for (const [k, v] of Object.entries(d.config))
      row(t, [k, v.value, v.source]);
""")
    return render(
        "alluxio-tpu logs", "/api/v1/master",
        sections=[("Recent log records", "logs")],
        raw_routes=["/api/v1/master/logs?n=200&level=WARNING"],
        js_body="""
    const params = new URLSearchParams(location.search);
    const d = await j('/logs?n=' + (params.get('n') || 200) +
                      '&level=' + (params.get('level') || ''));
    const t = document.getElementById('logs');
    row(t, ['time','level','logger','message'], true);
    for (const r of d.records.reverse())
      row(t, [new Date(r.ts_ms).toISOString(), r.level, r.logger,
              r.message]);
""")


class MasterWebServer:
    def __init__(self, master_process, port: int = 0,
                 bind_host: str = "0.0.0.0") -> None:
        self._mp = master_process
        mp = master_process

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet: route to logger
                LOG.debug("web: " + fmt, *args)

            def do_GET(self):  # noqa: N802 (stdlib API)
                try:
                    from urllib.parse import parse_qs, urlsplit

                    parts = urlsplit(self.path)
                    route = parts.path.rstrip("/")
                    self.query = {k: v[0] for k, v in
                                  parse_qs(parts.query).items()}
                    if route == "":
                        self._send(200, _dashboard_html(),
                                   "text/html; charset=utf-8")
                        return
                    if route in ("/browse", "/config", "/logs"):
                        self._send(200, _page_html(route[1:]),
                                   "text/html; charset=utf-8")
                        return
                    if route == "/metrics":
                        from alluxio_tpu.metrics import metrics

                        body = metrics().to_prometheus().encode()
                        self._send(200, body, "text/plain; version=0.0.4")
                        return
                    payload = self._route(route)
                    if payload is None:
                        self._send(404, json.dumps(
                            {"error": f"no route {route}"}).encode(),
                            "application/json")
                        return
                    self._send(200, json.dumps(
                        payload, sort_keys=True, default=str).encode(),
                        "application/json")
                except Exception as e:  # noqa: BLE001 - surface as 500
                    LOG.warning("web handler failed", exc_info=True)
                    self._send(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _route(self, route: str):
                if route == "/api/v1/master/info":
                    import time as _time

                    return {
                        "cluster_id": mp.cluster_id,
                        "cluster_name": mp._conf.get(Keys.CLUSTER_NAME),
                        "start_time_ms": mp.start_time_ms,
                        "uptime_ms": max(0, int(_time.time() * 1000)
                                         - mp.start_time_ms),
                        "safe_mode": mp.in_safe_mode(),
                        "rpc_port": mp.rpc_port,
                        "live_workers": len(
                            mp.block_master.get_worker_infos()),
                    }
                if route == "/api/v1/master/capacity":
                    workers = mp.block_master.get_worker_infos(
                        include_lost=True)
                    return {
                        "capacity": mp.block_master.capacity_bytes_on_tiers(),
                        "used": mp.block_master.used_bytes_on_tiers(),
                        "workers": [{
                            "id": w.id,
                            "host": w.address.host,
                            "state": w.state,
                            "capacity": dict(w.capacity_bytes_on_tiers),
                            "used": dict(w.used_bytes_on_tiers),
                        } for w in workers],
                    }
                if route == "/api/v1/master/metrics":
                    from alluxio_tpu.metrics import metrics

                    snap = metrics().snapshot()
                    mm = getattr(mp, "metrics_master", None)
                    if mm is not None:
                        snap = mm.merged_snapshot(snap)
                    return {"metrics": snap}
                if route == "/api/v1/master/metrics/history":
                    mm = getattr(mp, "metrics_master", None)
                    if mm is None or mm.history is None:
                        return {"error": "metrics history is disabled",
                                "series": [], "names": []}
                    return mm.history_report(self.query)
                if route == "/api/v1/master/health":
                    hm = getattr(mp, "health_monitor", None)
                    if hm is None:
                        return {"status": "DISABLED", "alerts": [],
                                "pending": [], "recently_resolved": [],
                                "rules": []}
                    resp = hm.fresh_report()
                    engine = getattr(mp, "remediation", None)
                    if engine is not None:
                        resp["remediation"] = engine.report()
                    return resp
                if route == "/api/v1/master/remediation":
                    engine = getattr(mp, "remediation", None)
                    if engine is None:
                        return {"enabled": False, "audit": [],
                                "quarantined": [], "overlay": {}}
                    return engine.report()
                if route == "/api/v1/master/masters":
                    return mp.masters_report()
                if route == "/api/v1/master/metastore":
                    return {"stats": dict(
                        mp.fs_master.metastore_stats())}
                if route == "/api/v1/master/mounts":
                    return {"mounts": [
                        {"path": m.alluxio_path, "ufs": m.ufs_uri,
                         "read_only": m.read_only}
                        for m in
                        mp.fs_master.mount_table.mount_points()]}
                if route == "/api/v1/master/catalog":
                    tm = mp.table_master
                    return {"databases": {
                        db: tm.list_tables(db)
                        for db in tm.list_databases()}}
                if route == "/api/v1/master/trace":
                    from alluxio_tpu.utils.tracing import (
                        stitch_spans, tracer,
                    )

                    mm = getattr(mp, "metrics_master", None)
                    stitched = stitch_spans(
                        mm.traces if mm is not None else None,
                        limit=int(self.query.get("limit", "500") or 500),
                        prefix=self.query.get("prefix", ""),
                        trace_id=self.query.get("trace_id", ""),
                        local_source="master")
                    if self.query.get("fanout"):
                        from alluxio_tpu.utils.trace_fanout import (
                            merge_stitched, peer_traces)
                        stitched = merge_stitched(
                            stitched, peer_traces(
                                mp._conf,
                                limit=int(self.query.get("limit", "500")
                                          or 500),
                                prefix=self.query.get("prefix", ""),
                                trace_id=self.query.get("trace_id", "")))
                    return {"enabled": tracer().enabled, **stitched}
                if route == "/api/v1/master/profile":
                    mm = getattr(mp, "metrics_master", None)
                    if mm is None:
                        return {"sources": {}}
                    return mm.flame_report(
                        self.query.get("source", ""))
                if route == "/api/v1/master/trace/profile":
                    from alluxio_tpu.utils.critical_path import (
                        analyze_trace, profile)
                    from alluxio_tpu.utils.tracing import (
                        stitch_spans, tracer,
                    )

                    mm = getattr(mp, "metrics_master", None)
                    trace_id = self.query.get("trace_id", "")
                    stitched = stitch_spans(
                        mm.traces if mm is not None else None,
                        limit=int(self.query.get("limit", "4000")
                                  or 4000),
                        prefix=self.query.get("prefix", ""),
                        trace_id=trace_id,
                        local_source="master")
                    if trace_id:
                        return {"enabled": tracer().enabled,
                                "critical_path":
                                    analyze_trace(stitched["spans"])}
                    return {"enabled": tracer().enabled,
                            "profile": profile(
                                stitched["spans"],
                                root_prefix=self.query.get(
                                    "root_prefix", ""))}
                if route == "/api/v1/master/browse":
                    path = self.query.get("path", "/") or "/"
                    entries = mp.fs_master.list_status(path, wire=True)
                    return {"path": path, "entries": [{
                        "name": e["name"], "path": e["path"],
                        "folder": e["folder"], "length": e["length"],
                        "in_memory_percentage":
                            e["in_memory_percentage"],
                        "persistence_state": e["persistence_state"],
                        "pinned": e["pinned"], "owner": e["owner"],
                        "group": e["group"], "mode": oct(e["mode"]),
                        "block_count": len(e["block_ids"]),
                    } for e in entries]}
                if route == "/api/v1/master/config":
                    from alluxio_tpu.conf.property_key import (
                        REGISTRY, mask_credential)

                    conf = mp._conf
                    # EFFECTIVE configuration: every registered key with
                    # its default, overlaid by whatever is actually set
                    # (reference: the webui Configuration page shows the
                    # full resolved table, not just overrides). Values of
                    # credential-flagged keys — and anything that LOOKS
                    # like a secret — are masked, never serialized
                    # (reference DisplayType.CREDENTIALS masking on the
                    # config webUI/REST endpoint).
                    out = {name: {"value": str(pk.default),
                                  "source": "DEFAULT"}
                           for name, pk in REGISTRY.all_keys().items()}
                    for k, v in conf.to_map().items():
                        out[k] = {"value": str(v),
                                  "source": conf.source(k).name}
                    for k, row in out.items():
                        row["value"] = mask_credential(k, row["value"])
                    return {"config": dict(sorted(out.items()))}
                if route == "/api/v1/master/logs":
                    from alluxio_tpu.utils import weblog

                    n = int(self.query.get("n", "200") or 200)
                    return {"records": weblog.tail(
                        n, level=self.query.get("level", ""))}
                return None

        self._server = ThreadingHTTPServer((bind_host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        from alluxio_tpu.utils import weblog

        weblog.install()  # /logs serves this in-process ring
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="master-web", daemon=True)
        self._thread.start()
        LOG.info("master web endpoint on port %d", self.port)
        return self.port

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
