"""Replication control: keep cached-copy counts within [min, max].

Re-design of ``core/server/master/src/main/java/alluxio/master/file/
replication/ReplicationChecker.java:57`` + ``job/plan/replicate/
DefaultReplicationHandler.java``: a periodic heartbeat walks files with
replication constraints, compares each block's live location count against
``replication_min``/``replication_max``, and launches replicate/evict jobs
through the job service. In-flight jobs are tracked per block so one
deficit never spawns duplicate jobs. This is also the elastic-recovery
loop: when a worker is lost, its blocks' location counts drop and the next
check re-replicates (SURVEY §5.3).

Besides the constraint walk, the checker exposes
:meth:`request_replication` — targeted one-shot replication the
remediation engine uses to fan a straggling worker's hottest blocks out
to healthy peers (docs/self_healing.md).

Observability/bounds (PR-6 hardening): launches/failures/deferrals are
counted (``Master.ReplicationJobs{Launched,Failed,Deferred}`` +
``Master.ReplicationJobsInflight`` gauge, surfaced by ``fsadmin report
metrics``), launch failures warn rate-limited instead of vanishing at
debug level, ``_inflight`` is capped so a mass worker loss cannot flood
the job master, and only a NOT-FOUND ``get_status`` reaps an in-flight
entry — a transient job-master RPC blip retries next heartbeat instead
of silently dropping deficit tracking.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Set

from alluxio_tpu.job.wire import Status
from alluxio_tpu.utils.exceptions import (
    BlockDoesNotExistError, NotFoundError,
)

LOG = logging.getLogger(__name__)

#: seconds between launch-failure warnings (each carries the count
#: accumulated since the last one)
_WARN_EVERY_S = 60.0


class ReplicationChecker:
    def __init__(self, fs_master, block_master, job_client, *,
                 max_inflight: int = 256,
                 clock=time.monotonic, registry=None) -> None:
        self._fs = fs_master
        self._bm = block_master
        self._jobs = job_client
        self._clock = clock
        self.max_inflight = max(1, int(max_inflight))
        #: block_id -> in-flight job id
        self._inflight: Dict[int, int] = {}
        self._lock = threading.Lock()
        self._failures_since_warn = 0
        self._last_warn = float("-inf")
        if registry is None:
            from alluxio_tpu.metrics import metrics

            registry = metrics()
        self._c_launched = registry.counter(
            "Master.ReplicationJobsLaunched")
        self._c_failed = registry.counter("Master.ReplicationJobsFailed")
        self._c_deferred = registry.counter(
            "Master.ReplicationJobsDeferred")
        registry.register_gauge("Master.ReplicationJobsInflight",
                                lambda: float(len(self._inflight)))

    def heartbeat(self) -> None:
        self._reap_finished()
        for inode in self._fs.files_with_replication_constraints():
            rmin = inode.replication_min
            rmax = inode.replication_max
            for bid in inode.block_ids:
                if bid in self._inflight:
                    continue
                try:
                    info = self._bm.get_block_info(bid)
                except (BlockDoesNotExistError, NotFoundError):
                    continue  # block gone; skip
                replicas = len(info.locations)
                if rmin > 0 and replicas < rmin:
                    self._launch(bid, {"type": "replicate",
                                       "block_id": bid,
                                       "replicas": rmin - replicas})
                elif 0 <= rmax < replicas:
                    self._launch(bid, {"type": "evict", "block_id": bid,
                                       "replicas": replicas - rmax})

    def request_replication(self, block_ids: List[int], *,
                            replicas: int = 1) -> List[int]:
        """Targeted one-shot replication: +``replicas`` copies of each
        block, deduplicated against in-flight jobs and bounded by the
        same cap as the constraint walk.  Returns the block ids whose
        jobs actually launched (the remediation audit records them)."""
        launched = []
        for bid in block_ids:
            if self._launch(bid, {"type": "replicate", "block_id": bid,
                                  "replicas": int(replicas)}):
                launched.append(bid)
        return launched

    #: placeholder job id while the launch RPC is in flight — keeps the
    #: (bid) slot reserved so the second writer thread (the remediation
    #: engine calls request_replication off the health heartbeat while
    #: the constraint walk runs on its own) cannot double-launch
    _RESERVED = -1

    def _launch(self, bid: int, config: dict) -> bool:
        with self._lock:
            if bid in self._inflight:
                return False
            if len(self._inflight) >= self.max_inflight:
                # bounded: after a mass worker loss the deficit list
                # can be the whole namespace; the rest waits for the
                # next beat
                self._c_deferred.inc()
                return False
            self._inflight[bid] = self._RESERVED
        try:
            # the RPC runs outside the lock: a slow job master must not
            # serialize the other launcher behind it
            job_id = self._jobs.run(config)
        except Exception:  # noqa: BLE001 - job svc may be down
            with self._lock:
                self._inflight.pop(bid, None)
            self._c_failed.inc()
            self._warn_rate_limited(bid, config)
            return False
        with self._lock:
            self._inflight[bid] = job_id
        self._c_launched.inc()
        return True

    def _warn_rate_limited(self, bid: int, config: dict) -> None:
        """Launch failures used to vanish at debug level while the
        deficit silently persisted; warn, but at most once per minute
        with the accumulated count — a dead job master must not spew
        one line per deficient block per heartbeat."""
        self._failures_since_warn += 1
        now = self._clock()
        if now - self._last_warn < _WARN_EVERY_S:
            LOG.debug("replication job for block %s failed to launch",
                      bid, exc_info=True)
            return
        LOG.warning(
            "%d replication job launch(es) failed since the last "
            "warning (latest: %s for block %s) — is the job service "
            "up?  Master.ReplicationJobsFailed carries the total",
            self._failures_since_warn, config.get("type"), bid,
            exc_info=True)
        self._failures_since_warn = 0
        self._last_warn = now

    def _reap_finished(self) -> None:
        done: Set[int] = set()
        with self._lock:
            inflight = [(b, j) for b, j in self._inflight.items()
                        if j != self._RESERVED]  # launch RPC in flight
        for bid, job_id in inflight:
            try:
                info = self._jobs.get_status(job_id)
            except NotFoundError:
                # genuinely evicted from the job master's ring: the job
                # finished long ago — reap
                done.add(bid)
                continue
            # transport blip: the job may well still be running; reaping
            # now would drop the dedupe entry and double-launch on the
            # next beat. Retried next heartbeat; launch failures are
            # already WARN-logged rate-limited.
            # lint: allow[except-swallow] -- deliberate silent retry: transport blip, job likely still running
            except Exception:  # noqa: BLE001
                continue
            if Status.is_finished(info.status):
                done.add(bid)
        with self._lock:
            for bid in done:
                self._inflight.pop(bid, None)
