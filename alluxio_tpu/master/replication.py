"""Replication control: keep cached-copy counts within [min, max].

Re-design of ``core/server/master/src/main/java/alluxio/master/file/
replication/ReplicationChecker.java:57`` + ``job/plan/replicate/
DefaultReplicationHandler.java``: a periodic heartbeat walks files with
replication constraints, compares each block's live location count against
``replication_min``/``replication_max``, and launches replicate/evict jobs
through the job service. In-flight jobs are tracked per block so one
deficit never spawns duplicate jobs. This is also the elastic-recovery
loop: when a worker is lost, its blocks' location counts drop and the next
check re-replicates (SURVEY §5.3).
"""

from __future__ import annotations

import logging
from typing import Dict, Set

from alluxio_tpu.job.wire import Status

LOG = logging.getLogger(__name__)


class ReplicationChecker:
    def __init__(self, fs_master, block_master, job_client) -> None:
        self._fs = fs_master
        self._bm = block_master
        self._jobs = job_client
        #: block_id -> in-flight job id
        self._inflight: Dict[int, int] = {}

    def heartbeat(self) -> None:
        self._reap_finished()
        for inode in self._fs.files_with_replication_constraints():
            rmin = inode.replication_min
            rmax = inode.replication_max
            for bid in inode.block_ids:
                if bid in self._inflight:
                    continue
                try:
                    info = self._bm.get_block_info(bid)
                except Exception:  # noqa: BLE001 - block gone; skip
                    continue
                replicas = len(info.locations)
                try:
                    if rmin > 0 and replicas < rmin:
                        job_id = self._jobs.run({
                            "type": "replicate", "block_id": bid,
                            "replicas": rmin - replicas})
                        self._inflight[bid] = job_id
                    elif 0 <= rmax < replicas:
                        job_id = self._jobs.run({
                            "type": "evict", "block_id": bid,
                            "replicas": replicas - rmax})
                        self._inflight[bid] = job_id
                except Exception:  # noqa: BLE001 - job svc may be down
                    LOG.debug("replication job for block %s failed to "
                              "launch", bid, exc_info=True)

    def _reap_finished(self) -> None:
        done: Set[int] = set()
        for bid, job_id in self._inflight.items():
            try:
                info = self._jobs.get_status(job_id)
                if Status.is_finished(info.status):
                    done.add(bid)
            except Exception:  # noqa: BLE001 - evicted from job master
                done.add(bid)
        for bid in done:
            self._inflight.pop(bid, None)
