"""TTL bucket list.

Re-design of ``core/server/master/.../file/meta/{TtlBucket,TtlBucketList}.java``:
inodes with a TTL are hashed into coarse time buckets keyed by expiry
interval; the TTL checker heartbeat (``file/InodeTtlChecker.java``) polls
expired buckets and applies each inode's TtlAction (DELETE or FREE).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Set

DEFAULT_BUCKET_INTERVAL_MS = 60 * 60 * 1000  # 1h, reference default


class TtlBucketList:
    def __init__(self, bucket_interval_ms: int = DEFAULT_BUCKET_INTERVAL_MS):
        self._interval = bucket_interval_ms
        self._buckets: Dict[int, Set[int]] = {}
        self._expiry: Dict[int, int] = {}  # inode id -> expiry ms
        self._lock = threading.Lock()

    def _bucket_of(self, expiry_ms: int) -> int:
        return expiry_ms // self._interval

    def insert(self, inode_id: int, base_time_ms: int, ttl_ms: int) -> None:
        expiry = base_time_ms + ttl_ms
        with self._lock:
            self._expiry[inode_id] = expiry
            self._buckets.setdefault(self._bucket_of(expiry), set()).add(inode_id)

    def remove(self, inode_id: int) -> None:
        with self._lock:
            expiry = self._expiry.pop(inode_id, None)
            if expiry is None:
                return
            b = self._buckets.get(self._bucket_of(expiry))
            if b is not None:
                b.discard(inode_id)
                if not b:
                    del self._buckets[self._bucket_of(expiry)]

    def poll_expired(self, now_ms: int) -> List[int]:
        """Return (and retain) ids of inodes whose TTL has elapsed; the TTL
        checker removes them after a successful action."""
        out: List[int] = []
        with self._lock:
            for bucket_key in sorted(self._buckets):
                if bucket_key * self._interval > now_ms:
                    break
                for iid in self._buckets[bucket_key]:
                    if self._expiry.get(iid, 1 << 62) <= now_ms:
                        out.append(iid)
        return out

    def clear(self) -> None:
        with self._lock:
            self._buckets.clear()
            self._expiry.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._expiry)
