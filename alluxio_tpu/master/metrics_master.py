"""MetricsMaster: cluster-wide metric aggregation at the metadata master.

Re-design of ``core/server/master/src/main/java/alluxio/master/metrics/
{DefaultMetricsMaster,MetricsStore}.java`` + ``grpc/metric_master.proto``:
workers and clients ship their metric snapshots on a heartbeat; the master
stores them per source and serves ``Cluster.*`` aggregates (sums across
sources, with the instance prefix rewritten) alongside its own metrics —
what ``fsadmin report metrics`` and the Prometheus endpoint read.

Aggregation is additive-only: counters/meters/gauges sum across sources;
timer percentile sub-metrics (non-additive) are skipped, as the reference
aggregates counters and throughput meters, not latency histograms.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

_NON_ADDITIVE_SUFFIXES = (".p50", ".p95", ".p99", ".mean", ".min", ".max")
_INSTANCE_PREFIXES = ("Worker.", "Client.", "JobWorker.", "Process.")


class MetricsStore:
    """Per-source metric reports + cluster aggregation."""

    def __init__(self, *, source_ttl_s: float = 300.0,
                 max_sources: int = 4096,
                 clock=time.monotonic) -> None:
        self._reports: Dict[str, Dict[str, float]] = {}
        self._last_seen: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._ttl = source_ttl_s
        self._max_sources = max_sources
        self._clock = clock

    def report(self, source: str, metrics: Dict[str, float]) -> None:
        """A node's full snapshot replaces its previous one (the reference
        ships complete snapshots, not deltas — idempotent under retry).
        New sources beyond ``max_sources`` are dropped — bounds memory
        against spoofed source-name floods (advisor r2 finding)."""
        now = self._clock()
        with self._lock:
            if source not in self._reports and \
                    len(self._reports) >= self._max_sources:
                self._gc(now)
                if len(self._reports) >= self._max_sources:
                    return
            self._reports[source] = {str(k): float(v)
                                     for k, v in (metrics or {}).items()}
            self._last_seen[source] = now
            self._gc(now)

    def clear_source(self, source: str) -> None:
        with self._lock:
            self._reports.pop(source, None)
            self._last_seen.pop(source, None)

    def _gc(self, now: float) -> None:
        dead = [s for s, t in self._last_seen.items()
                if now - t > self._ttl]
        for s in dead:
            self._reports.pop(s, None)
            self._last_seen.pop(s, None)

    def cluster_metrics(self) -> Dict[str, float]:
        """``Cluster.<name>`` = sum over sources of additive metrics."""
        out: Dict[str, float] = {}
        with self._lock:
            self._gc(self._clock())
            for snap in self._reports.values():
                for name, value in snap.items():
                    if name.endswith(_NON_ADDITIVE_SUFFIXES):
                        continue
                    for p in _INSTANCE_PREFIXES:
                        if name.startswith(p):
                            name = name[len(p):]
                            break
                    key = f"Cluster.{name}"
                    out[key] = out.get(key, 0.0) + value
        return out

    def source_count(self) -> int:
        with self._lock:
            return len(self._reports)

    def sources(self) -> Dict[str, float]:
        """source -> seconds since last report (fsadmin diagnostics)."""
        now = self._clock()
        with self._lock:
            return {s: now - t for s, t in self._last_seen.items()}


class MetricsMaster:
    """Facade the master process owns (reference: DefaultMetricsMaster)."""

    def __init__(self, store: Optional[MetricsStore] = None) -> None:
        self.store = store or MetricsStore()

    def handle_heartbeat(self, request: dict) -> dict:
        source = str(request.get("source") or "unknown")
        self.store.report(source, request.get("metrics") or {})
        return {}

    def merged_snapshot(self, own: Dict[str, float]) -> Dict[str, float]:
        merged = dict(own)
        merged.update(self.store.cluster_metrics())
        merged["Cluster.metrics.sources"] = float(self.store.source_count())
        return merged
