"""MetricsMaster: cluster-wide metric aggregation at the metadata master.

Re-design of ``core/server/master/src/main/java/alluxio/master/metrics/
{DefaultMetricsMaster,MetricsStore}.java`` + ``grpc/metric_master.proto``:
workers and clients ship their metric snapshots on a heartbeat; the master
stores them per source and serves ``Cluster.*`` aggregates (sums across
sources, with the instance prefix rewritten) alongside its own metrics —
what ``fsadmin report metrics`` and the Prometheus endpoint read.

The same heartbeat carries completed SPAN batches (each node drains its
trace ring): they land in a ``TraceStore`` so ``/api/v1/master/trace``
serves stitched cross-process traces — one trace_id across client,
worker and master spans.

Aggregation is additive-only: counters/meters/gauges sum across sources;
timer percentile sub-metrics (non-additive) are skipped, as the reference
aggregates counters and throughput meters, not latency histograms.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from alluxio_tpu.utils.tracing import TraceStore

#: cached ``metrics()`` accessor (same pattern as client/block_streams):
#: the drop paths run under the store lock on the heartbeat path, and
#: must not pay the import machinery there.  The function, not the
#: registry, is cached so ``reset_metrics()`` in tests still applies.
_metrics_fn = None


def _metrics():
    global _metrics_fn
    if _metrics_fn is None:
        from alluxio_tpu.metrics import metrics as _m

        _metrics_fn = _m
    return _metrics_fn()


_NON_ADDITIVE_SUFFIXES = (".p50", ".p95", ".p99", ".mean", ".min", ".max")
#: fraction gauges aggregate as a MEAN across sources — summing 4
#: clients' 0.8 into a "3.2 input-bound" Cluster gauge is nonsense,
#: but dropping them would hide the input doctor's headline number
#: from exactly the distributed deployment it targets
_MEAN_SUFFIXES = ("InputBoundFraction",)
_INSTANCE_PREFIXES = ("Worker.", "Client.", "JobWorker.", "Process.")


class MetricsStore:
    """Per-source metric reports + cluster aggregation."""

    def __init__(self, *, source_ttl_s: float = 300.0,
                 max_sources: int = 4096,
                 blocked_ttl_s: float = 3600.0,
                 clock=time.monotonic) -> None:
        self._reports: Dict[str, Dict[str, float]] = {}
        self._last_seen: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._ttl = source_ttl_s
        self._max_sources = max_sources
        self._clock = clock
        # expiry sweeps are O(sources); amortize them off the per-report
        # hot path (reads force their own sweep, so TTL stays exact
        # where it is observed)
        self._gc_every_s = min(5.0, source_ttl_s / 2.0)
        self._last_gc = float("-inf")
        #: reports refused by the max_sources cap (also counted in the
        #: Master.MetricsReportsDropped counter for the heartbeat path)
        self.dropped_reports = 0
        #: reports refused because their source is blocked (counted
        #: separately — Master.MetricsReportsBlocked — so fsadmin's
        #: "raise the source cap" advice never points at what is
        #: actually a dead worker needing restart)
        self.blocked_reports = 0
        #: sources whose reports are refused until explicitly
        #: unblocked: a worker the block master declared lost may keep
        #: shipping metrics heartbeats (wedged block-sync thread), and
        #: those must not re-admit its snapshot into the Cluster.*
        #: aggregates after clear_source — only re-registration
        #: (unblock_source) readmits it.  Entries age out after
        #: ``blocked_ttl_s`` (mirrors the history end markers aging
        #: out with retention) so churned workers that never return
        #: cannot leak entries forever.
        self._blocked: Dict[str, float] = {}
        self._blocked_ttl = blocked_ttl_s

    def report(self, source: str, metrics: Dict[str, float], *,
               sanitized: bool = False) -> bool:
        """A node's full snapshot replaces its previous one (the reference
        ships complete snapshots, not deltas — idempotent under retry).
        New sources beyond ``max_sources`` are dropped — bounds memory
        against spoofed source-name floods (advisor r2 finding).  Drops
        are counted (``Master.MetricsReportsDropped``) so the cap is
        observable instead of silently eating a fleet expansion.
        Returns False when the report was dropped.  ``sanitized=True``
        promises keys are already str and values float — the heartbeat
        path coerces once and shares the dict with the history offer."""
        now = self._clock()
        if not sanitized:
            metrics = {str(k): float(v)
                       for k, v in (metrics or {}).items()}
        with self._lock:
            blocked_at = self._blocked.get(source)
            if blocked_at is not None:
                if now - blocked_at <= self._blocked_ttl:
                    self.blocked_reports += 1
                    _metrics().counter(
                        "Master.MetricsReportsBlocked").inc()
                    return False
                del self._blocked[source]  # block aged out
            if source not in self._reports and \
                    len(self._reports) >= self._max_sources:
                self._gc(now)
                if len(self._reports) >= self._max_sources:
                    self.dropped_reports += 1
                    _metrics().counter(
                        "Master.MetricsReportsDropped").inc()
                    return False
            self._reports[source] = metrics
            self._last_seen[source] = now
            if now - self._last_gc >= self._gc_every_s:
                self._last_gc = now
                self._gc(now)
        return True

    def clear_source(self, source: str, *, block: bool = False) -> None:
        """Drop ``source``'s snapshot; with ``block=True`` also refuse
        its future reports until :meth:`unblock_source` — the
        worker-lost path uses this so a lost-but-chatty worker (metrics
        heartbeat outliving a wedged block-sync thread) cannot re-admit
        itself into the ``Cluster.*`` aggregates seconds after being
        cleared."""
        with self._lock:
            self._reports.pop(source, None)
            self._last_seen.pop(source, None)
            if block:
                self._blocked[source] = self._clock()

    def unblock_source(self, source: str) -> None:
        """Re-admit a blocked source (worker re-registered)."""
        with self._lock:
            self._blocked.pop(source, None)

    def _gc(self, now: float) -> None:
        dead = [s for s, t in self._last_seen.items()
                if now - t > self._ttl]
        for s in dead:
            self._reports.pop(s, None)
            self._last_seen.pop(s, None)
        if self._blocked:
            # a churned worker that never re-registers (new host:port
            # on reschedule) must not leak its block entry forever
            expired = [s for s, t in self._blocked.items()
                       if now - t > self._blocked_ttl]
            for s in expired:
                del self._blocked[s]

    def cluster_metrics(self) -> Dict[str, float]:
        """``Cluster.<name>`` = sum over sources of additive metrics
        (fraction gauges average instead)."""
        out: Dict[str, float] = {}
        mean_counts: Dict[str, int] = {}
        with self._lock:
            self._gc(self._clock())
            for snap in self._reports.values():
                for name, value in snap.items():
                    if name.endswith(_NON_ADDITIVE_SUFFIXES):
                        continue
                    for p in _INSTANCE_PREFIXES:
                        if name.startswith(p):
                            name = name[len(p):]
                            break
                    else:
                        # every legit heartbeat metric carries an
                        # instance prefix (the registry forces one);
                        # anything else is a spoofed name and must not
                        # launder into a Cluster.* series past the
                        # history's prefix allowlist
                        continue
                    key = f"Cluster.{name}"
                    out[key] = out.get(key, 0.0) + value
                    if name.endswith(_MEAN_SUFFIXES):
                        mean_counts[key] = mean_counts.get(key, 0) + 1
        for key, n in mean_counts.items():
            out[key] = out[key] / n
        return out

    def source_count(self) -> int:
        with self._lock:
            return len(self._reports)

    def sources(self) -> Dict[str, float]:
        """source -> seconds since last report (fsadmin diagnostics)."""
        now = self._clock()
        with self._lock:
            return {s: now - t for s, t in self._last_seen.items()}

    def per_source(self, name: str) -> Dict[str, float]:
        """Latest value of one metric in every source's last snapshot —
        includes the non-additive timer sub-metrics (``.p99`` etc.) the
        ``Cluster.*`` aggregation skips, which is exactly what the
        per-worker-vs-fleet health rules need."""
        with self._lock:
            return {src: snap[name] for src, snap in self._reports.items()
                    if name in snap}


class MetricsMaster:
    """Facade the master process owns (reference: DefaultMetricsMaster).

    When a :class:`~alluxio_tpu.metrics.history.MetricsHistory` is
    attached, every accepted heartbeat snapshot is *offered* to it —
    an O(1) hand-off that keeps the RPC path flat — and
    :meth:`drain_history` (called from the health heartbeat and the
    history query surfaces) folds pending snapshots into the rings and
    samples the ``Cluster.*`` aggregates alongside the per-source
    series."""

    #: minimum spacing of Cluster.* aggregate samples: aggregation is
    #: O(sources x metrics), so it must not run per-heartbeat
    CLUSTER_SAMPLE_INTERVAL_S = 5.0

    def __init__(self, store: Optional[MetricsStore] = None,
                 traces: Optional[TraceStore] = None,
                 history=None) -> None:
        self.store = store or MetricsStore()
        self.traces = traces or TraceStore()
        self.history = history
        #: source -> accumulated flame data shipped by that node's
        #: stack sampler (utils/profiler.py) on the metrics heartbeat
        self.flames: dict = {}
        self._flames_lock = threading.Lock()
        self._last_cluster_sample = 0.0
        #: serializes drain_history: the health heartbeat and the
        #: query surfaces (web/RPC) all drain, and an unsynchronized
        #: check-then-set on the cluster-sample interval would let two
        #: near-simultaneous callers ingest Cluster.* samples
        #: microseconds apart — a poisoned dt for rate derivation
        self._drain_lock = threading.Lock()

    def handle_heartbeat(self, request: dict) -> dict:
        source = str(request.get("source") or "unknown")
        # coerce once: store and history offer share this dict (both
        # treat it read-only), and a non-string metric key reaching
        # the history would crash the drain later, off the RPC path
        snapshot = {str(k): float(v)
                    for k, v in (request.get("metrics") or {}).items()}
        accepted = self.store.report(source, snapshot, sanitized=True)
        if accepted and self.history is not None:
            self.history.offer(source, snapshot)
        spans = request.get("spans")
        if spans and accepted:
            # a refused source (spoofed past the cap, or a blocked
            # lost worker) must not keep washing the bounded trace
            # ring with live-looking spans either
            self.traces.ingest(source, spans)
        flame = request.get("profile")
        if isinstance(flame, dict) and accepted:
            from alluxio_tpu.utils.profiler import merge_flames

            with self._flames_lock:
                # same source cap as the metric store: `accepted`
                # already bounds who gets a flame slot
                self.flames[source] = merge_flames(
                    self.flames.get(source), flame)
        return {}

    def flame_report(self, source: str = "") -> dict:
        """Accumulated flame data (``/api/v1/master/profile``): one
        source's merged stacks, or the per-source sample totals."""
        from alluxio_tpu.utils.profiler import merge_flames, profiler

        # the master is its own source: nothing heartbeats its sampler
        # to itself, so fold the local delta in at query time
        local = profiler().drain() if profiler().running else None
        if local is not None:
            with self._flames_lock:
                self.flames["master"] = merge_flames(
                    self.flames.get("master"), local)
        with self._flames_lock:
            if source:
                return {"source": source,
                        "flame": self.flames.get(source)}
            return {"sources": {
                s: {"samples": f.get("samples", 0),
                    "dropped": f.get("dropped", 0),
                    "stacks": len(f.get("stacks") or ())}
                for s, f in self.flames.items()}}

    def drain_history(self, now: Optional[float] = None) -> int:
        """Fold pending heartbeat snapshots into the history rings and
        (rate-limited) record the ``Cluster.*`` aggregate series under
        the synthetic source ``cluster``.  Never called on the RPC hot
        path."""
        h = self.history
        if h is None:
            return 0
        with self._drain_lock:
            n = h.drain()
            ts = h._clock() if now is None else now
            if ts - self._last_cluster_sample >= \
                    self.CLUSTER_SAMPLE_INTERVAL_S:
                self._last_cluster_sample = ts
                agg = self.store.cluster_metrics()
                if agg:
                    n += h.ingest("cluster", agg, now=ts)
        return n

    def history_report(self, params: Optional[dict] = None) -> dict:
        """One parser + response shape for every history query surface
        (RPC ``get_metrics_history``, ``/api/v1/master/metrics/history``)
        — values may arrive typed (RPC) or as query strings (web).
        Caller checks ``history is not None`` first; how "disabled" is
        reported is the one thing that stays surface-specific."""
        p = params or {}
        self.drain_history()
        h = self.history
        name = str(p.get("name") or "")
        if not name:
            return {"names": h.names(prefix=str(p.get("prefix") or "")),
                    "stats": h.stats()}
        rate = p.get("rate")
        if isinstance(rate, str):
            rate = rate.lower() in ("1", "true", "yes")
        return {"series": h.query(
            name, source=str(p.get("source") or ""),
            resolution=str(p.get("resolution") or "raw"),
            since=float(p.get("since") or 0.0),
            rate=bool(rate),
            limit=int(p.get("limit") or 0)),
            "stats": h.stats()}

    def merged_snapshot(self, own: Dict[str, float]) -> Dict[str, float]:
        merged = dict(own)
        merged.update(self.store.cluster_metrics())
        # lint: allow[metric-unknown] -- synthetic aggregate minted at snapshot-merge time; no single emit site
        merged["Cluster.MetricsSources"] = float(self.store.source_count())
        return merged
