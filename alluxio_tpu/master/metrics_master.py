"""MetricsMaster: cluster-wide metric aggregation at the metadata master.

Re-design of ``core/server/master/src/main/java/alluxio/master/metrics/
{DefaultMetricsMaster,MetricsStore}.java`` + ``grpc/metric_master.proto``:
workers and clients ship their metric snapshots on a heartbeat; the master
stores them per source and serves ``Cluster.*`` aggregates (sums across
sources, with the instance prefix rewritten) alongside its own metrics —
what ``fsadmin report metrics`` and the Prometheus endpoint read.

The same heartbeat carries completed SPAN batches (each node drains its
trace ring): they land in a ``TraceStore`` so ``/api/v1/master/trace``
serves stitched cross-process traces — one trace_id across client,
worker and master spans.

Aggregation is additive-only: counters/meters/gauges sum across sources;
timer percentile sub-metrics (non-additive) are skipped, as the reference
aggregates counters and throughput meters, not latency histograms.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from alluxio_tpu.utils.tracing import TraceStore

_NON_ADDITIVE_SUFFIXES = (".p50", ".p95", ".p99", ".mean", ".min", ".max")
#: fraction gauges aggregate as a MEAN across sources — summing 4
#: clients' 0.8 into a "3.2 input-bound" Cluster gauge is nonsense,
#: but dropping them would hide the input doctor's headline number
#: from exactly the distributed deployment it targets
_MEAN_SUFFIXES = ("InputBoundFraction",)
_INSTANCE_PREFIXES = ("Worker.", "Client.", "JobWorker.", "Process.")


class MetricsStore:
    """Per-source metric reports + cluster aggregation."""

    def __init__(self, *, source_ttl_s: float = 300.0,
                 max_sources: int = 4096,
                 clock=time.monotonic) -> None:
        self._reports: Dict[str, Dict[str, float]] = {}
        self._last_seen: Dict[str, float] = {}
        self._lock = threading.Lock()
        self._ttl = source_ttl_s
        self._max_sources = max_sources
        self._clock = clock

    def report(self, source: str, metrics: Dict[str, float]) -> None:
        """A node's full snapshot replaces its previous one (the reference
        ships complete snapshots, not deltas — idempotent under retry).
        New sources beyond ``max_sources`` are dropped — bounds memory
        against spoofed source-name floods (advisor r2 finding)."""
        now = self._clock()
        with self._lock:
            if source not in self._reports and \
                    len(self._reports) >= self._max_sources:
                self._gc(now)
                if len(self._reports) >= self._max_sources:
                    return
            self._reports[source] = {str(k): float(v)
                                     for k, v in (metrics or {}).items()}
            self._last_seen[source] = now
            self._gc(now)

    def clear_source(self, source: str) -> None:
        with self._lock:
            self._reports.pop(source, None)
            self._last_seen.pop(source, None)

    def _gc(self, now: float) -> None:
        dead = [s for s, t in self._last_seen.items()
                if now - t > self._ttl]
        for s in dead:
            self._reports.pop(s, None)
            self._last_seen.pop(s, None)

    def cluster_metrics(self) -> Dict[str, float]:
        """``Cluster.<name>`` = sum over sources of additive metrics
        (fraction gauges average instead)."""
        out: Dict[str, float] = {}
        mean_counts: Dict[str, int] = {}
        with self._lock:
            self._gc(self._clock())
            for snap in self._reports.values():
                for name, value in snap.items():
                    if name.endswith(_NON_ADDITIVE_SUFFIXES):
                        continue
                    for p in _INSTANCE_PREFIXES:
                        if name.startswith(p):
                            name = name[len(p):]
                            break
                    key = f"Cluster.{name}"
                    out[key] = out.get(key, 0.0) + value
                    if name.endswith(_MEAN_SUFFIXES):
                        mean_counts[key] = mean_counts.get(key, 0) + 1
        for key, n in mean_counts.items():
            out[key] = out[key] / n
        return out

    def source_count(self) -> int:
        with self._lock:
            return len(self._reports)

    def sources(self) -> Dict[str, float]:
        """source -> seconds since last report (fsadmin diagnostics)."""
        now = self._clock()
        with self._lock:
            return {s: now - t for s, t in self._last_seen.items()}


class MetricsMaster:
    """Facade the master process owns (reference: DefaultMetricsMaster)."""

    def __init__(self, store: Optional[MetricsStore] = None,
                 traces: Optional[TraceStore] = None) -> None:
        self.store = store or MetricsStore()
        self.traces = traces or TraceStore()

    def handle_heartbeat(self, request: dict) -> dict:
        source = str(request.get("source") or "unknown")
        self.store.report(source, request.get("metrics") or {})
        spans = request.get("spans")
        if spans:
            self.traces.ingest(source, spans)
        return {}

    def merged_snapshot(self, own: Dict[str, float]) -> Dict[str, float]:
        merged = dict(own)
        merged.update(self.store.cluster_metrics())
        merged["Cluster.metrics.sources"] = float(self.store.source_count())
        return merged
