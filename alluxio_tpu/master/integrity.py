"""Master integrity daemons: lost files, orphan blocks, abandoned temps.

Re-design of ``core/server/master/src/main/java/alluxio/master/file/
{LostFileDetector,BlockIntegrityChecker,UfsCleaner}.java`` as tickable
heartbeats:

- **LostFileDetector** — a file whose every block has no live worker
  location and no UFS copy is unrecoverable: mark it ``LOST`` (journaled)
  so clients fail fast instead of timing out; if a worker holding the
  blocks re-registers, the detector restores the state.
- **BlockIntegrityChecker** — blocks in the master map whose owning file
  inode no longer exists are garbage (a crash between delete journal
  batches can leak them): free them on their workers and drop metadata.
- **UfsCleaner** — async persist writes ``.atpu_persist.*`` temp files
  that a worker crash can abandon; sweep mounted UFSes for temps older
  than a TTL.
"""

from __future__ import annotations

import logging
import time
from typing import List

from alluxio_tpu.journal.format import EntryType
from alluxio_tpu.master.inode import PersistenceState
from alluxio_tpu.utils import ids
from alluxio_tpu.utils.exceptions import NotFoundError
from alluxio_tpu.utils.uri import AlluxioURI

LOG = logging.getLogger(__name__)

PERSIST_TEMP_PREFIX = ".atpu_persist."
#: every temp-file family the framework writes into UFSes: persist temps
#: plus the local-UFS atomic-create temps (underfs/local.py mkstemp)
INFRA_TEMP_PREFIXES = (PERSIST_TEMP_PREFIX, ".atpu_tmp_")


def is_infra_temp(name: str) -> bool:
    """True for framework-internal temp names that must never surface in
    the namespace (metadata sync) and are sweepable when stale."""
    return name.startswith(INFRA_TEMP_PREFIXES)


class LostFileDetector:
    """Reference: ``LostFileDetector.java`` (HeartbeatContext
    MASTER_LOST_FILES_DETECTION)."""

    def __init__(self, fs_master, block_master) -> None:
        self._fsm = fs_master
        self._bm = block_master

    def heartbeat(self) -> None:
        self._detect()
        self._recover()

    def _detect(self) -> None:
        lost_blocks = self._bm.lost_blocks()
        if not lost_blocks:
            return
        tree = self._fsm.inode_tree
        candidates = {ids.file_id_for_block(b) for b in lost_blocks}
        with tree.lock.write_locked():
            for fid in sorted(candidates):
                inode = tree.get_inode(fid)
                if inode is None or inode.is_directory or \
                        not inode.completed:
                    continue
                if inode.persistence_state in (PersistenceState.PERSISTED,
                                               PersistenceState.LOST):
                    # persisted: re-fetchable from the UFS, not lost
                    continue
                blocks = inode.block_ids
                if blocks and all(b in lost_blocks for b in blocks):
                    pending = inode.persistence_state == \
                        PersistenceState.TO_BE_PERSISTED
                    with self._fsm._journal.create_context() as ctx:
                        ctx.append(EntryType.SET_ATTRIBUTE, {
                            "id": inode.id,
                            "persistence_state": PersistenceState.LOST,
                            "lost_pending_persist": pending})
                    LOG.warning("file %s marked LOST (all %d blocks on "
                                "lost workers)", inode.name, len(blocks))

    def _recover(self) -> None:
        """Scan the tree's journaled LOST registry (survives restarts —
        the SET_ATTRIBUTE entries rebuild ``lost_file_ids`` on replay)."""
        tree = self._fsm.inode_tree
        if not tree.lost_file_ids:
            return
        with tree.lock.write_locked():
            for fid in sorted(tree.lost_file_ids):
                inode = tree.get_inode(fid)
                if inode is None or \
                        inode.persistence_state != PersistenceState.LOST:
                    tree.lost_file_ids.discard(fid)
                    continue
                # recover only when every block is actually available
                # again (a merely-unknown block after a restart is not
                # evidence of recovery)
                if inode.block_ids and all(
                        self._bm.has_locations(b)
                        for b in inode.block_ids):
                    # a durability request pending at loss time is
                    # restored, not dropped (ASYNC_THROUGH contract)
                    state = PersistenceState.TO_BE_PERSISTED if \
                        inode.lost_pending_persist else \
                        PersistenceState.NOT_PERSISTED
                    with self._fsm._journal.create_context() as ctx:
                        ctx.append(EntryType.SET_ATTRIBUTE, {
                            "id": inode.id,
                            "persistence_state": state,
                            "lost_pending_persist": False})
                    if state == PersistenceState.TO_BE_PERSISTED:
                        self._fsm._persist_requests.add(inode.id)
                    LOG.info("file %s recovered from LOST (-> %s)",
                             inode.name, state)


class BlockIntegrityChecker:
    """Reference: ``BlockIntegrityChecker.java`` — delete orphaned
    blocks whose owning file is gone."""

    def __init__(self, fs_master, block_master) -> None:
        self._fsm = fs_master
        self._bm = block_master

    def heartbeat(self) -> None:
        tree = self._fsm.inode_tree
        orphans: List[int] = []
        for bid in self._bm.all_block_ids():
            inode = tree.get_inode(ids.file_id_for_block(bid))
            if inode is None or bid not in inode.block_ids:
                orphans.append(bid)
        if orphans:
            LOG.warning("freeing %d orphaned blocks with no owning file",
                        len(orphans))
            self._bm.remove_blocks(orphans, delete_metadata=True)


class UfsCleaner:
    """Reference: ``UfsCleaner.java`` — sweep abandoned persist temps.

    Cost note: temps live next to their final files (same-dir rename
    atomicity), so the sweep walks the whole mounted namespace — on
    object stores that is one listing per prefix per tick. Abandoned
    temps exist only after a worker crash, so the default interval is
    long (1h) and each tick is bounded by ``max_entries_per_tick``; a
    registry of in-flight temp paths on the master would remove the walk
    entirely and is the planned upgrade if mounts grow past the budget.
    """

    def __init__(self, mount_table, ufs_manager, *,
                 ttl_ms: int = 60 * 60 * 1000,
                 max_entries_per_tick: int = 100_000) -> None:
        self._mounts = mount_table
        self._ufs = ufs_manager
        self._ttl_ms = ttl_ms
        self._budget = max_entries_per_tick

    def heartbeat(self) -> int:
        """Returns the number of temps removed (for tests/metrics)."""
        removed = 0
        now_ms = int(time.time() * 1000)
        for mi in self._mounts.mount_points():
            try:
                ufs = self._ufs.get(mi.mount_id)
            except NotFoundError:
                continue  # unmounted mid-scan
            removed += self._sweep(ufs, mi.ufs_uri, now_ms, self._budget)
        return removed

    def _sweep(self, ufs, root: str, now_ms: int, budget: int) -> int:
        removed = 0
        stack = [root.rstrip("/")]
        seen = 0
        while stack and seen < budget:
            d = stack.pop()
            try:
                entries = ufs.list_status(d) or []
            except Exception:  # noqa: BLE001 racing deletes
                LOG.debug("UfsCleaner list of %s failed", d, exc_info=True)
                continue
            for st in entries:
                if seen >= budget:
                    LOG.debug("UfsCleaner tick budget exhausted at %s", d)
                    break
                seen += 1
                path = f"{d}/{st.name}"
                if st.is_directory:
                    stack.append(path)
                elif is_infra_temp(st.name):
                    age = now_ms - (st.last_modified_ms or 0)
                    if age > self._ttl_ms:
                        try:
                            if ufs.delete_file(path):
                                removed += 1
                                LOG.info("UfsCleaner removed abandoned "
                                         "persist temp %s", path)
                        except Exception:  # noqa: BLE001 next tick
                            LOG.debug("temp delete failed: %s", path,
                                      exc_info=True)
        return removed
