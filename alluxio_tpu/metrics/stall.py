"""Input-doctor vocabulary shared across layers.

The serving-tier buckets every block read is attributed to, and the
operator advice keyed by the top-ranked stall bucket. Lives here (not in
the jax client) so the shell and web surfaces can rank a stall report
without importing the device-loader stack.
"""

from __future__ import annotations

#: serving-tier buckets the input doctor attributes waits to:
#: hbm (device-resident hit), shm (same-host /dev/shm mmap ~= DRAM),
#: remote (cached on a remote worker), ufs (cold read-through)
STALL_BUCKETS = ("hbm", "shm", "remote", "ufs", "unknown")

#: op-size buckets shared by the read-latency histograms
#: (``Client.ReadLatency.*``) and the per-size stall columns of the
#: input doctor — small-read stalls (per-op RPC overhead) must be
#: distinguishable from stripe-sized ones (bandwidth)
SIZE_BUCKETS = ("le4k", "le64k", "le1m", "gt1m")


def size_bucket(nbytes: int) -> str:
    """The op-size bucket a read of ``nbytes`` falls in."""
    if nbytes <= 4 << 10:
        return "le4k"
    if nbytes <= 64 << 10:
        return "le64k"
    if nbytes <= 1 << 20:
        return "le1m"
    return "gt1m"


#: per-bucket operator hint, ranked bottleneck -> what to turn
BUCKET_ADVICE = {
    "ufs": "cold UFS reads dominate — warm the cache or enable "
           "clairvoyant prefetch (atpu.prefetch.*)",
    "remote": "remote-worker reads dominate — co-locate the client "
              "with its workers or raise replication",
    "shm": "short-circuit host reads dominate — raise HBM retention "
           "(hbm_bytes) or loader prefetch depth",
    "hbm": "waits are HBM-resident hits — the input path keeps up; "
           "the job is compute-bound",
    "unknown": "waits could not be attributed — check worker version "
               "(source tagging) and loader wiring",
}
