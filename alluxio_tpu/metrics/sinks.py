"""Metrics sinks: periodic export of registry snapshots.

Re-design of ``core/common/src/main/java/alluxio/metrics/sink/
{Sink,ConsoleSink,CsvSink,GraphiteSink,Slf4jSink}.java`` (JMX has no
environment analogue here; the JSON-lines sink is the modern structured
equivalent): a sink receives the flat snapshot each scheduler tick and
writes it somewhere durable/visible. Sinks are configured by name
(``atpu.metrics.sinks=csv,jsonl,console,graphite``) and driven by one
heartbeat.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional

LOG = logging.getLogger(__name__)


class Sink:
    """SPI (reference: ``metrics/sink/Sink.java``)."""

    def report(self, snapshot: Dict[str, float]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class ConsoleSink(Sink):
    def __init__(self, stream=None) -> None:
        self._stream = stream or sys.stderr

    def report(self, snapshot: Dict[str, float]) -> None:
        ts = time.strftime("%Y-%m-%d %H:%M:%S")
        print(f"-- metrics @ {ts} " + "-" * 40, file=self._stream)
        for name, value in sorted(snapshot.items()):
            print(f"{name} = {value}", file=self._stream)
        self._stream.flush()


class CsvSink(Sink):
    """One CSV file per metric under ``directory``, appending
    ``epoch_seconds,value`` rows (reference: CsvSink's per-metric file
    layout, the format Graphite/pandas ingest directly)."""

    def __init__(self, directory: str) -> None:
        self._dir = directory
        os.makedirs(directory, exist_ok=True)

    def report(self, snapshot: Dict[str, float]) -> None:
        now = int(time.time())
        for name, value in snapshot.items():
            safe = name.replace("/", "_")
            path = os.path.join(self._dir, f"{safe}.csv")
            is_new = not os.path.exists(path)
            try:
                with open(path, "a") as f:
                    if is_new:
                        f.write("t,value\n")
                    f.write(f"{now},{value}\n")
            except OSError:  # disk pressure: skip this tick
                LOG.debug("csv sink write failed for %s", name,
                          exc_info=True)


class JsonLinesSink(Sink):
    """One JSON object per tick appended to ``path`` — the structured
    log shape every modern collector tails."""

    def __init__(self, path: str) -> None:
        self._path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def report(self, snapshot: Dict[str, float]) -> None:
        line = json.dumps({"ts": round(time.time(), 3),
                           "metrics": snapshot}, sort_keys=True)
        try:
            with open(self._path, "a") as f:
                f.write(line + "\n")
        except OSError:
            LOG.debug("jsonl sink write failed", exc_info=True)


class GraphiteSink(Sink):
    """Plaintext Graphite/Carbon protocol (reference:
    ``metrics/sink/GraphiteSink.java``): one ``<prefix>.<name> <value>
    <unix-ts>\\n`` line per metric over TCP. The socket reconnects per
    report tick — Carbon treats connections as cheap and a long-lived
    one would silently die across Carbon restarts.

    The TCP send runs on a dedicated sender thread with a bounded
    connect/send deadline: ``report()`` only enqueues, so a dead carbon
    host can never stall the shared sink heartbeat (which would starve
    EVERY other sink for the full connect timeout each tick). The queue
    keeps only the newest pending snapshot — under backpressure stale
    ticks are dropped, latest wins."""

    def __init__(self, host: str, port: int,
                 prefix: str = "alluxio-tpu",
                 timeout_s: float = 5.0) -> None:
        import queue

        self._host = host
        self._port = port
        self._prefix = prefix.rstrip(".")
        self._timeout_s = timeout_s
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        self._sender = threading.Thread(target=self._run, daemon=True,
                                        name="graphite-sink")
        self._sender.start()

    @staticmethod
    def _sanitize(name: str) -> str:
        # Graphite path segments must not contain spaces; dots are
        # hierarchy separators and kept as-is
        return name.replace(" ", "_")

    def report(self, snapshot: Dict[str, float]) -> None:
        import queue

        ts = int(time.time())
        lines = [f"{self._prefix}.{self._sanitize(n)} {v} {ts}\n"
                 for n, v in sorted(snapshot.items())
                 if isinstance(v, (int, float))]
        if not lines:
            return
        payload = "".join(lines).encode()
        while True:
            try:
                self._queue.put_nowait(payload)
                return
            except queue.Full:  # sender wedged on a dead host
                try:
                    self._queue.get_nowait()
                    LOG.debug("graphite sink backlogged; dropped one "
                              "stale snapshot")
                except queue.Empty:
                    pass

    def _run(self) -> None:
        import socket

        while True:
            payload = self._queue.get()
            if payload is None:
                return
            try:
                with socket.create_connection(
                        (self._host, self._port),
                        timeout=self._timeout_s) as s:
                    s.sendall(payload)
            except OSError:
                LOG.warning("graphite sink send to %s:%s failed",
                            self._host, self._port, exc_info=True)

    def close(self) -> None:
        import queue

        # same drop-oldest discipline as report(): a wedged sender must
        # not let close() block behind a full queue
        while True:
            try:
                self._queue.put_nowait(None)
                break
            except queue.Full:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
        self._sender.join(timeout=self._timeout_s + 1.0)


class SinkManager:
    """Builds sinks from config and reports on a heartbeat tick
    (reference: MetricsSystem's sink scheduling)."""

    def __init__(self, conf, registry) -> None:
        from alluxio_tpu.conf import Keys

        self._registry = registry
        self.sinks: List[Sink] = []
        names = [s.strip() for s in
                 (conf.get(Keys.METRICS_SINKS) or "").split(",")
                 if s.strip()]
        # the host-global DEFAULT paths get a per-process namespace:
        # two processes appending the same file would interleave rows
        # and race the CSV header; an EXPLICITLY configured path is the
        # operator's call and is honored verbatim
        me = f"{registry.instance.lower()}-{os.getpid()}"
        for name in names:
            if name == "console":
                self.sinks.append(ConsoleSink())
            elif name == "csv":
                d = conf.get(Keys.METRICS_SINK_CSV_DIR)
                if d == Keys.METRICS_SINK_CSV_DIR.default:
                    d = os.path.join(d, me)
                self.sinks.append(CsvSink(d))
            elif name == "jsonl":
                p = conf.get(Keys.METRICS_SINK_JSONL_PATH)
                if p == Keys.METRICS_SINK_JSONL_PATH.default:
                    root, ext = os.path.splitext(p)
                    p = f"{root}.{me}{ext}"
                self.sinks.append(JsonLinesSink(p))
            elif name == "graphite":
                addr = conf.get(Keys.METRICS_SINK_GRAPHITE_ADDRESS)
                if not addr:
                    LOG.warning("graphite sink configured without "
                                "atpu.metrics.sink.graphite.address")
                    continue
                host, sep, port = addr.rpartition(":")
                if not sep or not host or not port.isdigit():
                    # a malformed address must fail LOUDLY: silently
                    # defaulting host/port would ship metrics to the
                    # wrong place while the operator believes they
                    # configured carbon
                    LOG.warning("graphite sink skipped: address %r is "
                                "not host:port", addr)
                    continue
                self.sinks.append(GraphiteSink(
                    host, int(port),
                    prefix=conf.get(
                        Keys.METRICS_SINK_GRAPHITE_PREFIX),
                    timeout_s=conf.get_duration_s(
                        Keys.METRICS_SINK_GRAPHITE_TIMEOUT)))
            else:
                LOG.warning("unknown metrics sink %r (known: console, "
                            "csv, jsonl, graphite)", name)

    def heartbeat(self) -> None:
        if not self.sinks:
            return
        snapshot = self._registry.snapshot()
        for sink in self.sinks:
            try:
                sink.report(snapshot)
            except Exception:  # noqa: BLE001 one sink must not kill others
                LOG.warning("metrics sink %s failed",
                            type(sink).__name__, exc_info=True)

    def close(self) -> None:
        for sink in self.sinks:
            try:
                sink.close()
            except Exception:  # noqa: BLE001
                pass
