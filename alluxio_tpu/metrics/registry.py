"""Metrics system.

Re-design of the reference's Dropwizard-based ``metrics/MetricsSystem.java:63``
+ ``metrics/MetricKey.java``: a process-wide registry of counters, gauges,
meters and timers with instance-prefixed names
(``Master.FilesCreated``, ``Worker.BytesReadLocal``, ``Client...``), a
Prometheus text exposition (reference: ``PrometheusMetricsServlet.java``),
and snapshot/aggregation support so workers and clients can ship their
metrics to the master for cluster-level aggregation
(reference: ``master/metrics/DefaultMetricsMaster.java``).
"""

from __future__ import annotations

import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)

    @property
    def count(self) -> int:
        with self._lock:
            return self._value


class Meter:
    """Rate meter: counts events, reports 1-minute-window rate."""

    __slots__ = ("_count", "_window", "_lock")

    def __init__(self) -> None:
        self._count = 0
        self._window: deque = deque()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            self._count += n
            self._window.append((now, n))
            self._trim(now)

    def _trim(self, now: float) -> None:
        while self._window and now - self._window[0][0] > 60.0:
            self._window.popleft()

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def one_minute_rate(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            total = sum(n for _, n in self._window)
            return total / 60.0


class Timer:
    """Latency histogram (reservoir of recent samples) + throughput count."""

    #: classic Prometheus latency bucket bounds (seconds); lifetime
    #: cumulative counts are kept per bound so the exposition series
    #: stay monotonic across scrapes (a sliding-reservoir histogram
    #: would DECREASE when samples age out — PromQL reads that as a
    #: counter reset and inflates every rate()/quantile)
    HISTOGRAM_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                         1.0, 2.5, 5.0, 10.0)

    def __init__(self, reservoir: int = 1028) -> None:
        self._samples: deque = deque(maxlen=reservoir)
        self._count = 0
        self._total_s = 0.0
        self._bucket_counts = [0] * len(self.HISTOGRAM_BUCKETS)
        # bucket index -> (trace_id, observed seconds, unix ts): the most
        # recent sampled trace that landed in that bucket, so the
        # exposition can link slow buckets straight to a trace
        self._exemplars: Dict[int, "tuple[str, float, float]"] = {}
        self._lock = threading.Lock()

    def update(self, seconds: float, exemplar: Optional[str] = None) -> None:
        with self._lock:
            self._count += 1
            self._total_s += seconds
            self._samples.append(seconds)
            idx = len(self.HISTOGRAM_BUCKETS)  # +Inf
            for i, le in enumerate(self.HISTOGRAM_BUCKETS):
                if seconds <= le:
                    self._bucket_counts[i] += 1
                    idx = min(idx, i)
            if exemplar is not None:
                self._exemplars[idx] = (exemplar, seconds, time.time())

    class _Ctx:
        def __init__(self, timer: "Timer") -> None:
            self._timer = timer

        def __enter__(self):
            self._t0 = time.monotonic()
            return self

        def __exit__(self, *exc):
            self._timer.update(time.monotonic() - self._t0)
            return False

    def time(self) -> "_Ctx":
        return Timer._Ctx(self)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, p: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
        idx = min(len(s) - 1, int(p / 100.0 * len(s)))
        return s[idx]

    def snapshot(self) -> Dict[str, float]:
        # ONE locked copy of (samples, count, total): reading _total_s /
        # _count piecemeal outside the lock tore the mean under a
        # concurrent update() (count incremented between the two reads)
        with self._lock:
            samples = sorted(self._samples)
            count = self._count
            total = self._total_s

        def pct(p: float) -> float:
            if not samples:
                return 0.0
            return samples[min(len(samples) - 1,
                               int(p / 100.0 * len(samples)))]

        return {"count": count, "p50": pct(50), "p95": pct(95),
                "p99": pct(99),
                "mean": (total / count) if count else 0.0}

    def histogram(self) -> "tuple[List[int], float, int]":
        """Lifetime cumulative bucket counts plus (sum, count) — one
        consistent monotonic series for Prometheus exposition."""
        with self._lock:
            counts = list(self._bucket_counts)
            counts.append(self._count)  # +Inf
            return counts, self._total_s, self._count

    def exemplars(self) -> "Dict[int, tuple[str, float, float]]":
        """Bucket index -> (trace_id, seconds, unix_ts); index
        ``len(HISTOGRAM_BUCKETS)`` is the +Inf bucket."""
        with self._lock:
            return dict(self._exemplars)


class MetricsRegistry:
    def __init__(self, instance: str = "Process") -> None:
        self.instance = instance
        self._counters: Dict[str, Counter] = {}
        self._meters: Dict[str, Meter] = {}
        self._timers: Dict[str, Timer] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def _name(self, name: str) -> str:
        return name if "." in name and name.split(".", 1)[0] in (
            "Master", "Worker", "Client", "JobMaster", "JobWorker", "Cluster",
            "Process") else f"{self.instance}.{name}"

    def counter(self, name: str) -> Counter:
        name = self._name(name)
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def meter(self, name: str) -> Meter:
        name = self._name(name)
        with self._lock:
            return self._meters.setdefault(name, Meter())

    def timer(self, name: str) -> Timer:
        name = self._name(name)
        with self._lock:
            return self._timers.setdefault(name, Timer())

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        name = self._name(name)
        with self._lock:
            self._gauges[name] = fn

    # -- snapshots ----------------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        """Flat name -> value map (counters, meter counts, gauges, timer p50s)."""
        out: Dict[str, float] = {}
        with self._lock:
            counters = dict(self._counters)
            meters = dict(self._meters)
            timers = dict(self._timers)
            gauges = dict(self._gauges)
        for n, c in counters.items():
            out[n] = c.count
        for n, m in meters.items():
            out[n] = m.count
            out[n + ".rate1m"] = m.one_minute_rate
        for n, t in timers.items():
            for k, v in t.snapshot().items():
                out[f"{n}.{k}"] = v
        for n, g in gauges.items():
            try:
                out[n] = float(g())
            except Exception:
                pass
        return out

    @staticmethod
    def _prom_name(name: str) -> str:
        """Exposition-legal metric name: ``[a-zA-Z_:][a-zA-Z0-9_:]*``."""
        metric = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
        if metric and metric[0].isdigit():
            metric = "_" + metric
        return metric

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (# HELP/# TYPE preambles,
        ``_total``-suffixed counters, timer histograms with
        bucket/sum/count — what promtool check metrics accepts)."""
        with self._lock:
            counters = dict(self._counters)
            meters = dict(self._meters)
            timers = dict(self._timers)
            gauges = dict(self._gauges)
        lines: List[str] = []

        def emit(name: str, kind: str, help_text: str) -> str:
            metric = self._prom_name(name)
            lines.append(f"# HELP {metric} {help_text}")
            lines.append(f"# TYPE {metric} {kind}")
            return metric

        for name, c in sorted(counters.items()):
            metric = emit(name + "_total", "counter",
                          f"counter {name}")
            lines.append(f"{metric} {c.count}")
        for name, m in sorted(meters.items()):
            metric = emit(name + "_total", "counter", f"meter {name}")
            lines.append(f"{metric} {m.count}")
            metric = emit(name + "_rate1m", "gauge",
                          f"1-minute rate of {name}")
            lines.append(f"{metric} {m.one_minute_rate}")
        for name, g in sorted(gauges.items()):
            try:
                value = float(g())
            except Exception:  # noqa: BLE001 - dead gauge: skip
                continue
            metric = emit(name, "gauge", f"gauge {name}")
            lines.append(f"{metric} {value}")
        for name, t in sorted(timers.items()):
            counts, total, n = t.histogram()
            ex = t.exemplars()
            metric = emit(name + "_seconds", "histogram",
                          f"latency histogram of {name}")

            def bucket_line(le: str, cum: int, idx: int) -> str:
                line = f'{metric}_bucket{{le="{le}"}} {cum}'
                e = ex.get(idx)
                if e is not None:
                    # OpenMetrics exemplar: links the bucket to a
                    # representative trace id for drill-down
                    tid, val, ts = e
                    line += (f' # {{trace_id="{tid}"}}'
                             f" {val:.6f} {ts:.3f}")
                return line

            for i, (le, cum) in enumerate(
                    zip(t.HISTOGRAM_BUCKETS, counts)):
                lines.append(bucket_line(str(le), cum, i))
            lines.append(bucket_line("+Inf", counts[-1],
                                     len(t.HISTOGRAM_BUCKETS)))
            lines.append(f"{metric}_sum {total}")
            lines.append(f"{metric}_count {n}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._meters.clear()
            self._timers.clear()
            self._gauges.clear()


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def metrics(instance: Optional[str] = None) -> MetricsRegistry:
    """Process-default registry (set ``instance`` on first call in a process)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry(instance or "Process")
        elif instance is not None:
            _default.instance = instance
        return _default


def reset_metrics() -> None:
    global _default
    with _default_lock:
        _default = None
