"""Metrics system.

Re-design of the reference's Dropwizard-based ``metrics/MetricsSystem.java:63``
+ ``metrics/MetricKey.java``: a process-wide registry of counters, gauges,
meters and timers with instance-prefixed names
(``Master.FilesCreated``, ``Worker.BytesReadLocal``, ``Client...``), a
Prometheus text exposition (reference: ``PrometheusMetricsServlet.java``),
and snapshot/aggregation support so workers and clients can ship their
metrics to the master for cluster-level aggregation
(reference: ``master/metrics/DefaultMetricsMaster.java``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional


class Counter:
    __slots__ = ("_value", "_lock")

    def __init__(self) -> None:
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: int = 1) -> None:
        self.inc(-n)

    @property
    def count(self) -> int:
        with self._lock:
            return self._value


class Meter:
    """Rate meter: counts events, reports 1-minute-window rate."""

    __slots__ = ("_count", "_window", "_lock")

    def __init__(self) -> None:
        self._count = 0
        self._window: deque = deque()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        now = time.monotonic()
        with self._lock:
            self._count += n
            self._window.append((now, n))
            self._trim(now)

    def _trim(self, now: float) -> None:
        while self._window and now - self._window[0][0] > 60.0:
            self._window.popleft()

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def one_minute_rate(self) -> float:
        now = time.monotonic()
        with self._lock:
            self._trim(now)
            total = sum(n for _, n in self._window)
            return total / 60.0


class Timer:
    """Latency histogram (reservoir of recent samples) + throughput count."""

    def __init__(self, reservoir: int = 1028) -> None:
        self._samples: deque = deque(maxlen=reservoir)
        self._count = 0
        self._total_s = 0.0
        self._lock = threading.Lock()

    def update(self, seconds: float) -> None:
        with self._lock:
            self._count += 1
            self._total_s += seconds
            self._samples.append(seconds)

    class _Ctx:
        def __init__(self, timer: "Timer") -> None:
            self._timer = timer

        def __enter__(self):
            self._t0 = time.monotonic()
            return self

        def __exit__(self, *exc):
            self._timer.update(time.monotonic() - self._t0)
            return False

    def time(self) -> "_Ctx":
        return Timer._Ctx(self)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, p: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
        idx = min(len(s) - 1, int(p / 100.0 * len(s)))
        return s[idx]

    def snapshot(self) -> Dict[str, float]:
        return {"count": self.count, "p50": self.percentile(50),
                "p95": self.percentile(95), "p99": self.percentile(99),
                "mean": (self._total_s / self._count) if self._count else 0.0}


class MetricsRegistry:
    def __init__(self, instance: str = "Process") -> None:
        self.instance = instance
        self._counters: Dict[str, Counter] = {}
        self._meters: Dict[str, Meter] = {}
        self._timers: Dict[str, Timer] = {}
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._lock = threading.Lock()

    def _name(self, name: str) -> str:
        return name if "." in name and name.split(".", 1)[0] in (
            "Master", "Worker", "Client", "JobMaster", "JobWorker", "Cluster",
            "Process") else f"{self.instance}.{name}"

    def counter(self, name: str) -> Counter:
        name = self._name(name)
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def meter(self, name: str) -> Meter:
        name = self._name(name)
        with self._lock:
            return self._meters.setdefault(name, Meter())

    def timer(self, name: str) -> Timer:
        name = self._name(name)
        with self._lock:
            return self._timers.setdefault(name, Timer())

    def register_gauge(self, name: str, fn: Callable[[], float]) -> None:
        name = self._name(name)
        with self._lock:
            self._gauges[name] = fn

    # -- snapshots ----------------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        """Flat name -> value map (counters, meter counts, gauges, timer p50s)."""
        out: Dict[str, float] = {}
        with self._lock:
            counters = dict(self._counters)
            meters = dict(self._meters)
            timers = dict(self._timers)
            gauges = dict(self._gauges)
        for n, c in counters.items():
            out[n] = c.count
        for n, m in meters.items():
            out[n] = m.count
            out[n + ".rate1m"] = m.one_minute_rate
        for n, t in timers.items():
            for k, v in t.snapshot().items():
                out[f"{n}.{k}"] = v
        for n, g in gauges.items():
            try:
                out[n] = float(g())
            except Exception:
                pass
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        for name, value in sorted(self.snapshot().items()):
            metric = name.replace(".", "_").replace("-", "_")
            lines.append(f"{metric} {value}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._meters.clear()
            self._timers.clear()
            self._gauges.clear()


class ClusterAggregator:
    """Aggregates metric snapshots reported by workers/clients into
    ``Cluster.*`` metrics (reference: ``MetricsStore`` +
    ``DefaultMetricsMaster``)."""

    def __init__(self) -> None:
        self._reports: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()

    def report(self, source_id: str, snapshot: Dict[str, float]) -> None:
        with self._lock:
            self._reports[source_id] = dict(snapshot)

    def clear_source(self, source_id: str) -> None:
        with self._lock:
            self._reports.pop(source_id, None)

    def cluster_snapshot(self) -> Dict[str, float]:
        agg: Dict[str, float] = {}
        with self._lock:
            reports = [dict(r) for r in self._reports.values()]
        for snap in reports:
            for name, value in snap.items():
                if name.endswith(".p50") or name.endswith(".p95") or \
                        name.endswith(".p99") or name.endswith(".mean"):
                    continue
                key = "Cluster." + name.split(".", 1)[-1]
                agg[key] = agg.get(key, 0.0) + value
        return agg


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def metrics(instance: Optional[str] = None) -> MetricsRegistry:
    """Process-default registry (set ``instance`` on first call in a process)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry(instance or "Process")
        elif instance is not None:
            _default.instance = instance
        return _default


def reset_metrics() -> None:
    global _default
    with _default_lock:
        _default = None
