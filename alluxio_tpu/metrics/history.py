"""Bounded metrics-history store: the time dimension of observability.

The master's :class:`~alluxio_tpu.master.metrics_master.MetricsStore`
keeps only the *latest* snapshot per source, so "when did the stall
fraction spike" / "is the hit ratio degrading" are unanswerable.  This
module keeps per-``(source, metric)`` rings of ``(ts, value)`` samples
fed from the existing metrics heartbeat, with tiered downsampling
(raw -> 1m -> 10m rollups), counter->rate derivation at query time, and
hard memory bounds (capacity per ring, a series cap, and a name-prefix
allowlist against cardinality floods).

Ingestion is **two-phase** so the heartbeat RPC path stays O(1): the
handler calls :meth:`MetricsHistory.offer` (one deque append — the
snapshot dict is reused, never copied), and the actual ring/rollup work
happens in :meth:`drain`, invoked from the master's health heartbeat
and from every query surface.  ``make bench-health`` gates the offer
path at <5% heartbeat-handling overhead.

Reference vocabulary: the Java master's ``MetricsTimeSeriesStore`` kept
a small fixed set of cluster series; this store generalizes it to every
allowlisted metric, per source, because time-resolved per-tier
telemetry is what diagnosing DL input pipelines actually needs
(arXiv:2301.01494).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

#: rollup tiers: (label, bucket width seconds, retention multiple of
#: the raw retention) — coarser tiers survive longer so a day of 10m
#: buckets outlives an hour of raw points under the same capacity cap
ROLLUPS: Tuple[Tuple[str, float, float], ...] = (
    ("1m", 60.0, 10.0),
    ("10m", 600.0, 60.0),
)

RESOLUTIONS = ("raw",) + tuple(label for label, _, _ in ROLLUPS)


class _Bucket:
    """One rollup bucket: running count/sum/min/max plus the last raw
    value (the counter-rate path reads ``last``, the gauge path reads
    ``mean``)."""

    __slots__ = ("start", "count", "sum", "min", "max", "last")

    def __init__(self, start: float, value: float) -> None:
        self.start = start
        self.count = 1
        self.sum = value
        self.min = value
        self.max = value
        self.last = value

    def add(self, value: float) -> None:
        self.count += 1
        self.sum += value
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        self.last = value

    def to_dict(self) -> dict:
        return {"ts": self.start, "count": self.count,
                "sum": self.sum, "mean": self.sum / self.count,
                "min": self.min, "max": self.max, "last": self.last}


class _Series:
    """One (source, metric) series.  The raw ring is a pair of packed
    ``array('d')`` circular buffers, NOT a deque of tuples: at the
    cardinality this store is bounded for (thousands of series x
    hundreds of points) per-point tuple objects made every Python GC
    pass measurably slower — which billed the history's cost to the
    heartbeat hot path.  Packed doubles are invisible to the GC and 4x
    smaller."""

    __slots__ = ("_ts", "_v", "_head", "_n", "_cap", "rollups",
                 "ended_at", "last_ts")

    def __init__(self, capacity: int) -> None:
        from array import array

        self._cap = capacity
        self._ts = array("d")
        self._v = array("d")
        self._head = 0  # index of the oldest live sample
        self._n = 0     # live sample count
        self.rollups: Dict[str, deque] = {
            label: deque(maxlen=capacity) for label, _, _ in ROLLUPS}
        #: set when the source was declared dead (worker lost) — an
        #: explicit end marker instead of silent staleness; cleared
        #: only by revive_source (block-master re-registration), never
        #: by metrics arrival: a lost worker whose metrics heartbeat
        #: outlives its block-sync thread is still dead to the cluster
        self.ended_at: Optional[float] = None
        #: newest sample timestamp ever ingested — drives reclamation
        #: of series whose source silently vanished (clients have no
        #: lost-worker event, so idleness is their only death signal)
        self.last_ts = 0.0

    def add(self, ts: float, value: float) -> None:
        if ts > self.last_ts:
            self.last_ts = ts
        if len(self._ts) < self._cap:
            # growing phase: the ring has never wrapped, so appending
            # keeps time order even after left-prunes advanced head
            self._ts.append(ts)
            self._v.append(value)
            self._n += 1
        else:
            i = (self._head + self._n) % self._cap
            self._ts[i] = ts
            self._v[i] = value
            if self._n == self._cap:
                self._head = (self._head + 1) % self._cap
            else:
                self._n += 1
        for label, width, _ in ROLLUPS:
            ring = self.rollups[label]
            start = ts - (ts % width)
            if ring and ring[-1].start == start:
                ring[-1].add(value)
            elif ring and ring[-1].start > start:
                pass  # out-of-order past a bucket boundary: drop
            else:
                ring.append(_Bucket(start, value))

    def raw_points(self) -> List[Tuple[float, float]]:
        """Live samples oldest-first as (ts, value) pairs."""
        ts, v, head, n = self._ts, self._v, self._head, self._n
        size = len(ts)
        if n == 0:
            return []
        if head + n <= size:
            return list(zip(ts[head:head + n], v[head:head + n]))
        k = size - head
        return list(zip(ts[head:], v[head:])) + \
            list(zip(ts[:n - k], v[:n - k]))

    def raw_len(self) -> int:
        return self._n

    def prune(self, now: float, retention_s: float) -> None:
        size = len(self._ts)
        while self._n and now - self._ts[self._head] > retention_s:
            self._head = (self._head + 1) % size if size == self._cap \
                else self._head + 1
            self._n -= 1
        for label, _, keep_mult in ROLLUPS:
            ring = self.rollups[label]
            horizon = retention_s * keep_mult
            while ring and now - ring[0].start > horizon:
                ring.popleft()

    def points(self) -> int:
        return self._n + sum(len(r) for r in self.rollups.values())


def derive_rate(points: List[Tuple[float, float]]
                ) -> List[Tuple[float, float]]:
    """Counter series -> per-second rate between consecutive samples.
    A negative delta is a counter reset (process restart): clamp to 0
    rather than emitting a huge negative spike."""
    out: List[Tuple[float, float]] = []
    for (t0, v0), (t1, v1) in zip(points, points[1:]):
        dt = t1 - t0
        if dt <= 0:
            continue
        out.append((t1, max(0.0, v1 - v0) / dt))
    return out


class MetricsHistory:
    """Bounded per-(source, metric) time series with tiered rollups."""

    def __init__(self, *, capacity: int = 360,
                 retention_s: float = 3600.0,
                 max_series: int = 4096,
                 allow_prefixes: Tuple[str, ...] = (
                     "Cluster.", "Master.", "Worker.", "Client.",
                     "JobMaster.", "JobWorker.", "Process."),
                 pending_max: int = 1024,
                 clock: Callable[[], float] = time.time) -> None:
        self.capacity = max(2, int(capacity))
        self.retention_s = float(retention_s)
        self.max_series = max(1, int(max_series))
        self.allow_prefixes = tuple(allow_prefixes)
        self._clock = clock
        self._series: Dict[Tuple[str, str], _Series] = {}
        self._lock = threading.Lock()
        #: heartbeat hot path appends here (O(1), no lock beyond the
        #: deque's own); drain() does the real work off the RPC path
        self._pending: deque = deque()
        self._pending_max = max(1, int(pending_max))
        self._pending_lock = threading.Lock()
        self.dropped_samples = 0  # series-cap / allowlist rejections
        self.dropped_ticks = 0    # pending-queue overflow
        self._last_prune = 0.0
        self._evict_scan_ts = float("-inf")
        #: source -> end-marker ts (worker declared lost); cleared only
        #: by revive_source on block-master re-registration (NOT by
        #: metrics arrival — a worker whose metrics heartbeat outlives
        #: its wedged block-sync thread is lost-but-chatty and must
        #: keep alerting), aged out with retention — feeds the
        #: worker-lost health rule so a death outlives the TTL'd
        #: snapshot instead of silently resolving back to OK
        self._ended_sources: Dict[str, float] = {}

    # ------------------------------------------------------------ ingest
    def offer(self, source: str, metrics: Dict[str, float],
              now: Optional[float] = None) -> None:
        """O(1) hand-off from the heartbeat path.  The caller's dict is
        referenced, not copied — heartbeat snapshots are never mutated
        after shipping.  Kept to two attribute loads + one append: this
        is the only history cost the RPC path pays (bench-health gates
        it at <5% of heartbeat handling)."""
        pending = self._pending
        pending.append((source, metrics,
                        self._clock() if now is None else now))
        # bound enforced append-then-trim so the common path stays
        # lock-free (deque ops are atomic): only the rare overflow
        # path locks, and every evicted tick is counted — a maxlen
        # deque would evict silently under concurrent offers
        if len(pending) > self._pending_max:
            with self._pending_lock:
                try:
                    pending.popleft()
                except IndexError:
                    pass  # drain emptied it under us
                else:
                    self.dropped_ticks += 1

    def drain(self) -> int:
        """Fold every pending heartbeat into the rings; returns samples
        ingested.  Runs on the health heartbeat and on query paths —
        never on the RPC hot path."""
        ingested = 0
        while True:
            try:
                source, metrics, ts = self._pending.popleft()
            except IndexError:
                break
            ingested += self.ingest(source, metrics, now=ts)
        return ingested

    def ingest(self, source: str, metrics: Dict[str, float],
               now: Optional[float] = None) -> int:
        """Synchronous ingestion (drain path and tests)."""
        ts = self._clock() if now is None else now
        allow = self.allow_prefixes
        n = 0
        with self._lock:
            series_map = self._series
            for name, value in metrics.items():
                if allow and not name.startswith(allow):
                    self.dropped_samples += 1
                    continue
                key = (source, name)
                s = series_map.get(key)
                if s is None:
                    if len(series_map) >= self.max_series and \
                            not self._evict_one(ts):
                        self.dropped_samples += 1
                        continue
                    s = series_map[key] = _Series(self.capacity)
                    ended = self._ended_sources.get(source)
                    if ended is not None:
                        # a series minted for an already-ended source
                        # (new metric name from a lost-but-chatty
                        # worker, or recreated after the sweep) must
                        # carry the marker, not read as live
                        s.ended_at = ended
                try:
                    s.add(ts, float(value))
                except (TypeError, ValueError):
                    continue
                n += 1
            # amortized retention sweep: at most once per minute of
            # series time, so drain cost stays O(new samples)
            if ts - self._last_prune >= 60.0:
                self._last_prune = ts
                dead = []
                for key, s in series_map.items():
                    s.prune(ts, self.retention_s)
                    if s.points() == 0 or self._departed(s, ts):
                        dead.append(key)
                for key in dead:
                    del series_map[key]
                self._ended_sources = {
                    s: t for s, t in self._ended_sources.items()
                    if ts - t <= self.retention_s}
        return n

    def _departed(self, s: _Series, now: float) -> bool:
        """A series whose source is gone must release its slot long
        before its 10m rollups would expire (retention x 60 — 60 hours
        at defaults), or a parade of short-lived client sources pins
        the whole ``max_series`` budget on dead data.  Gone means:
        explicitly ended (worker lost) for a full raw retention, or —
        for clients, which have no lost event — idle for two."""
        if s.ended_at is not None and now - s.ended_at > self.retention_s:
            return True
        return now - s.last_ts > 2.0 * self.retention_s

    def _evict_one(self, now: float) -> bool:
        """Series-cap pressure: evict the stalest ended-or-idle series
        so dead sources never lock live ones out between retention
        sweeps.  Caller holds ``_lock``.  A fruitless scan is cached
        for a few seconds of series time so a cardinality flood of
        live allowlisted names costs O(1) per rejected sample, not an
        O(series) sweep each."""
        if now - self._evict_scan_ts < 5.0:
            return False
        victim = None
        victim_ts = now
        for key, s in self._series.items():
            if s.ended_at is None and now - s.last_ts <= self.retention_s:
                continue
            if s.last_ts < victim_ts:
                victim_ts = s.last_ts
                victim = key
        if victim is None:
            self._evict_scan_ts = now
            return False
        del self._series[victim]
        return True

    def end_source(self, source: str,
                   now: Optional[float] = None) -> int:
        """Mark every series of ``source`` ended (worker declared lost):
        queries show ``ended_at`` instead of silently-stale points.
        Only :meth:`revive_source` (block-master re-registration)
        clears the marker — metrics arrival does not, so a worker whose
        metrics heartbeat outlives its wedged block-sync thread keeps
        the worker-lost alert firing instead of laundering itself back
        to OK."""
        ts = self._clock() if now is None else now
        n = 0
        with self._lock:
            self._ended_sources[source] = ts
            for (src, _name), s in self._series.items():
                if src == source:
                    s.ended_at = ts
                    n += 1
        return n

    def revive_source(self, source: str) -> int:
        """Clear ``source``'s end marker: the worker completed a full
        block-master re-registration, the one signal that it is
        genuinely back serving blocks."""
        n = 0
        with self._lock:
            self._ended_sources.pop(source, None)
            for (src, _name), s in self._series.items():
                if src == source and s.ended_at is not None:
                    s.ended_at = None
                    n += 1
        return n

    def ended_sources(self, now: Optional[float] = None) -> Dict[str, float]:
        """Sources explicitly end-marked (worker lost) and not since
        revived, with their end timestamps; entries age out after
        ``retention_s`` — the worker-lost health alert's lifetime."""
        ts = self._clock() if now is None else now
        with self._lock:
            return {s: t for s, t in self._ended_sources.items()
                    if ts - t <= self.retention_s}

    # ------------------------------------------------------------- query
    def query(self, name: str, *, source: str = "",
              resolution: str = "raw", since: float = 0.0,
              rate: bool = False, limit: int = 0) -> List[dict]:
        """Series matching ``name`` (and ``source`` when given), one
        dict per (source, metric): raw points as ``[ts, value]`` pairs,
        rollups as bucket dicts; ``rate=True`` derives a per-second
        rate from consecutive values (counter resets clamp to 0)."""
        if resolution not in RESOLUTIONS:
            raise ValueError(
                f"resolution must be one of {RESOLUTIONS}, "
                f"got {resolution!r}")
        out: List[dict] = []
        with self._lock:
            for (src, metric), s in self._series.items():
                if metric != name or (source and src != source):
                    continue
                if resolution == "raw":
                    pts = [(t, v) for t, v in s.raw_points()
                           if t >= since]
                else:
                    pts = [b.to_dict() for b in s.rollups[resolution]
                           if b.start >= since]
                entry = {"source": src, "name": metric,
                         "resolution": resolution,
                         "ended_at": s.ended_at}
                if rate:
                    base = pts if resolution == "raw" else \
                        [(b["ts"], b["last"]) for b in pts]
                    entry["points"] = [list(p) for p in derive_rate(base)]
                    entry["rate"] = True
                elif resolution == "raw":
                    entry["points"] = [list(p) for p in pts]
                else:
                    entry["points"] = pts
                if limit and len(entry["points"]) > limit:
                    entry["points"] = entry["points"][-limit:]
                out.append(entry)
        out.sort(key=lambda e: e["source"])
        return out

    def latest(self, name: str, source: str) -> Optional[float]:
        with self._lock:
            s = self._series.get((source, name))
            if s is None or not s.raw_len():
                return None
            return s._v[(s._head + s._n - 1) % len(s._v)]

    def window(self, name: str, source: str,
               window_s: float, now: Optional[float] = None
               ) -> List[Tuple[float, float]]:
        """Raw points of one series inside ``[now - window_s, now]``."""
        ts = self._clock() if now is None else now
        with self._lock:
            s = self._series.get((source, name))
            if s is None:
                return []
            return [(t, v) for t, v in s.raw_points()
                    if ts - t <= window_s]

    def names(self, prefix: str = "") -> List[str]:
        with self._lock:
            seen = {metric for (_src, metric) in self._series}
        return sorted(n for n in seen if n.startswith(prefix))

    def sources_for(self, name: str) -> List[str]:
        with self._lock:
            return sorted(src for (src, metric) in self._series
                          if metric == name)

    # ------------------------------------------------------------- admin
    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def stats(self) -> dict:
        with self._lock:
            points = sum(s.points() for s in self._series.values())
            n = len(self._series)
        return {"series": n, "points": points,
                "max_series": self.max_series,
                "capacity": self.capacity,
                "retention_s": self.retention_s,
                "pending": len(self._pending),
                "dropped_samples": self.dropped_samples,
                "dropped_ticks": self.dropped_ticks}
