"""Metrics (reference: ``core/common/.../metrics``).

Cluster-level aggregation lives in ``master/metrics_master.py``
(``MetricsStore``) — the one authoritative implementation; the old
``ClusterAggregator`` duplicate is gone.
"""

from alluxio_tpu.metrics.registry import (  # noqa: F401
    Counter, Meter, MetricsRegistry, Timer, metrics, reset_metrics,
)
