"""Metrics (reference: ``core/common/.../metrics``)."""

from alluxio_tpu.metrics.registry import (  # noqa: F401
    ClusterAggregator, Counter, Meter, MetricsRegistry, Timer, metrics,
    reset_metrics,
)
