"""AWS Glue catalog under-database.

Re-design of ``table/server/underdb/glue/src/main/java/alluxio/table/
under/glue/GlueDatabase.java:72`` (+ ``GlueUtils``): snapshot a Glue
database's tables/partitions into the journaled catalog. Differences
from the reference, on purpose:

* The Glue client is a ~100-line AWS JSON-1.1 REST client signed with
  the repo's own SigV4 signer (``underfs/s3.py``) instead of the AWS
  SDK — the protocol is one POST per operation with an
  ``X-Amz-Target: AWSGlue.<Op>`` header.
* Path translation rides the same ``PathTranslator`` as the Hive UDB
  (reference ``PathTranslator.java``) so table locations map onto the
  caching data plane via the mount table.

Attach options (reference ``Property.java:249-254`` names kept):
  aws.region       Glue region (required unless glue.endpoint set)
  aws.catalog.id   optional catalog id (cross-account catalogs)
  aws.accesskey    access key (defaults to env AWS_ACCESS_KEY_ID)
  aws.secretkey    secret key (defaults to env AWS_SECRET_ACCESS_KEY)
  glue.endpoint    endpoint override (fake servers / VPC endpoints)
  path_translations  "ufs1=/ns1,ufs2=/ns2" explicit overrides
"""

from __future__ import annotations

import hashlib
import json
import os
import urllib.error
import urllib.request
from typing import Dict, Iterator, List, Optional

from alluxio_tpu.table.hive import PathTranslator, mount_translations
from alluxio_tpu.table.udb import UdbTable, UnderDatabase
from alluxio_tpu.utils.exceptions import NotFoundError, UnavailableError
from alluxio_tpu.utils.httperr import error_body


class GlueClient:
    """Minimal AWS JSON-1.1 client for the five catalog-snapshot calls
    (reference: the AWSGlue SDK usage in ``GlueDatabase.java``)."""

    def __init__(self, *, region: str, access_key: str = "",
                 secret_key: str = "", endpoint: str = "",
                 catalog_id: str = "", timeout_s: float = 30.0) -> None:
        if not endpoint:
            if not region:
                raise ValueError("glue udb needs aws.region "
                                 "(or glue.endpoint)")
            endpoint = f"https://glue.{region}.amazonaws.com"
        self._endpoint = endpoint.rstrip("/")
        self._catalog_id = catalog_id
        self._timeout = timeout_s
        self._signer = None
        if access_key and secret_key:
            from alluxio_tpu.underfs.s3 import SigV4Signer

            self._signer = SigV4Signer(access_key, secret_key,
                                       region or "us-east-1",
                                       service="glue")

    def _post(self, op: str, body: dict) -> dict:
        if self._catalog_id:
            body = {"CatalogId": self._catalog_id, **body}
        payload = json.dumps(body).encode()
        headers = {
            "Content-Type": "application/x-amz-json-1.1",
            "X-Amz-Target": f"AWSGlue.{op}",
        }
        if self._signer is not None:
            headers = self._signer.sign(
                "POST", self._endpoint + "/", headers,
                hashlib.sha256(payload).hexdigest())
        req = urllib.request.Request(self._endpoint + "/", data=payload,
                                     headers=headers, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                return json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            # parse-sensitive: Glue signals EntityNotFound as HTTP 400
            # with the type in the body — read it whole, truncate only
            # what goes into the message
            full = error_body(e, limit=1 << 20)
            detail = full[:400]
            try:
                err_type = json.loads(full).get("__type", "")
            except ValueError:
                err_type = ""
            if "EntityNotFoundException" in err_type or e.code == 404:
                raise NotFoundError(f"glue {op}: {detail}") from None
            raise UnavailableError(
                f"glue {op}: HTTP {e.code} {detail}") from None
        except OSError as e:
            raise UnavailableError(f"glue {op}: {e}") from None

    def _paged(self, op: str, body: dict, result_key: str) -> Iterator[dict]:
        token: Optional[str] = None
        while True:
            page = dict(body)
            if token:
                page["NextToken"] = token
            resp = self._post(op, page)
            yield from resp.get(result_key, [])
            token = resp.get("NextToken")
            if not token:
                return

    def get_database(self, name: str) -> dict:
        return self._post("GetDatabase", {"Name": name}).get("Database", {})

    def get_tables(self, db: str) -> List[dict]:
        return list(self._paged("GetTables", {"DatabaseName": db},
                                "TableList"))

    def get_table(self, db: str, name: str) -> dict:
        return self._post("GetTable", {"DatabaseName": db,
                                       "Name": name}).get("Table", {})

    def get_partitions(self, db: str, table: str) -> List[dict]:
        return list(self._paged(
            "GetPartitions", {"DatabaseName": db, "TableName": table},
            "Partitions"))


class GlueUnderDatabase(UnderDatabase):
    """``table attachdb glue <endpoint-or-region> <db> [-o k=v ...]``.

    The connection string is either a ``https://...`` endpoint override
    or a bare region name (``us-west-2``)."""

    udb_type = "glue"

    def __init__(self, fs, connection: str, db_name: str = "",
                 options: Optional[Dict[str, str]] = None) -> None:
        self._fs = fs
        self._name = db_name
        opts = options or {}
        endpoint = opts.get("glue.endpoint", "")
        region = opts.get("aws.region", "")
        if connection.startswith(("http://", "https://")):
            endpoint = endpoint or connection
        elif connection:
            region = region or connection
        self._client = GlueClient(
            region=region, endpoint=endpoint,
            catalog_id=opts.get("aws.catalog.id", ""),
            access_key=opts.get("aws.accesskey",
                                os.environ.get("AWS_ACCESS_KEY_ID", "")),
            secret_key=opts.get("aws.secretkey",
                                os.environ.get("AWS_SECRET_ACCESS_KEY", "")))
        mapping = mount_translations(fs)
        for pair in opts.get("path_translations", "").split(","):
            if "=" in pair:
                u, _, a = pair.partition("=")
                mapping[u.strip()] = a.strip()
        self._translator = PathTranslator(mapping)

    def database_name(self) -> str:
        if not self._name:
            raise NotFoundError("glue udb needs an explicit database "
                                "name (attachdb <type> <uri> <db>)")
        return self._name

    def _translate(self, location: str) -> str:
        t = self._translator.translate(location)
        return t if t is not None else location

    def table_names(self) -> List[str]:
        db = self.database_name()
        self._client.get_database(db)  # EntityNotFound -> NotFoundError
        return sorted(t.get("Name", "") for t in
                      self._client.get_tables(db))

    def get_table(self, name: str) -> UdbTable:
        db = self.database_name()
        t = self._client.get_table(db, name)
        if not t:
            raise NotFoundError(f"glue table {db}.{name} not found")
        sd = t.get("StorageDescriptor", {}) or {}
        schema = [{"name": c.get("Name", ""), "type": c.get("Type", "")}
                  for c in sd.get("Columns", [])]
        pkeys = [c.get("Name", "") for c in t.get("PartitionKeys", [])]
        location = self._translate(sd.get("Location", ""))
        rows = []
        if pkeys:
            rows = [(p.get("Values", []),
                     self._translate((p.get("StorageDescriptor", {})
                                      or {}).get("Location", "")))
                    for p in self._client.get_partitions(db, name)]
        return UdbTable.build(name, schema, location, pkeys, rows)
