"""Hive metastore under-database.

Re-design of ``table/server/underdb/hive/src/main/java/alluxio/table/
under/hive/HiveDatabase.java:59`` (+ ``HiveUtils``): snapshot a Hive
database's tables/partitions from a metastore into the journaled
catalog. Differences from the reference, on purpose:

* The HMS client is the ~150-line hand-rolled binary-protocol subset in
  ``table/thrift_proto.py`` (read path only: databases, tables,
  partitions) instead of the hive-exec jar.
* Path translation (reference ``PathTranslator``): HMS locations are UFS
  URIs (``hdfs://nn/warehouse/t`` / ``s3://bucket/t``); they map into
  the namespace through the caller-supplied mount mapping
  (``path_translations`` attach option, or automatic longest-prefix
  match against the cluster's mount table), so table reads ride the
  caching data plane.

HMS Thrift field ids used (hive_metastore.thrift, stable since 1.x):
  Table:   1 tableName, 7 sd, 8 partitionKeys
  StorageDescriptor: 1 cols, 2 location
  FieldSchema: 1 name, 2 type
  Partition: 1 values, 6 sd
"""

from __future__ import annotations

from typing import Dict, List, Optional

from alluxio_tpu.table.thrift_proto import (
    I16, STRING, ThriftClient, ThriftError,
)
from alluxio_tpu.table.udb import UdbTable, UnderDatabase
from alluxio_tpu.utils.exceptions import NotFoundError


def parse_thrift_uri(connection: str) -> "tuple[str, int]":
    """``thrift://host:port`` -> (host, port)."""
    rest = connection
    if "://" in rest:
        scheme, _, rest = rest.partition("://")
        if scheme != "thrift":
            raise ValueError(
                f"hive udb needs a thrift:// uri, got {connection!r}")
    host, _, port = rest.partition("/")[0].rpartition(":")
    if not host:
        raise ValueError(f"no port in metastore uri {connection!r}")
    return host, int(port)


class HiveMetastoreClient:
    """Read-side HMS client: the four calls the catalog snapshot needs."""

    def __init__(self, host: str, port: int, *, framed: bool = False,
                 timeout_s: float = 30.0) -> None:
        self._c = ThriftClient(host, port, framed=framed,
                               timeout_s=timeout_s)

    def close(self) -> None:
        self._c.close()

    def __enter__(self) -> "HiveMetastoreClient":
        self._c.connect()
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _call(self, method: str, args) -> dict:
        result = self._c.call(method, args)
        for fid, v in result.items():
            if fid != 0 and isinstance(v, dict) and v:
                # declared exception struct (NoSuchObjectException etc.
                # carry their message in field 1)
                raise NotFoundError(f"{method}: {v.get(1, v)}")
        return result

    def get_all_databases(self) -> List[str]:
        return self._call("get_all_databases", [])[0] or []

    def get_database(self, name: str) -> dict:
        return self._call("get_database", [(1, STRING, name)])[0] or {}

    def get_all_tables(self, db: str) -> List[str]:
        return self._call("get_all_tables", [(1, STRING, db)])[0] or []

    def get_table(self, db: str, table: str) -> dict:
        return self._call("get_table", [(1, STRING, db),
                                        (2, STRING, table)])[0] or {}

    def get_partitions(self, db: str, table: str,
                       max_parts: int = -1) -> List[dict]:
        return self._call("get_partitions", [
            (1, STRING, db), (2, STRING, table),
            (3, I16, max_parts)])[0] or []


class PathTranslator:
    """UFS location -> namespace path, longest-prefix first (reference:
    ``table/server/common/.../udb/PathTranslator.java``)."""

    def __init__(self, mapping: Dict[str, str]) -> None:
        #: {ufs_uri_prefix: namespace_path}
        self._map = sorted(((u.rstrip("/"), a.rstrip("/") or "/")
                            for u, a in mapping.items()),
                           key=lambda kv: -len(kv[0]))

    def translate(self, ufs_uri: str) -> Optional[str]:
        ufs_uri = ufs_uri.rstrip("/")
        for prefix, alluxio in self._map:
            if ufs_uri == prefix:
                return alluxio
            if ufs_uri.startswith(prefix + "/"):
                return alluxio + ufs_uri[len(prefix):]
        return None


def mount_translations(fs) -> Dict[str, str]:
    """Auto-derive the translation map from the cluster's mount table."""
    out: Dict[str, str] = {}
    try:
        for m in fs.get_mount_points():
            if m.ufs_uri:
                out[m.ufs_uri] = m.alluxio_path
    except Exception:  # noqa: BLE001 — no mount RPC: explicit map only
        pass
    return out


class HiveUnderDatabase(UnderDatabase):
    """``table attachdb hive thrift://host:port <db>``.

    Options (attach properties):
      hive.metastore.framed    "true" for TFramedTransport metastores
      path_translations        "ufs1=/ns1,ufs2=/ns2" explicit overrides
                               (defaults to the cluster mount table)
    """

    udb_type = "hive"

    def __init__(self, fs, connection: str, db_name: str = "",
                 options: Optional[Dict[str, str]] = None) -> None:
        self._fs = fs
        self._conn = connection
        self._name = db_name
        opts = options or {}
        self._framed = str(opts.get("hive.metastore.framed",
                                    "")).lower() == "true"
        mapping = mount_translations(fs)
        spec = opts.get("path_translations", "")
        for pair in spec.split(","):
            if "=" in pair:
                u, _, a = pair.partition("=")
                mapping[u.strip()] = a.strip()
        self._translator = PathTranslator(mapping)

    def _client(self) -> HiveMetastoreClient:
        host, port = parse_thrift_uri(self._conn)
        return HiveMetastoreClient(host, port, framed=self._framed)

    def database_name(self) -> str:
        if not self._name:
            raise NotFoundError("hive udb needs an explicit database "
                                "name (attachdb <type> <uri> <db>)")
        return self._name

    def _translate(self, location: str) -> str:
        t = self._translator.translate(location)
        if t is not None:
            return t
        # untranslated locations stay as-is: reads bypass the cache but
        # the catalog is still complete (reference logs the same way)
        return location

    def table_names(self) -> List[str]:
        with self._client() as c:
            return sorted(c.get_all_tables(self.database_name()))

    def get_table(self, name: str) -> UdbTable:
        db = self.database_name()
        with self._client() as c:
            t = c.get_table(db, name)
            if not t:
                raise NotFoundError(f"hive table {db}.{name} not found")
            sd = t.get(7, {})
            schema = [{"name": f.get(1, ""), "type": f.get(2, "")}
                      for f in sd.get(1, [])]
            pkeys = [f.get(1, "") for f in t.get(8, [])]
            location = self._translate(sd.get(2, ""))
            rows = []
            if pkeys:
                rows = [(p.get(1, []),
                         self._translate(p.get(6, {}).get(2, "")))
                        for p in c.get_partitions(db, name)]
        return UdbTable.build(name, schema, location, pkeys, rows)
