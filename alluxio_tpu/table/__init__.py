"""Structured-data (table) service: catalog + UDB SPI + transforms.

Re-design of the reference's ``table/`` module (12.5k LoC Java:
``table/server/master/.../AlluxioCatalog.java:55``, ``DefaultTableMaster``,
UDB SPI ``table/server/common/.../udb/UnderDatabase.java``,
``transform/TransformManager.java:82``) for the TPU data plane: the
catalog snapshots an under-database's schemas/partitions into journaled
master state; reads are **column projections** straight out of Parquet
through the caching FS client (the path bench config #4 measures); the
compact transform runs as a job-service plan.
"""

from alluxio_tpu.table.master import TableMaster  # noqa: F401
from alluxio_tpu.table.plan import (  # noqa: F401
    ColumnRange, FooterCache, ParquetPlanError, RowGroupPlan, cached_plan,
    coalesce, footer_cache, plan_row_groups, read_footer,
)
from alluxio_tpu.table.udb import (  # noqa: F401
    FsUnderDatabase, UdbPartition, UdbTable, UnderDatabase, udb_factory,
)
