"""Parquet footer/range planner: projection pushdown v2 (docs/table_reads.md).

The legacy table read path hands a ``FileInStream`` to pyarrow and lets
it drive every byte through seek+read — a serial RPC per column chunk,
blind to the scatter/gather, SHM, and striped planes the small-read
stack already has. This module is the other half of the fix: parse the
footer ONCE (one tail-range read instead of pyarrow's probe-seek
sequence, LRU-cached keyed on path + metadata version), and emit, per
row group, the exact column-chunk byte ranges of a projection — a plan
the range executor (``client/streams.py:FileInStream.pread_ranges``)
can route down the ``choose_route`` ladder in bulk.

Reference analogues: Presto's ``ParquetReader`` footer cache + Arrow's
``pre_buffer`` range coalescing (arxiv 2503.22643's latency-hiding
pipeline plans transfers the same way: ranges first, decode overlapped
behind them).

Coalescing: adjacent ranges whose gap is at or under
``atpu.user.table.coalesce.slack.bytes`` merge into one read — the
dropped gap bytes buy fewer round trips. Every consumer slices the
original ranges back out of the merged buffer, so coalescing is
invisible above the transfer layer.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, NamedTuple, Optional, Sequence, Tuple

#: footer-length trailer + magic, the fixed Parquet tail
_TAIL_FIXED = 8
_MAGIC = b"PAR1"


class ParquetPlanError(Exception):
    """The file cannot be footer-planned (not Parquet / truncated /
    encrypted footer); the reader falls back to the legacy pyarrow-driven
    path, which surfaces its own (identical) error if the file is bad."""


class ColumnRange(NamedTuple):
    """One planned column-chunk byte range inside a row group."""

    column: str
    offset: int
    length: int


class RowGroupPlan(NamedTuple):
    """The projection's exact byte ranges for one row group, plus the
    coalesced read list the transfer layer executes."""

    index: int
    num_rows: int
    #: per-column exact ranges (pre-coalesce, for accounting/tests)
    ranges: List[ColumnRange]
    #: gap-merged (offset, length) reads, ascending, non-overlapping
    reads: List[Tuple[int, int]]
    #: exact projected bytes (sum of ranges, excludes coalescing slack)
    projected_bytes: int


class Footer(NamedTuple):
    """A parsed footer plus the raw tail bytes it came from — the tail
    is pre-seeded into the range cache so pyarrow's own footer
    probe-seeks never touch the wire again."""

    metadata: object  # pyarrow.parquet.FileMetaData
    tail: bytes
    tail_offset: int


def _metrics():
    from alluxio_tpu.metrics import metrics

    return metrics()


class FooterCache:
    """Bounded LRU of parsed footers keyed on (path, metadata version)
    — also reused, with richer keys, for derived row-group plans.

    The version rides the same fields the PR-10 client metadata cache
    serves coherently (file id, length, mtime): a rewritten or
    re-transformed file changes them and naturally misses, while a warm
    projection re-plans with zero footer I/O."""

    def __init__(self, max_entries: int = 256) -> None:
        self._max = max(1, int(max_entries))
        self._entries: "OrderedDict[tuple, object]" = OrderedDict()
        self._lock = threading.Lock()

    def configure(self, max_entries: int) -> None:
        with self._lock:
            self._max = max(1, int(max_entries))
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)

    def get(self, key: tuple):
        with self._lock:
            f = self._entries.get(key)
            if f is not None:
                self._entries.move_to_end(key)
            return f

    def put(self, key: tuple, footer) -> None:
        with self._lock:
            self._entries[key] = footer
            self._entries.move_to_end(key)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


#: process-wide footer cache (capacity re-applied from conf at each
#: planned open — cheap, and keeps the module import-light)
_FOOTER_CACHE = FooterCache()


def footer_cache() -> FooterCache:
    return _FOOTER_CACHE


def metadata_version(info) -> tuple:
    """The (file_id, length, mtime) stamp a footer cache entry is keyed
    on — the fields the PR-10 metadata cache keeps heartbeat-coherent."""
    return (info.file_id, info.length, info.last_modification_time_ms)


def read_footer(pread, length: int, *, guess_bytes: int = 64 << 10
                ) -> Footer:
    """Fetch + parse a Parquet footer with at most two range reads:
    one ``guess_bytes`` tail read (vs pyarrow's probe-seek sequence of
    tiny reads), and — only when the footer outgrows the guess — one
    exact re-read sized from the footer-length trailer.

    ``pread(offset, n) -> bytes`` is the only transport dependency, so
    the planner runs over a FileInStream, a raw file, or a test stub."""
    if length < _TAIL_FIXED:
        raise ParquetPlanError(f"file too short for a Parquet tail "
                               f"({length} bytes)")
    m = _metrics()
    tail_off = max(0, length - max(_TAIL_FIXED, int(guess_bytes)))
    tail = pread(tail_off, length - tail_off)
    m.counter("Client.TableFooterReads").inc()
    if len(tail) < _TAIL_FIXED or tail[-4:] != _MAGIC:
        raise ParquetPlanError("missing PAR1 magic (not a Parquet file?)")
    footer_len = int.from_bytes(tail[-8:-4], "little")
    need = footer_len + _TAIL_FIXED
    if need > length:
        raise ParquetPlanError(
            f"footer length {footer_len} exceeds file ({length} bytes)")
    if need > len(tail):
        tail_off = length - need
        tail = pread(tail_off, need)
        m.counter("Client.TableFooterReads").inc()
    try:
        import pyarrow as pa
        import pyarrow.parquet as pq

        # BufferReader over the tail: footer parsing only touches the
        # end of the file, and every offset inside the decoded metadata
        # is absolute, so the truncated view parses identically
        md = pq.read_metadata(pa.BufferReader(tail))
    except ParquetPlanError:
        raise
    except Exception as e:  # noqa: BLE001 - fall back to the legacy path
        raise ParquetPlanError(f"footer parse failed: {e}") from e
    return Footer(metadata=md, tail=bytes(tail), tail_offset=tail_off)


def cached_footer(pread, path: str, info, *, guess_bytes: int = 64 << 10,
                  cache_max: int = 256) -> Footer:
    """The LRU-cached form of :func:`read_footer`."""
    cache = _FOOTER_CACHE
    cache.configure(cache_max)
    key = (path, metadata_version(info))
    hit = cache.get(key)
    if hit is not None:
        _metrics().counter("Client.TableFooterCacheHits").inc()
        return hit
    footer = read_footer(pread, info.length, guess_bytes=guess_bytes)
    cache.put(key, footer)
    return footer


def _chunk_range(col) -> Tuple[int, int]:
    """The absolute byte range of one column chunk: pages start at the
    dictionary page when present, else the first data page; the chunk
    runs ``total_compressed_size`` bytes from there."""
    start = col.data_page_offset
    dict_off = col.dictionary_page_offset
    if dict_off is not None and 0 <= dict_off < start:
        start = dict_off
    return int(start), int(col.total_compressed_size)


def coalesce(ranges: Sequence[Tuple[int, int]], *, slack: int = 0
             ) -> List[Tuple[int, int]]:
    """Merge ascending-sorted (offset, length) ranges whose gap is at
    or under ``slack`` (0 merges only touching/overlapping ranges).
    Output is ascending and non-overlapping; empty ranges are dropped."""
    merged: List[Tuple[int, int]] = []
    for off, n in sorted((r for r in ranges if r[1] > 0)):
        if merged:
            last_off, last_n = merged[-1]
            if off - (last_off + last_n) <= slack:
                merged[-1] = (last_off,
                              max(last_n, off + n - last_off))
                continue
        merged.append((off, n))
    return merged


def plan_row_groups(metadata, columns: Optional[Sequence[str]], *,
                    slack: int = 0,
                    row_groups: Optional[Sequence[int]] = None
                    ) -> List[RowGroupPlan]:
    """Per-row-group projection plan from a parsed footer.

    ``columns=None`` plans every column (a planned full scan still
    coalesces and pipelines). Column matching follows pyarrow's
    ``read(columns=...)`` semantics: a requested name selects every
    leaf whose dotted path starts at it, so nested roots project all
    their leaves. Unknown names are ignored here — pyarrow raises the
    canonical error at decode time, keeping error behavior identical
    to the legacy path."""
    wanted = None if columns is None else {str(c) for c in columns}
    plans: List[RowGroupPlan] = []
    indices = range(metadata.num_row_groups) if row_groups is None \
        else row_groups
    for rg_i in indices:
        rg = metadata.row_group(rg_i)
        ranges: List[ColumnRange] = []
        for c_i in range(rg.num_columns):
            col = rg.column(c_i)
            path = col.path_in_schema
            root = path.split(".", 1)[0]
            if wanted is not None and root not in wanted \
                    and path not in wanted:
                continue
            off, n = _chunk_range(col)
            ranges.append(ColumnRange(path, off, n))
        reads = coalesce([(r.offset, r.length) for r in ranges],
                         slack=slack)
        plans.append(RowGroupPlan(
            index=rg_i, num_rows=rg.num_rows, ranges=ranges, reads=reads,
            projected_bytes=sum(r.length for r in ranges)))
    return plans


#: derived-plan LRU: planning walks the full (rg × column) metadata
#: through pyarrow property calls — noticeable per read on warm
#: repeated projections, and fully determined by (footer version,
#: projection, slack), so it caches alongside the footers
_PLAN_CACHE = FooterCache()


def cached_plan(path: str, info, metadata,
                columns: Optional[Sequence[str]], *, slack: int = 0,
                cache_max: int = 256) -> List[RowGroupPlan]:
    """The LRU-cached form of :func:`plan_row_groups`, keyed on the
    footer-cache key plus the projection and coalescing slack."""
    cache = _PLAN_CACHE
    cache.configure(cache_max)
    key = (path, metadata_version(info),
           None if columns is None else tuple(columns), int(slack))
    hit = cache.get(key)
    if hit is None:
        hit = plan_row_groups(metadata, columns, slack=slack)
        cache.put(key, hit)
    return hit
