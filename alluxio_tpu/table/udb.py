"""Under-database SPI: pluggable external catalogs.

Re-design of ``table/server/common/src/main/java/alluxio/table/common/udb/
{UnderDatabase,UdbTable,UdbPartition}.java`` + ``PathTranslator``: a UDB
enumerates tables and partitions with their storage locations; the table
master snapshots that into its journaled catalog, translating UFS paths
into namespace paths so reads go through the caching data plane.

The reference ships ``hive`` and ``glue`` connectors (Thrift/AWS
services). This environment has neither, so the in-tree connector is
**FsUnderDatabase**: a Hive-*layout* database rooted at a directory —
each table a subdirectory of Parquet files, partitions as nested
``key=value`` subdirectories, schema read from Parquet footers. That is
the same metadata a Hive metastore would return for an external table;
the SPI seam is where a Thrift-backed UDB would plug in.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from alluxio_tpu.utils.exceptions import NotFoundError


@dataclass
class UdbPartition:
    """One partition: spec (k=v values) + storage location."""

    spec: str                      # "" for unpartitioned, else "k1=v1/k2=v2"
    location: str                  # namespace (Alluxio) path
    values: Dict[str, str] = field(default_factory=dict)


@dataclass
class UdbTable:
    name: str
    schema: List[Dict[str, str]]   # [{"name":..., "type":...}]
    location: str                  # namespace path of the table root
    partition_keys: List[str] = field(default_factory=list)
    partitions: List[UdbPartition] = field(default_factory=list)

    def to_wire(self) -> dict:
        return {
            "name": self.name, "schema": self.schema,
            "location": self.location,
            "partition_keys": list(self.partition_keys),
            "partitions": [{"spec": p.spec, "location": p.location,
                            "values": dict(p.values)}
                           for p in self.partitions],
        }

    @staticmethod
    def from_wire(w: dict) -> "UdbTable":
        return UdbTable(
            name=w["name"], schema=list(w.get("schema", [])),
            location=w["location"],
            partition_keys=list(w.get("partition_keys", [])),
            partitions=[UdbPartition(p["spec"], p["location"],
                                     dict(p.get("values", {})))
                        for p in w.get("partitions", [])])

    @staticmethod
    def build(name: str, schema: List[Dict[str, str]], location: str,
              partition_keys: List[str],
              value_rows: "List[tuple]") -> "UdbTable":
        """Assemble a snapshot table the way every UDB does: each
        ``(values, location)`` row becomes a ``k=v/k2=v2`` partition;
        an unpartitioned table gets the single root partition."""
        partitions = [
            UdbPartition("/".join(f"{k}={v}" for k, v in
                                  zip(partition_keys, values)),
                         loc, dict(zip(partition_keys, values)))
            for values, loc in value_rows]
        return UdbTable(name=name, schema=schema, location=location,
                        partition_keys=partition_keys,
                        partitions=partitions or
                        [UdbPartition("", location, {})])


class UnderDatabase:
    """SPI (reference: ``UnderDatabase.java``)."""

    #: registry key (the reference's udb `type`, e.g. "hive")
    udb_type = ""

    def database_name(self) -> str:
        raise NotImplementedError

    def table_names(self) -> List[str]:
        raise NotImplementedError

    def get_table(self, name: str) -> UdbTable:
        raise NotImplementedError


class FsUnderDatabase(UnderDatabase):
    """Hive-directory-layout database over the mounted namespace.

    ``connection`` is a namespace path (usually a mount of an object
    store); tables are its child directories; ``key=value`` subdirs are
    partitions; schemas come from Parquet footers via the caching read
    path (so attaching a db warms the footers).
    """

    udb_type = "fs"

    def __init__(self, fs, connection: str, db_name: str = "") -> None:
        self._fs = fs
        self._root = connection.rstrip("/")
        self._name = db_name or self._root.rsplit("/", 1)[-1]

    def database_name(self) -> str:
        return self._name

    def table_names(self) -> List[str]:
        return sorted(info.name for info in self._fs.list_status(self._root)
                      if info.folder)

    def get_table(self, name: str) -> UdbTable:
        root = f"{self._root}/{name}"
        if not self._fs.exists(root):
            raise NotFoundError(f"table directory {root} does not exist")
        partition_keys: List[str] = []
        partitions: List[UdbPartition] = []
        sample_file: Optional[str] = None

        def walk(path: str, values: Dict[str, str]) -> None:
            nonlocal sample_file
            files, subparts = [], []
            for info in self._fs.list_status(path):
                if info.folder and "=" in info.name:
                    subparts.append(info)
                elif not info.folder and info.name.endswith(".parquet"):
                    files.append(info)
            if subparts:
                for info in subparts:
                    k, _, v = info.name.partition("=")
                    if k not in partition_keys:
                        partition_keys.append(k)
                    walk(f"{path}/{info.name}", {**values, k: v})
            elif files:
                spec = "/".join(f"{k}={v}" for k, v in values.items())
                partitions.append(UdbPartition(spec, path, dict(values)))
                if sample_file is None:
                    sample_file = f"{path}/{files[0].name}"

        walk(root, {})
        schema = self._read_schema(sample_file) if sample_file else []
        return UdbTable(name=name, schema=schema, location=root,
                        partition_keys=partition_keys,
                        partitions=partitions or
                        [UdbPartition("", root, {})])

    def _read_schema(self, path: str) -> List[Dict[str, str]]:
        from alluxio_tpu.table.reader import open_parquet

        pf = open_parquet(self._fs, path)
        return [{"name": f.name, "type": str(f.type)}
                for f in pf.schema_arrow]


def udb_factory(udb_type: str, fs, connection: str, db_name: str = "",
                options: Optional[Dict[str, str]] = None) -> UnderDatabase:
    """Registry keyed by udb type (reference: ServiceLoader discovery)."""
    if udb_type == "fs":
        return FsUnderDatabase(fs, connection, db_name)
    if udb_type == "hive":
        from alluxio_tpu.table.hive import HiveUnderDatabase

        return HiveUnderDatabase(fs, connection, db_name, options)
    if udb_type == "glue":
        from alluxio_tpu.table.glue import GlueUnderDatabase

        return GlueUnderDatabase(fs, connection, db_name, options)
    raise NotFoundError(
        f"unknown under-database type {udb_type!r} "
        f"(available: fs, hive, glue)")
