"""TableMaster: the journaled catalog + transform orchestration.

Re-design of ``table/server/master/src/main/java/alluxio/master/table/
{DefaultTableMaster,AlluxioCatalog}.java:55`` and
``transform/TransformManager.java:82``: ``attach_database`` snapshots an
under-database's tables/partitions into journaled state (so the catalog
survives failover and serves reads without touching the UDB);
``sync_database`` refreshes the snapshot; transforms run as job-service
plans and, on completion, a journaled layout update repoints partitions
at the transformed data — exactly the reference's commit protocol
(journal entry, not in-place mutation).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from alluxio_tpu.journal.format import EntryType, JournalEntry, Journaled
from alluxio_tpu.table.udb import UdbTable, udb_factory
from alluxio_tpu.utils.exceptions import (
    AlreadyExistsError, NotFoundError,
)


class TableMaster(Journaled):
    journal_name = "TableMaster"

    def __init__(self, journal, fs_factory=None, job_client_factory=None
                 ) -> None:
        """``fs_factory() -> FileSystem`` supplies the data-plane client
        used for UDB enumeration + schema reads; ``job_client_factory()``
        a job master client for transforms. Both lazy: the table master
        journals fine without either (replay/standby)."""
        self._journal = journal
        self._fs_factory = fs_factory
        self._job_factory = job_client_factory
        self._fs = None
        #: db -> {"type","connection","tables":{name: wire}}
        self._dbs: Dict[str, Dict[str, Any]] = {}
        #: job_id -> transform info wire
        self._transforms: Dict[int, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        # held across check+journal on mutations so two concurrent
        # attaches of the same db can't both pass the existence check
        # (same discipline as PathProperties._mutate_lock)
        self._mutate_lock = threading.Lock()
        journal.register(self)

    # -- helpers -------------------------------------------------------------
    def _file_system(self):
        if self._fs is None:
            if self._fs_factory is None:
                raise NotFoundError(
                    "table master has no data-plane client configured")
            self._fs = self._fs_factory()
        return self._fs

    # -- API: databases ------------------------------------------------------
    def attach_database(self, udb_type: str, connection: str,
                        db_name: str = "",
                        options: Optional[Dict[str, str]] = None) -> str:
        udb = udb_factory(udb_type, self._file_system(), connection,
                          db_name, options)
        name = udb.database_name()
        with self._mutate_lock:
            with self._lock:
                if name in self._dbs:
                    raise AlreadyExistsError(f"database {name} is attached")
            tables = [udb.get_table(t) for t in udb.table_names()]
            with self._journal.create_context() as ctx:
                ctx.append(EntryType.ATTACH_DB, {
                    "db": name, "type": udb_type, "connection": connection,
                    "options": dict(options or {})})
                for t in tables:
                    ctx.append(EntryType.ADD_TABLE,
                               {"db": name, "table": t.to_wire()})
        return name

    def detach_database(self, db_name: str) -> None:
        with self._mutate_lock:
            with self._lock:
                if db_name not in self._dbs:
                    raise NotFoundError(
                        f"database {db_name} is not attached")
            with self._journal.create_context() as ctx:
                ctx.append(EntryType.DETACH_DB, {"db": db_name})

    def sync_database(self, db_name: str) -> int:
        """Re-snapshot the UDB; returns the table count. Tables dropped
        from the UDB are journaled as removals so the catalog converges
        (reference: AlluxioCatalog sync removes stale tables too)."""
        with self._mutate_lock:
            with self._lock:
                db = self._dbs.get(db_name)
                if db is None:
                    raise NotFoundError(
                        f"database {db_name} is not attached")
                udb_type, connection = db["type"], db["connection"]
                options = db.get("options") or {}
                known = set(db["tables"])
            udb = udb_factory(udb_type, self._file_system(), connection,
                              db_name, options)
            tables = [udb.get_table(t) for t in udb.table_names()]
            dropped = known - {t.name for t in tables}
            with self._journal.create_context() as ctx:
                for t in tables:
                    ctx.append(EntryType.ADD_TABLE,
                               {"db": db_name, "table": t.to_wire()})
                for name in sorted(dropped):
                    ctx.append(EntryType.REMOVE_TABLE,
                               {"db": db_name, "table_name": name})
        return len(tables)

    def list_databases(self) -> List[str]:
        with self._lock:
            return sorted(self._dbs)

    def list_tables(self, db_name: str) -> List[str]:
        with self._lock:
            db = self._dbs.get(db_name)
            if db is None:
                raise NotFoundError(f"database {db_name} is not attached")
            return sorted(db["tables"])

    def get_table(self, db_name: str, table_name: str) -> Dict[str, Any]:
        with self._lock:
            db = self._dbs.get(db_name)
            if db is None:
                raise NotFoundError(f"database {db_name} is not attached")
            t = db["tables"].get(table_name)
            if t is None:
                raise NotFoundError(
                    f"table {db_name}.{table_name} does not exist")
            return dict(t)

    # -- API: transforms -----------------------------------------------------
    def transform_table(self, db_name: str, table_name: str, *,
                        definition: str = "compact",
                        options: Optional[Dict[str, Any]] = None) -> int:
        """Kick a transform job; journaled so a failover master keeps
        monitoring it (reference: TransformManager.java:82 'journaled
        before the job starts')."""
        table = self.get_table(db_name, table_name)
        if self._job_factory is None:
            raise NotFoundError("no job service configured for transforms")
        out_root = f"{table['location']}/_transformed"
        config = {"type": "transform", "db": db_name, "table": table_name,
                  "table_wire": table, "definition": definition,
                  "output_root": out_root, **(options or {})}
        job_id = self._job_factory().run(config)
        with self._journal.create_context() as ctx:
            ctx.append(EntryType.ADD_TRANSFORM_JOB_INFO, {
                "job_id": job_id, "db": db_name, "table": table_name,
                "definition": definition, "output_root": out_root})
        return job_id

    def transform_status(self, job_id: int) -> Dict[str, Any]:
        """Read-only status report. Layout commit happens on the master's
        transform-monitor heartbeat (``heartbeat()``), matching the
        reference's TransformManager.java:82 — a client polling status
        must not be the thing that commits."""
        with self._lock:
            info = self._transforms.get(job_id)
            if info is not None:
                info = dict(info)
        if info is None:
            raise NotFoundError(f"no transform with job id {job_id}")
        if info.get("applied"):
            return {**info, "status": "COMPLETED", "error": ""}
        if self._job_factory is None:
            return {**info, "status": "UNKNOWN",
                    "error": "no job service configured"}
        status = self._job_factory().get_status(job_id)
        return {**info, "status": status.status,
                "error": status.error_message}

    def heartbeat(self) -> None:
        """Transform-monitor tick: poll running transform jobs; commit the
        layout of completed ones (reference: TransformManager.java:82 —
        the manager monitors via heartbeat, journaling the commit)."""
        if self._job_factory is None:
            return
        with self._lock:
            pending = [dict(v) for v in self._transforms.values()
                       if not v.get("applied")]
        for info in pending:
            try:
                status = self._job_factory().get_status(info["job_id"])
            except Exception:  # noqa: BLE001 job master unreachable: retry
                continue
            if status.status == "COMPLETED":
                self._apply_transform(info, status)

    def _apply_transform(self, info: Dict[str, Any], status: dict) -> None:
        """Commit the transformed layout: journaled partition re-point.
        Idempotent — _mutate_lock + an applied re-check make concurrent
        heartbeat ticks / failover replays commit exactly once."""
        with self._mutate_lock:
            with self._lock:
                live = self._transforms.get(info["job_id"])
                if live is None or live.get("applied"):
                    return
            table = self.get_table(info["db"], info["table"])
            new_parts = []
            for part in table["partitions"]:
                spec = part["spec"]
                new_loc = f"{info['output_root']}/{spec}" if spec \
                    else info["output_root"]
                fs = self._file_system()
                if fs.exists(new_loc):
                    new_parts.append({**part, "location": new_loc})
                else:  # transform produced nothing for this partition
                    new_parts.append(part)
            table["partitions"] = new_parts
            with self._journal.create_context() as ctx:
                ctx.append(EntryType.ADD_TABLE,
                           {"db": info["db"], "table": table})
                ctx.append(EntryType.REMOVE_TRANSFORM_JOB_INFO,
                           {"job_id": info["job_id"], "applied": True})

    # -- journal contract ----------------------------------------------------
    def process_entry(self, entry: JournalEntry) -> bool:
        t, p = entry.type, entry.payload
        if t == EntryType.ATTACH_DB:
            with self._lock:
                self._dbs[p["db"]] = {"type": p["type"],
                                      "connection": p["connection"],
                                      "options": dict(p.get("options", {})),
                                      "tables": {}}
            return True
        if t == EntryType.DETACH_DB:
            with self._lock:
                self._dbs.pop(p["db"], None)
            return True
        if t == EntryType.ADD_TABLE:
            with self._lock:
                db = self._dbs.get(p["db"])
                if db is not None:
                    db["tables"][p["table"]["name"]] = p["table"]
            return True
        if t == EntryType.REMOVE_TABLE:
            with self._lock:
                db = self._dbs.get(p["db"])
                if db is not None:
                    db["tables"].pop(p["table_name"], None)
            return True
        if t == EntryType.ADD_TRANSFORM_JOB_INFO:
            with self._lock:
                self._transforms[p["job_id"]] = dict(p)
            return True
        if t == EntryType.REMOVE_TRANSFORM_JOB_INFO:
            with self._lock:
                info = self._transforms.get(p["job_id"])
                if info is not None:
                    info["applied"] = bool(p.get("applied"))
            return True
        return False

    def snapshot(self) -> dict:
        with self._lock:
            return {"dbs": {n: {"type": d["type"],
                                "connection": d["connection"],
                                "tables": dict(d["tables"])}
                            for n, d in self._dbs.items()},
                    "transforms": {str(k): dict(v)
                                   for k, v in self._transforms.items()}}

    def restore(self, snap: dict) -> None:
        with self._lock:
            self._dbs = {n: {"type": d["type"],
                             "connection": d["connection"],
                             "tables": dict(d["tables"])}
                         for n, d in snap.get("dbs", {}).items()}
            self._transforms = {int(k): dict(v) for k, v in
                                snap.get("transforms", {}).items()}

    def reset_state(self) -> None:
        with self._lock:
            self._dbs.clear()
            self._transforms.clear()
