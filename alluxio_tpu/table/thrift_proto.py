"""Minimal Thrift binary protocol, hand-rolled.

The Hive metastore speaks TBinaryProtocol over a buffered (optionally
framed) socket. The reference's ``table/server/underdb/hive`` pulls the
whole hive-metastore client jar for this; the TPU build needs only the
read-side subset (call + reply, generic struct decode), so these ~200
lines replace that dependency. Protocol layout per the Thrift spec:

  message  = i32 (VERSION_1 | type) + string name + i32 seqid + struct
  struct   = { i8 field-type, i16 field-id, value }* , i8 STOP
  string   = i32 length + bytes
  list/set = i8 elem-type + i32 count + elems
  map      = i8 ktype + i8 vtype + i32 count + pairs

Decoded structs come back as ``{field_id: value}`` dicts — the callers
(``table/hive.py``) name the ids they need; unknown fields decode and
drop, which is exactly the forward-compat contract generated Thrift code
provides.
"""

from __future__ import annotations

import socket
import struct
from io import BytesIO
from typing import Any, Dict, Optional, Tuple

VERSION_1 = 0x80010000

CALL, REPLY, EXCEPTION, ONEWAY = 1, 2, 3, 4

STOP, VOID, BOOL, BYTE, DOUBLE = 0, 1, 2, 3, 4
I16, I32, I64, STRING, STRUCT = 6, 8, 10, 11, 12
MAP, SET, LIST = 13, 14, 15

_i8 = struct.Struct("!b")
_i16 = struct.Struct("!h")
_i32 = struct.Struct("!i")
_i64 = struct.Struct("!q")
_dbl = struct.Struct("!d")


class ThriftError(Exception):
    pass


# ---------------------------------------------------------------- writing
class Writer:
    def __init__(self) -> None:
        self._b = BytesIO()

    def data(self) -> bytes:
        return self._b.getvalue()

    def i8(self, v: int) -> "Writer":
        self._b.write(_i8.pack(v))
        return self

    def i16(self, v: int) -> "Writer":
        self._b.write(_i16.pack(v))
        return self

    def i32(self, v: int) -> "Writer":
        self._b.write(_i32.pack(v))
        return self

    def i64(self, v: int) -> "Writer":
        self._b.write(_i64.pack(v))
        return self

    def double(self, v: float) -> "Writer":
        self._b.write(_dbl.pack(v))
        return self

    def string(self, v: "str | bytes") -> "Writer":
        raw = v.encode() if isinstance(v, str) else v
        self.i32(len(raw))
        self._b.write(raw)
        return self

    def field(self, ftype: int, fid: int) -> "Writer":
        return self.i8(ftype).i16(fid)

    def stop(self) -> "Writer":
        return self.i8(STOP)

    def message(self, name: str, mtype: int, seqid: int) -> "Writer":
        # the version word has the sign bit set; write its signed-i32
        # two's-complement value
        self.i32(((VERSION_1 | mtype) & 0xFFFFFFFF) - (1 << 32))
        self.string(name)
        self.i32(seqid)
        return self

    def write_value(self, ftype: int, v: Any) -> "Writer":
        """Encode a python value as ``ftype``. Structs are passed as
        ``[(fid, ftype, value), ...]`` tuples; lists as
        ``(elem_type, [values])``; maps as ``(ktype, vtype, dict)``."""
        if ftype == BOOL:
            return self.i8(1 if v else 0)
        if ftype == BYTE:
            return self.i8(v)
        if ftype == I16:
            return self.i16(v)
        if ftype == I32:
            return self.i32(v)
        if ftype == I64:
            return self.i64(v)
        if ftype == DOUBLE:
            return self.double(v)
        if ftype == STRING:
            return self.string(v)
        if ftype == STRUCT:
            for fid, ft, fv in v:
                self.field(ft, fid).write_value(ft, fv)
            return self.stop()
        if ftype in (LIST, SET):
            et, items = v
            self.i8(et).i32(len(items))
            for item in items:
                self.write_value(et, item)
            return self
        if ftype == MAP:
            kt, vt, d = v
            self.i8(kt).i8(vt).i32(len(d))
            for k, val in d.items():
                self.write_value(kt, k)
                self.write_value(vt, val)
            return self
        raise ThriftError(f"cannot write thrift type {ftype}")


# ---------------------------------------------------------------- reading
class Reader:
    def __init__(self, data: "bytes | memoryview") -> None:
        self._d = memoryview(data)
        self._pos = 0

    def _take(self, n: int) -> memoryview:
        if self._pos + n > len(self._d):
            raise ThriftError("truncated thrift payload")
        v = self._d[self._pos:self._pos + n]
        self._pos += n
        return v

    def i8(self) -> int:
        return _i8.unpack(self._take(1))[0]

    def i16(self) -> int:
        return _i16.unpack(self._take(2))[0]

    def i32(self) -> int:
        return _i32.unpack(self._take(4))[0]

    def i64(self) -> int:
        return _i64.unpack(self._take(8))[0]

    def double(self) -> float:
        return _dbl.unpack(self._take(8))[0]

    def string(self) -> str:
        n = self.i32()
        return bytes(self._take(n)).decode("utf-8", "replace")

    def message(self) -> Tuple[str, int, int]:
        head = self.i32()
        if head & 0xFFFF0000 == VERSION_1 & 0xFFFFFFFF or head < 0:
            mtype = head & 0xFF
            name = self.string()
            seqid = self.i32()
        else:  # old-style unversioned message
            name = bytes(self._take(head)).decode()
            mtype = self.i8()
            seqid = self.i32()
        return name, mtype, seqid

    def value(self, ftype: int) -> Any:
        if ftype == BOOL:
            return self.i8() != 0
        if ftype == BYTE:
            return self.i8()
        if ftype == I16:
            return self.i16()
        if ftype == I32:
            return self.i32()
        if ftype == I64:
            return self.i64()
        if ftype == DOUBLE:
            return self.double()
        if ftype == STRING:
            return self.string()
        if ftype == STRUCT:
            return self.struct()
        if ftype in (LIST, SET):
            et = self.i8()
            n = self.i32()
            return [self.value(et) for _ in range(n)]
        if ftype == MAP:
            kt, vt = self.i8(), self.i8()
            n = self.i32()
            return {self.value(kt): self.value(vt) for _ in range(n)}
        raise ThriftError(f"cannot read thrift type {ftype}")

    def struct(self) -> Dict[int, Any]:
        """Generic struct decode: {field_id: python value}. Unknown
        fields decode fine (type information is inline)."""
        out: Dict[int, Any] = {}
        while True:
            ftype = self.i8()
            if ftype == STOP:
                return out
            fid = self.i16()
            out[fid] = self.value(ftype)


# --------------------------------------------------------------- transport
class ThriftClient:
    """Buffered (default) or framed TBinaryProtocol client connection."""

    def __init__(self, host: str, port: int, *, framed: bool = False,
                 timeout_s: float = 30.0) -> None:
        self._addr = (host, port)
        self._framed = framed
        self._timeout = timeout_s
        self._sock: Optional[socket.socket] = None
        self._seq = 0

    def connect(self) -> None:
        if self._sock is not None:
            return
        s = socket.create_connection(self._addr, timeout=self._timeout)
        s.settimeout(self._timeout)
        self._sock = s

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self) -> "ThriftClient":
        self.connect()
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ThriftError("metastore closed the connection")
            buf += chunk
        return buf

    def call(self, method: str,
             args: "list[tuple[int, int, Any]]") -> Dict[int, Any]:
        """One RPC: returns the decoded RESULT struct ({0: success,
        k>0: declared exceptions}). Raises ThriftError on transport or
        TApplicationException."""
        self.connect()
        self._seq += 1
        w = Writer().message(method, CALL, self._seq)
        w.write_value(STRUCT, args)
        payload = w.data()
        if self._framed:
            self._sock.sendall(_i32.pack(len(payload)) + payload)
        else:
            self._sock.sendall(payload)
        if self._framed:
            (n,) = _i32.unpack(self._recv_exact(4))
            data = self._recv_exact(n)
        else:
            # buffered transport: read the message incrementally — pull
            # the version+name+seq head, then the result struct. We read
            # greedily in chunks and retry decode on truncation.
            data = b""
            while True:
                try:
                    r = Reader(data)
                    r.message()
                    r.struct()
                    break
                except ThriftError:
                    self._sock.settimeout(self._timeout)
                    chunk = self._sock.recv(1 << 16)
                    if not chunk:
                        raise ThriftError(
                            "metastore closed mid-reply") from None
                    data += chunk
        r = Reader(data)
        name, mtype, _seq = r.message()
        if mtype == EXCEPTION:
            exc = r.struct()
            raise ThriftError(
                f"{method}: TApplicationException "
                f"{exc.get(2)}: {exc.get(1)}")
        result = r.struct()
        return result
