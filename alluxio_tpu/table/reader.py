"""Column-projection Parquet reads through the caching data plane.

The table-service read path (bench config #4: "Parquet column-projection
read"): Parquet's columnar layout means a projection of k of N columns
reads only those column chunks — through our FS client those byte ranges
come from the worker cache (short-circuit mmap when co-located), so a
warm projection never touches the UFS and never reads the other columns'
bytes.

Reference analogue: Presto reading through the HDFS-compat client +
``LocalCacheFileInStream`` page cache; here pyarrow drives the range
reads against ``FileInStream`` directly (it is a python file object:
read/seek/tell).
"""

from __future__ import annotations

from typing import List, Optional, Sequence


class _SizedStream:
    """File-like over FileInStream with the ``size`` pyarrow probes for
    (footer-relative seeks)."""

    def __init__(self, stream, size: int) -> None:
        self._s = stream
        self._size = size
        self._closed = False

    def read(self, n: int = -1) -> bytes:
        return self._s.read(n)

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 1:
            pos += self._s.tell()
        elif whence == 2:
            pos += self._size
        self._s.seek(pos)
        return pos

    def tell(self) -> int:
        return self._s.tell()

    def size(self) -> int:
        return self._size

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    @property
    def closed(self) -> bool:  # pyarrow probes this attribute-style
        return self._closed

    def close(self) -> None:
        self._closed = True
        self._s.close()

    def flush(self) -> None:
        pass


def open_parquet(fs, path: str):
    """ParquetFile over the caching FS client."""
    import pyarrow.parquet as pq

    info = fs.get_status(path)
    return pq.ParquetFile(_SizedStream(fs.open_file(path), info.length))


def read_columns(fs, paths: Sequence[str],
                 columns: Optional[List[str]] = None):
    """Read (a projection of) one or more Parquet files into a single
    pyarrow Table. ``columns=None`` reads everything."""
    import pyarrow as pa

    tables = []
    for p in paths:
        pf = open_parquet(fs, p)
        tables.append(pf.read(columns=columns))
    return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


def read_partition_columns(fs, table_wire: dict, *,
                           columns: Optional[List[str]] = None,
                           partition_filter=None):
    """Projection over a catalog table's partitions.

    ``partition_filter(values: dict) -> bool`` prunes partitions before
    any IO (the catalog's partition pruning); returns a pyarrow Table.
    """
    paths: List[str] = []
    for part in table_wire["partitions"]:
        if partition_filter is not None and \
                not partition_filter(part.get("values", {})):
            continue
        for info in fs.list_status(part["location"]):
            if not info.folder and info.name.endswith(".parquet"):
                paths.append(f"{part['location']}/{info.name}")
    if not paths:
        import pyarrow as pa

        return pa.table({})
    return read_columns(fs, paths, columns=columns)
