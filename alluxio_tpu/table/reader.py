"""Column-projection Parquet reads through the caching data plane.

Two read paths (docs/table_reads.md):

**Planned** (default, ``atpu.user.table.pushdown.enabled``): the
footer/range planner (``table/plan.py``) turns the projection into
per-row-group byte ranges, the range executor
(``FileInStream.pread_ranges``) routes them down the ``choose_route``
ladder in bulk (SHM zero-copy / ``read_many`` scatter batches / striped
reads), and a bounded two-stage pipeline keeps row group k+1's ranges
in flight while row group k decodes — decode time hides under transfer
time (the latency-hiding schedule of arxiv 2503.22643). Decode itself
stays pyarrow's: planned ranges are staged in a range cache that serves
pyarrow's own reads, so the planned path is byte-identical by
construction, and any read the plan missed falls through to the stream
(counted, never wrong).

**Legacy** (conf off, no pyarrow plan, or any ``ParquetPlanError``):
pyarrow drives every byte through seek+read on ``FileInStream`` — a
serial RPC per column chunk. Kept verbatim as the fallback rung and the
bench baseline.

Reference analogue: Presto reading through the HDFS-compat client +
``LocalCacheFileInStream`` page cache; the planned path adds what
Presto's ``ParquetReader`` does on top (footer cache + coalesced range
fetches + async column prefetch).
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence

from alluxio_tpu.table import plan as _plan


def _metrics():
    from alluxio_tpu.metrics import metrics

    return metrics()


class _SizedStream:
    """File-like over FileInStream with the ``size`` pyarrow probes for
    (footer-relative seeks)."""

    def __init__(self, stream, size: int) -> None:
        self._s = stream
        self._size = size
        self._closed = False

    def read(self, n: int = -1) -> bytes:
        return self._s.read(n)

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 1:
            pos += self._s.tell()
        elif whence == 2:
            pos += self._size
        self._s.seek(pos)
        return pos

    def tell(self) -> int:
        return self._s.tell()

    def size(self) -> int:
        return self._size

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    @property
    def closed(self) -> bool:  # pyarrow probes this attribute-style
        return self._closed

    def close(self) -> None:
        self._closed = True
        self._s.close()

    def flush(self) -> None:
        pass


class _RangeCachedFile:
    """File-like that serves pyarrow from staged range buffers.

    The pipeline installs each row group's planned (coalesced) reads
    here before handing the row group to pyarrow; pyarrow's seek+read
    stream then hits the buffers instead of the wire. Reads the plan
    did not cover fall through to the underlying ``FileInStream``
    (``Client.TableProjectionPlanMisses``) — a miss costs a round trip,
    never correctness. ``lock`` serializes that fallback against the
    fetch thread, because ``FileInStream`` is not thread-safe."""

    def __init__(self, stream, size: int, lock) -> None:
        self._s = stream
        self._size = size
        self._lock = lock
        self._pos = 0
        self._closed = False
        self._starts: List[int] = []       # sorted buffer start offsets
        self._bufs: Dict[int, object] = {}  # start offset -> buffer

    # -- staging -------------------------------------------------------------
    def install(self, offset: int, buf) -> None:
        if offset not in self._bufs:
            bisect.insort(self._starts, offset)
        self._bufs[offset] = buf

    def drop(self, offsets: Sequence[int]) -> None:
        """Release a decoded row group's buffers (bounds pipeline
        memory to ~depth row groups of projected bytes)."""
        for off in offsets:
            if off in self._bufs:
                del self._bufs[off]
                del self._starts[bisect.bisect_left(self._starts, off)]

    def _cached(self, pos: int, n: int):
        """The longest staged prefix of [pos, pos+n), or None."""
        i = bisect.bisect_right(self._starts, pos) - 1
        if i < 0:
            return None
        off = self._starts[i]
        buf = self._bufs[off]
        rel = pos - off
        if rel >= len(buf):
            return None
        return buf[rel:rel + n] if rel or n < len(buf) else buf

    # -- file protocol -------------------------------------------------------
    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self._size - self._pos
        n = max(0, min(n, self._size - self._pos))
        chunks = []
        while n > 0:
            got = self._cached(self._pos, n)
            if got is None:
                # miss: fetch only up to the next staged buffer so a
                # short gap doesn't shadow staged bytes behind it
                j = bisect.bisect_right(self._starts, self._pos)
                take = n if j >= len(self._starts) else \
                    min(n, self._starts[j] - self._pos)
                _metrics().counter(
                    "Client.TableProjectionPlanMisses").inc()
                with self._lock:
                    got = self._s.pread(self._pos, take)
                if not got:
                    break
            chunks.append(got)
            self._pos += len(got)
            n -= len(got)
        if len(chunks) == 1 and isinstance(chunks[0], bytes):
            return chunks[0]
        return b"".join(chunks)

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 1:
            pos += self._pos
        elif whence == 2:
            pos += self._size
        self._pos = pos
        return pos

    def tell(self) -> int:
        return self._pos

    def size(self) -> int:
        return self._size

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    @property
    def closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        # pyarrow closes its source when a ParquetFile is collected; the
        # owning reader closes the underlying stream itself
        self._closed = True

    def flush(self) -> None:
        pass


#: process-wide fetch pool shared by every planned read: transfer
#: stages are short and lock-serialized per file, and reusing warm
#: threads keeps the per-read pipeline cost at two submits instead of
#: a thread spawn (the 4-reader fan-out in ``read_columns`` still gets
#: per-file concurrency — fetches from different files interleave)
_FETCH_POOL: Optional[ThreadPoolExecutor] = None
_FETCH_POOL_LOCK = threading.Lock()


def _fetch_pool() -> ThreadPoolExecutor:
    global _FETCH_POOL
    pool = _FETCH_POOL
    if pool is None:
        with _FETCH_POOL_LOCK:
            pool = _FETCH_POOL
            if pool is None:
                pool = _FETCH_POOL = ThreadPoolExecutor(
                    max_workers=4,
                    thread_name_prefix="atpu-table-fetch")
    return pool


def _pread_ranges(stream, ranges, route_stats):
    """Range-list read with graceful degradation: ``pread_ranges`` when
    the stream has it (FileInStream), else per-range ``pread`` (e.g. the
    page-cache wrapper) — the plan and pipeline still apply."""
    fn = getattr(stream, "pread_ranges", None)
    if fn is not None:
        return fn(ranges, route_stats=route_stats)
    out = []
    for off, n in ranges:
        buf = stream.pread(off, n)
        out.append(buf)
        if route_stats is not None:
            route_stats["stream"] = route_stats.get("stream", 0) + len(buf)
    return out


class _PlannedRead:
    """One file's planned projection read: footer -> range plan ->
    pipelined fetch/decode.

    A single fetch thread keeps up to ``depth`` row groups' ranges in
    flight (``atpu.user.table.pipeline.depth``) while the caller thread
    decodes — the two-stage bounded pipeline of the tentpole. Teardown
    is unconditional: any mid-read error drains the executor and closes
    the stream before propagating."""

    def __init__(self, fs, path: str, columns: Optional[Sequence[str]],
                 conf) -> None:
        from alluxio_tpu.conf import Keys

        self._fs = fs
        self._path = path
        self._columns = list(columns) if columns is not None else None
        self._depth = max(1, conf.get_int(Keys.USER_TABLE_PIPELINE_DEPTH))
        self._slack = max(0, conf.get_bytes(
            Keys.USER_TABLE_COALESCE_SLACK_BYTES))
        self._footer_guess = max(_plan._TAIL_FIXED, conf.get_bytes(
            Keys.USER_TABLE_FOOTER_READ_BYTES))
        self._cache_max = conf.get_int(Keys.USER_TABLE_FOOTER_CACHE_MAX)

    def run(self):
        """Execute the planned read; raises ``ParquetPlanError`` (before
        any partial decode) when the file cannot be planned."""
        import pyarrow.parquet as pq

        from alluxio_tpu.utils.tracing import tracer

        m = _metrics()
        with tracer().span("atpu.client.table_read",
                           path=self._path) as sp:
            t_plan0 = time.perf_counter()
            info = self._fs.get_status(self._path)
            stream = self._fs.open_file(self._path, info=info)
            lock = threading.Lock()
            try:
                footer = _plan.cached_footer(
                    stream.pread, self._path, info,
                    guess_bytes=self._footer_guess,
                    cache_max=self._cache_max)
                plans = _plan.cached_plan(
                    self._path, info, footer.metadata, self._columns,
                    slack=self._slack, cache_max=self._cache_max)
                m.counter("Client.TableProjectionRanges").inc(
                    sum(len(p.ranges) for p in plans))
                m.counter("Client.TableProjectionRangesCoalesced").inc(
                    sum(len(p.reads) for p in plans))
                m.counter("Client.TableProjectionBytes").inc(
                    sum(p.projected_bytes for p in plans))
                src = _RangeCachedFile(stream, info.length, lock)
                src.install(footer.tail_offset, footer.tail)
                # hand the cached FileMetaData over: construction skips
                # the (already-done) footer re-parse
                pf = pq.ParquetFile(src, metadata=footer.metadata)
                if sp is not None:
                    sp.phase("table_plan",
                             (time.perf_counter() - t_plan0) * 1000.0)
                if not plans:
                    return pf.read(columns=self._columns)
                return self._pipeline(pf, src, stream, lock, plans, sp, m)
            finally:
                stream.close()

    def _pipeline(self, pf, src, stream, lock, plans, sp, m):
        import pyarrow as pa

        route_stats: Dict[str, int] = {}

        def fetch(p):
            with lock:
                bufs = _pread_ranges(stream, p.reads, route_stats)
            for (off, _n), buf in zip(p.reads, bufs):
                src.install(off, buf)
            return p

        parts = []
        decode_ms = 0.0
        overlap_ms = 0.0
        pending = deque(plans)
        inflight: "deque" = deque()
        pool = _fetch_pool()
        try:
            while pending and len(inflight) < self._depth:
                inflight.append(pool.submit(fetch, pending.popleft()))
            while inflight:
                ready = [inflight.popleft().result()]
                # drain every other fetch that already landed: decoding
                # ready row groups in ONE read_row_groups call amortizes
                # pyarrow's per-call setup, while a transfer-bound read
                # still decodes groups one by one as each lands
                while inflight and inflight[0].done():
                    ready.append(inflight.popleft().result())
                while pending and len(inflight) < self._depth:
                    inflight.append(pool.submit(fetch, pending.popleft()))
                overlapped = bool(inflight)
                t0 = time.perf_counter()
                parts.append(pf.read_row_groups(
                    [p.index for p in ready], columns=self._columns))
                d = (time.perf_counter() - t0) * 1000.0
                decode_ms += d
                if overlapped:
                    overlap_ms += d
                src.drop([off for p in ready for off, _n in p.reads])
        finally:
            # teardown on mid-read error: every in-flight fetch must
            # finish or cancel before the stream under it closes (the
            # pool is shared, so wait on the futures, not the pool)
            for f in inflight:
                if not f.cancel():
                    try:
                        f.result()
                    except Exception:  # noqa: BLE001 - original wins
                        pass
            if sp is not None:
                sp.phase("table_decode", decode_ms)
            m.counter("Client.TableDecodeOverlapMs").inc(int(overlap_ms))
            for route, nbytes in route_stats.items():
                m.counter(
                    f"Client.TableProjectionRouteBytes.{route}"
                ).inc(nbytes)
        return parts[0] if len(parts) == 1 else pa.concat_tables(parts)


def open_parquet(fs, path: str):
    """ParquetFile over the caching FS client (the legacy/unplanned
    entry point — pyarrow drives every range itself)."""
    import pyarrow.parquet as pq

    info = fs.get_status(path)
    return pq.ParquetFile(_SizedStream(fs.open_file(path), info.length))


def _read_one_legacy(fs, path: str, columns):
    return open_parquet(fs, path).read(columns=columns)


def _pushdown_conf(fs):
    """The client conf when pushdown is on, else None (legacy path).
    Fakes/wrappers without a ``conf`` attribute read legacy."""
    conf = getattr(fs, "conf", None)
    if conf is None:
        return None
    from alluxio_tpu.conf import Keys

    return conf if conf.get_bool(Keys.USER_TABLE_PUSHDOWN_ENABLED) \
        else None


def _read_one(fs, path: str, columns, conf):
    if conf is not None:
        try:
            return _PlannedRead(fs, path, columns, conf).run()
        except _plan.ParquetPlanError:
            # unplannable file: the legacy path surfaces the canonical
            # pyarrow error (or succeeds, e.g. exotic footers)
            pass
    return _read_one_legacy(fs, path, columns)


def read_columns(fs, paths: Sequence[str],
                 columns: Optional[List[str]] = None):
    """Read (a projection of) one or more Parquet files into a single
    pyarrow Table. ``columns=None`` reads everything.

    Multi-file reads fan out over a bounded executor
    (``atpu.user.table.read.parallelism``) so partition-spanning
    projections overlap their footer fetches and transfers instead of
    running file-serial."""
    import pyarrow as pa

    paths = list(paths)
    conf = _pushdown_conf(fs)
    fanout = 1
    if conf is not None:
        from alluxio_tpu.conf import Keys

        fanout = max(1, conf.get_int(Keys.USER_TABLE_READ_PARALLELISM))
    if len(paths) > 1 and fanout > 1:
        with ThreadPoolExecutor(
                max_workers=min(fanout, len(paths)),
                thread_name_prefix="atpu-table-file") as pool:
            tables = list(pool.map(
                lambda p: _read_one(fs, p, columns, conf), paths))
    else:
        tables = [_read_one(fs, p, columns, conf) for p in paths]
    return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


def read_partition_columns(fs, table_wire: dict, *,
                           columns: Optional[List[str]] = None,
                           partition_filter=None):
    """Projection over a catalog table's partitions.

    ``partition_filter(values: dict) -> bool`` prunes partitions before
    any IO (the catalog's partition pruning); returns a pyarrow Table.
    """
    paths: List[str] = []
    for part in table_wire["partitions"]:
        if partition_filter is not None and \
                not partition_filter(part.get("values", {})):
            continue
        for info in fs.list_status(part["location"]):
            if not info.folder and info.name.endswith(".parquet"):
                paths.append(f"{part['location']}/{info.name}")
    if not paths:
        import pyarrow as pa

        return pa.table({})
    return read_columns(fs, paths, columns=columns)
