"""Lightweight distributed span tracing + device-profiler bridge.

Re-design of the reference's tracing/profiling surface (SURVEY §5.1:
opentelemetry-style server spans + worker-side profiling hooks): a
process-local ring of recent spans with nesting via contextvars, cheap
enough to leave compiled in — recording is O(1) deque appends gated on
one bool — plus the TPU side: ``device_trace`` wraps
``jax.profiler.start_trace`` (xprof capture: MXU occupancy, HBM reads,
ICI traffic) and ``annotate`` threads host-span names onto the device
timeline so loader stages line up with XLA ops in the trace viewer.

Cross-process stitching: every span carries a W3C-traceparent-style
context (``trace_id``, parent ``span_id``, sampled flag). Client stubs
inject ``current_traceparent()`` into RPC metadata; server wrappers
``bind_remote_parent()`` before opening their span, so a read that
crosses client -> worker -> UFS is ONE trace, not three fragments.
Workers drain completed spans to the master on the metrics heartbeat
(``Tracer.drain``); the master stitches them with its own ring in
``TraceStore`` and serves the merged view at ``/api/v1/master/trace``.

Spans surface at ``/api/v1/master/trace`` (master web) and via
``Tracer.snapshot()`` anywhere else. Config: ``atpu.trace.enabled``,
``atpu.trace.sample.rate``, ``atpu.trace.ring.capacity``.
"""

from __future__ import annotations

import contextvars
import os
import random
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, Iterator, List, NamedTuple, Optional

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "atpu_span", default=None)
#: inbound trace context (parsed from RPC metadata) — the parent of the
#: next span opened on this thread of execution when no local span is live
_remote_parent: contextvars.ContextVar = contextvars.ContextVar(
    "atpu_remote_parent", default=None)

_RING_CAP = 4096

#: RPC metadata key carrying the serialized context (gRPC metadata keys
#: must be lowercase)
TRACEPARENT_KEY = "atpu-traceparent"

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")

#: The phase-name registry. Every ``Span.phase()`` emit site must use
#: one of these names — atpu-lint's phase analyzer resolves emit sites
#: against this catalog (near-miss typos flagged), and the critical-path
#: analyzer (utils/critical_path.py) attributes span self-time to them.
#: A phase is a *typed slice of wall time inside one span*; it may
#: overlap a child span's interval (e.g. the client's ``wire`` wait
#: covers the server's whole span) — the critical-path analyzer scales
#: phases down to the span's own self-time so nothing double-counts.
PHASES = (
    "queue_wait",   # waiting in an executor/dispatch queue before work ran
    "lock_wait",    # blocked acquiring a block/metadata lock
    "admission",    # QoS admission-control decision on the server
    "serialize",    # msgpack pack/unpack of RPC payloads
    "wire",         # client-observed RPC wait (network + remote service)
    "ufs_fetch",    # reading bytes out of the under-store
    "cache_fill",   # writing fetched bytes into the tiered store
    "tier_read",    # reading bytes out of a local tier
    "device_put",   # host->device transfer (shm staging / jax device_put)
    "drain",        # consumer draining/assembling delivered chunks
    "shm_map",      # mmap-ing a leased same-host SHM segment
    "lease_wait",   # client-observed shm_open/shm_renew lease RPC wait
    "batch_read",   # server-side scatter/gather assembly of a read_many
    "native_exec",  # GIL-free native execution of a packed read plan
    "table_plan",   # parquet footer fetch/parse + projection range planning
    "table_decode", # pyarrow decode of a planned row group's column chunks
)


class TraceContext(NamedTuple):
    """The propagated slice of a span: W3C trace-context fields."""

    trace_id: str  # 32 lowercase hex chars, not all-zero
    span_id: str   # 16 lowercase hex chars, not all-zero
    sampled: bool


#: id source — a PRNG seeded from the OS, NOT os.urandom per id: ids
#: need uniqueness, not unpredictability, and the urandom syscall costs
#: ~27us/call (measured) — 100x the rest of a span's bookkeeping.
#: Re-seeded on fork so child processes never mint colliding ids.
_ids = random.Random(int.from_bytes(os.urandom(16), "big"))
if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=lambda: _ids.seed(
        int.from_bytes(os.urandom(16), "big")))


def new_trace_id() -> str:
    return f"{_ids.getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_ids.getrandbits(64):016x}"


def format_traceparent(ctx: TraceContext) -> str:
    """``00-<trace_id>-<span_id>-<flags>`` (W3C traceparent, version 00)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-{'01' if ctx.sampled else '00'}"


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    """Parse a traceparent header; None on anything malformed (a bad
    header must degrade to 'new root trace', never to an error)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(str(value).strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, bool(int(flags, 16) & 1))


def current_trace_context() -> Optional[TraceContext]:
    """The context a child span (or outbound RPC) should join: the live
    local span first, else an inbound remote parent."""
    span = _current_span.get()
    if span is not None:
        return TraceContext(span.trace_id, span.span_id, span.sampled)
    return _remote_parent.get()


def current_traceparent() -> Optional[str]:
    """Serialized context for RPC injection; None when tracing is off or
    nothing is being traced (so the metadata stays untouched)."""
    if not _TRACER.enabled:
        return None
    ctx = current_trace_context()
    return None if ctx is None else format_traceparent(ctx)


def bind_remote_parent(header: Optional[str]):
    """Bind an inbound traceparent as this execution's parent context.
    Returns a reset token (None when the header is absent/invalid)."""
    ctx = parse_traceparent(header)
    if ctx is None:
        return None
    return _remote_parent.set(ctx)


def reset_remote_parent(token) -> None:
    if token is not None:
        _remote_parent.reset(token)


class Span:
    __slots__ = ("name", "start_ms", "duration_ms", "parent", "span_id",
                 "trace_id", "sampled", "tags", "thread", "error",
                 "phases")

    def __init__(self, name: str, span_id: str, parent: Optional[str],
                 trace_id: str, sampled: bool = True) -> None:
        self.name = name
        self.span_id = span_id
        self.parent = parent
        self.trace_id = trace_id
        self.sampled = sampled
        self.start_ms = time.time() * 1000.0
        self.duration_ms: Optional[float] = None
        self.tags: Dict[str, str] = {}
        self.thread = threading.current_thread().name
        self.error: Optional[str] = None
        #: typed phase events: [name, duration_ms] in emit order; lazily
        #: allocated so spans that never record a phase pay nothing
        self.phases: Optional[list] = None

    def phase(self, name: str, duration_ms: float) -> None:
        """Record a typed phase event (one of ``PHASES``) inside this
        span. O(1) list append; call sites hold the span object (from
        ``with tracer().span(...) as sp`` or ``current_span()``) and
        guard on ``sp is not None``, so the tracing-disabled path never
        reaches here — that guard IS the zero-cost-when-off contract."""
        p = self.phases
        if p is None:
            p = self.phases = []
        p.append((name, duration_ms))

    def to_dict(self) -> dict:
        d = {
            "name": self.name, "span_id": self.span_id,
            "parent": self.parent, "trace_id": self.trace_id,
            "start_ms": round(self.start_ms, 3),
            "duration_ms": None if self.duration_ms is None
            else round(self.duration_ms, 3),
            "thread": self.thread, "tags": self.tags,
            "error": self.error,
        }
        if self.phases:
            d["phases"] = [[n, round(ms, 3)] for n, ms in self.phases]
        return d


class Tracer:
    """Process tracer: bounded ring of completed spans."""

    def __init__(self, capacity: int = _RING_CAP) -> None:
        self.enabled = False
        #: probability a NEW ROOT trace is recorded; children (local and
        #: remote) inherit their parent's decision so traces never tear
        self.sample_rate = 1.0
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def configure(self, *, capacity: Optional[int] = None,
                  sample_rate: Optional[float] = None) -> None:
        if sample_rate is not None:
            self.sample_rate = min(1.0, max(0.0, float(sample_rate)))
        if capacity is not None and capacity != self._ring.maxlen:
            with self._lock:
                self._ring = deque(self._ring, maxlen=max(1, int(capacity)))

    def _sample(self) -> bool:
        rate = self.sample_rate
        return rate >= 1.0 or (rate > 0.0 and random.random() < rate)

    def span(self, name: str, **tags: str):
        """Context manager recording one span (no-op when disabled)."""
        return _SpanCtx(self, name, tags)

    def record(self, span: Span) -> None:
        self._ring.append(span)

    def snapshot(self, limit: int = 500,
                 prefix: str = "") -> List[dict]:
        """Most-recent-first dump of completed spans."""
        out = []
        # atomic copy first: iterating the live deque races concurrent
        # record() appends ("deque mutated during iteration")
        for s in reversed(list(self._ring)):
            if prefix and not s.name.startswith(prefix):
                continue
            out.append(s.to_dict())
            if len(out) >= limit:
                break
        return out

    def drain(self, limit: int = 500) -> List[dict]:
        """Pop up to ``limit`` completed spans, oldest first — the
        heartbeat shipping path (spans move to the master's TraceStore
        instead of aging out of this ring)."""
        out: List[dict] = []
        while len(out) < limit:
            try:
                out.append(self._ring.popleft().to_dict())
            except IndexError:
                break
        return out

    def clear(self) -> None:
        self._ring.clear()


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_tags", "_span", "_token", "_t0")

    def __init__(self, tracer: Tracer, name: str,
                 tags: Dict[str, str]) -> None:
        self._tracer = tracer
        self._name = name
        self._tags = tags
        self._span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Optional[Span]:
        if not self._tracer.enabled:
            return None
        parent = _current_span.get()
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
            sampled = parent.sampled
        else:
            remote = _remote_parent.get()
            if remote is not None:
                trace_id, parent_id = remote.trace_id, remote.span_id
                sampled = remote.sampled
            else:  # new root: this is where the sampling decision lands
                trace_id, parent_id = new_trace_id(), None
                sampled = self._tracer._sample()
        self._span = Span(self._name, new_span_id(), parent_id,
                          trace_id, sampled)
        if self._tags:
            self._span.tags.update(
                {k: str(v) for k, v in self._tags.items()})
        self._token = _current_span.set(self._span)
        self._t0 = time.perf_counter()
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._span is not None:
            self._span.duration_ms = \
                (time.perf_counter() - self._t0) * 1000.0
            if exc is not None:
                self._span.error = f"{type(exc).__name__}: {exc}"
            _current_span.reset(self._token)
            if self._span.sampled:
                self._tracer.record(self._span)
        return False


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def current_span() -> Optional[Span]:
    """The live local span on this thread of execution, if any — the
    handle phase emit sites use when the span was opened further up the
    stack (e.g. the RPC server wrapper owns the span, the service
    handler records the phases). One contextvar read; None whenever
    tracing is off or the caller is outside any span."""
    return _current_span.get()


def set_tracing_enabled(on: bool) -> None:
    _TRACER.enabled = bool(on)


def apply_trace_conf(conf) -> None:
    """Apply ``atpu.trace.sample.rate`` / ``atpu.trace.ring.capacity``
    to the process tracer (the enabled flag stays with the caller — the
    client only ever turns tracing ON, servers set it absolutely)."""
    from alluxio_tpu.conf import Keys

    _TRACER.configure(
        capacity=conf.get_int(Keys.TRACE_RING_CAPACITY),
        sample_rate=conf.get_float(Keys.TRACE_SAMPLE_RATE))


# -- master-side stitching ---------------------------------------------------
class TraceStore:
    """Spans shipped from remote processes (workers/clients drain their
    rings on the metrics heartbeat), deduplicated by (trace_id, span_id)
    so an in-process cluster — where every role shares one ring — never
    double-serves a span the reporter also shipped."""

    def __init__(self, capacity: int = 8192) -> None:
        self._ring: deque = deque(maxlen=capacity)
        self._seen: "OrderedDict[tuple, bool]" = OrderedDict()
        self._seen_cap = capacity * 2
        self._lock = threading.Lock()

    def ingest(self, source: str, spans: Optional[List[dict]]) -> int:
        n = 0
        with self._lock:
            for s in spans or ():
                if not isinstance(s, dict):
                    continue
                key = (s.get("trace_id"), s.get("span_id"))
                if key in self._seen:
                    continue
                self._seen[key] = True
                while len(self._seen) > self._seen_cap:
                    self._seen.popitem(last=False)
                d = dict(s)
                d.setdefault("source", source)
                self._ring.append(d)
                n += 1
        return n

    def snapshot(self, limit: int = 500, prefix: str = "",
                 trace_id: str = "") -> List[dict]:
        with self._lock:
            items = list(self._ring)
        out = []
        for s in reversed(items):
            if prefix and not str(s.get("name", "")).startswith(prefix):
                continue
            if trace_id and s.get("trace_id") != trace_id:
                continue
            out.append(s)
            if len(out) >= limit:
                break
        return out

    def span_count(self) -> int:
        with self._lock:
            return len(self._ring)


def stitch_spans(store: Optional[TraceStore], *, limit: int = 500,
                 prefix: str = "", trace_id: str = "",
                 local_source: str = "local") -> dict:
    """Merge the process-local ring with remotely-shipped spans into one
    view: a flat most-recent-first span list plus a per-trace summary
    (what ``/api/v1/master/trace`` and ``fsadmin trace`` serve)."""
    spans: List[dict] = []
    seen = set()
    # a trace_id filter scans the whole ring: the wanted trace's spans
    # may sit past the first `limit` recent spans of OTHER traces
    # (the ACTUAL configured capacity, not the default constant)
    scan = max(limit, _TRACER._ring.maxlen or _RING_CAP) \
        if trace_id else limit
    local = _TRACER.snapshot(limit=scan, prefix=prefix)
    for s in local:
        if trace_id and s.get("trace_id") != trace_id:
            continue
        s = dict(s)
        s.setdefault("source", local_source)
        seen.add((s.get("trace_id"), s.get("span_id")))
        spans.append(s)
    if store is not None:
        for s in store.snapshot(limit=limit, prefix=prefix,
                                trace_id=trace_id):
            key = (s.get("trace_id"), s.get("span_id"))
            if key in seen:
                continue
            seen.add(key)
            spans.append(s)
    spans.sort(key=lambda s: s.get("start_ms") or 0.0, reverse=True)
    del spans[limit:]
    return {"spans": spans, "traces": summarize_traces(spans)}


def summarize_traces(spans: List[dict]) -> List[dict]:
    """Per-trace rollup of a most-recent-first span list (span count,
    contributing sources, root name, wall duration). Shared by
    :func:`stitch_spans` and the HA fan-out merge."""
    traces: "OrderedDict[str, dict]" = OrderedDict()
    for s in spans:
        tid = s.get("trace_id")
        if not tid:
            continue
        t = traces.get(tid)
        if t is None:
            t = traces[tid] = {"trace_id": tid, "spans": 0,
                               "sources": [], "root": None,
                               "start_ms": None, "end_ms": None}
        t["spans"] += 1
        src = s.get("source")
        if src and src not in t["sources"]:
            t["sources"].append(src)
        if s.get("parent") is None:
            t["root"] = s.get("name")
        start = s.get("start_ms")
        if start is not None:
            end = start + (s.get("duration_ms") or 0.0)
            t["start_ms"] = start if t["start_ms"] is None \
                else min(t["start_ms"], start)
            t["end_ms"] = end if t["end_ms"] is None \
                else max(t["end_ms"], end)
    for t in traces.values():
        t["duration_ms"] = None if t["start_ms"] is None \
            else round(t["end_ms"] - t["start_ms"], 3)
        t.pop("end_ms", None)
    return list(traces.values())


# -- device-side (TPU) bridge ------------------------------------------------
class device_trace:
    """Capture an xprof/TensorBoard trace of everything the device does
    inside the block (compiled op timeline, HBM traffic). Usage::

        with device_trace("/tmp/xprof"):
            train_step(...)
            jax.block_until_ready(loss)
    """

    def __init__(self, log_dir: str) -> None:
        self._dir = log_dir

    def __enter__(self) -> "device_trace":
        import jax

        jax.profiler.start_trace(self._dir)
        return self

    def __exit__(self, *exc) -> bool:
        import jax

        jax.profiler.stop_trace()
        return False


_TA = None  # resolved TraceAnnotation class (False = jax unavailable)


def annotate(name: str):
    """Host-span name on the DEVICE timeline (shows up in xprof around
    whatever the annotated host code dispatches). Also records a host
    span when tracing is enabled, so host and device views correlate.
    The jax lookup is resolved once; per-call cost is one class
    construction (a no-op C object outside an active capture)."""
    import contextlib

    global _TA
    if _TA is None:
        try:
            import jax

            _TA = jax.profiler.TraceAnnotation
        except Exception:  # noqa: BLE001 - no jax in control-plane procs
            _TA = False
    dev = _TA(name) if _TA else contextlib.nullcontext()

    @contextlib.contextmanager
    def both() -> Iterator[None]:
        with _TRACER.span(name):
            with dev:
                yield

    return both()
