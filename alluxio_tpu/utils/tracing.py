"""Lightweight span tracing + device-profiler bridge.

Re-design of the reference's tracing/profiling surface (SURVEY §5.1:
opentelemetry-style server spans + worker-side profiling hooks): a
process-local ring of recent spans with nesting via contextvars, cheap
enough to leave compiled in — recording is O(1) deque appends gated on
one bool — plus the TPU side: ``device_trace`` wraps
``jax.profiler.start_trace`` (xprof capture: MXU occupancy, HBM reads,
ICI traffic) and ``annotate`` threads host-span names onto the device
timeline so loader stages line up with XLA ops in the trace viewer.

Spans surface at ``/api/v1/master/trace`` (master web) and via
``Tracer.snapshot()`` anywhere else.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from typing import Dict, Iterator, List, Optional

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "atpu_span", default=None)

_RING_CAP = 4096


class Span:
    __slots__ = ("name", "start_ms", "duration_ms", "parent", "span_id",
                 "tags", "thread", "error")

    def __init__(self, name: str, span_id: int,
                 parent: Optional[int]) -> None:
        self.name = name
        self.span_id = span_id
        self.parent = parent
        self.start_ms = time.time() * 1000.0
        self.duration_ms: Optional[float] = None
        self.tags: Dict[str, str] = {}
        self.thread = threading.current_thread().name
        self.error: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "name": self.name, "span_id": self.span_id,
            "parent": self.parent, "start_ms": round(self.start_ms, 3),
            "duration_ms": None if self.duration_ms is None
            else round(self.duration_ms, 3),
            "thread": self.thread, "tags": self.tags,
            "error": self.error,
        }


class Tracer:
    """Process tracer: bounded ring of completed spans."""

    def __init__(self, capacity: int = _RING_CAP) -> None:
        self.enabled = False
        self._ring: deque = deque(maxlen=capacity)
        self._next_id = 1
        self._lock = threading.Lock()

    def _new_id(self) -> int:
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            return sid

    def span(self, name: str, **tags: str):
        """Context manager recording one span (no-op when disabled)."""
        return _SpanCtx(self, name, tags)

    def record(self, span: Span) -> None:
        self._ring.append(span)

    def snapshot(self, limit: int = 500,
                 prefix: str = "") -> List[dict]:
        """Most-recent-first dump of completed spans."""
        out = []
        # atomic copy first: iterating the live deque races concurrent
        # record() appends ("deque mutated during iteration")
        for s in reversed(list(self._ring)):
            if prefix and not s.name.startswith(prefix):
                continue
            out.append(s.to_dict())
            if len(out) >= limit:
                break
        return out

    def clear(self) -> None:
        self._ring.clear()


class _SpanCtx:
    __slots__ = ("_tracer", "_name", "_tags", "_span", "_token", "_t0")

    def __init__(self, tracer: Tracer, name: str,
                 tags: Dict[str, str]) -> None:
        self._tracer = tracer
        self._name = name
        self._tags = tags
        self._span: Optional[Span] = None
        self._token = None

    def __enter__(self) -> Optional[Span]:
        if not self._tracer.enabled:
            return None
        parent = _current_span.get()
        self._span = Span(self._name, self._tracer._new_id(),
                          parent.span_id if parent else None)
        if self._tags:
            self._span.tags.update(
                {k: str(v) for k, v in self._tags.items()})
        self._token = _current_span.set(self._span)
        self._t0 = time.perf_counter()
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._span is not None:
            self._span.duration_ms = \
                (time.perf_counter() - self._t0) * 1000.0
            if exc is not None:
                self._span.error = f"{type(exc).__name__}: {exc}"
            _current_span.reset(self._token)
            self._tracer.record(self._span)
        return False


_TRACER = Tracer()


def tracer() -> Tracer:
    return _TRACER


def set_tracing_enabled(on: bool) -> None:
    _TRACER.enabled = bool(on)


# -- device-side (TPU) bridge ------------------------------------------------
class device_trace:
    """Capture an xprof/TensorBoard trace of everything the device does
    inside the block (compiled op timeline, HBM traffic). Usage::

        with device_trace("/tmp/xprof"):
            train_step(...)
            jax.block_until_ready(loss)
    """

    def __init__(self, log_dir: str) -> None:
        self._dir = log_dir

    def __enter__(self) -> "device_trace":
        import jax

        jax.profiler.start_trace(self._dir)
        return self

    def __exit__(self, *exc) -> bool:
        import jax

        jax.profiler.stop_trace()
        return False


_TA = None  # resolved TraceAnnotation class (False = jax unavailable)


def annotate(name: str):
    """Host-span name on the DEVICE timeline (shows up in xprof around
    whatever the annotated host code dispatches). Also records a host
    span when tracing is enabled, so host and device views correlate.
    The jax lookup is resolved once; per-call cost is one class
    construction (a no-op C object outside an active capture)."""
    import contextlib

    global _TA
    if _TA is None:
        try:
            import jax

            _TA = jax.profiler.TraceAnnotation
        except Exception:  # noqa: BLE001 - no jax in control-plane procs
            _TA = False
    dev = _TA(name) if _TA else contextlib.nullcontext()

    @contextlib.contextmanager
    def both() -> Iterator[None]:
        with _TRACER.span(name):
            with dev:
                yield

    return both()
