"""Wire types crossing RPC boundaries.

Re-designs of ``core/common/src/main/java/alluxio/wire/`` (``FileInfo``,
``BlockInfo``, ``BlockLocation``, ``WorkerInfo``, ``WorkerNetAddress``,
``MountPointInfo``) and the locality model ``wire/TieredIdentity.java:36,69``
— re-thought for TPU topology: locality tiers are ``host`` (same TPU VM,
short-circuit shm), ``slice`` (same ICI domain, collective transfers), ``pod``
(same pod, ICI across slices on v4+/DCN otherwise), then DCN.

All types serialize to/from plain dicts (msgpack-friendly) via
``to_wire``/``from_wire``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def _wire_dataclass(cls):
    """Attach dict (de)serialization to a dataclass.

    The converters are SPECIALIZED lazily on first use (the ``_NESTED``
    registry below is only complete once the module finishes loading):
    plain scalar fields ride a single ``__dict__`` copy, containers get
    a shallow copy, and only fields registered in ``_NESTED`` pay the
    recursive conversion. The generic per-field getattr/hasattr loop
    this replaces was the top CPU item in master list_status profiles
    (~39 us per 30-field FileInfo; now ~6 us)."""
    fields_ = dataclasses.fields(cls)
    _names = tuple(f.name for f in fields_)
    _containers = tuple(
        f.name for f in fields_
        if any(t in str(f.type) for t in ("List", "Dict", "list", "dict")))
    spec: Dict[str, Any] = {}

    def _specialize() -> tuple:
        nested = tuple(n for (c, n), _ in _NESTED.items()
                       if c == cls.__name__)
        plain_dicts = frozenset(f.name for f in fields_
                                if _is_plain_dict_field(f))
        copy_only = tuple(n for n in _containers if n not in nested)
        # ONE atomic assignment: concurrent first callers must never
        # observe a half-built spec
        s = (nested, copy_only, plain_dicts)
        spec["s"] = s
        return s

    def to_wire(self) -> Dict[str, Any]:
        nested, copy_only, _ = spec.get("s") or _specialize()
        known = self._wire_names
        out = {k: v for k, v in self.__dict__.items() if k in known}
        for n in copy_only:
            v = out[n]
            if v is not None:
                out[n] = v.copy()
        for n in nested:
            v = out[n]
            if v is None:
                continue
            if isinstance(v, list):
                out[n] = [x.to_wire() if hasattr(x, "to_wire") else x
                          for x in v]
            elif isinstance(v, dict):
                out[n] = {k: (x.to_wire() if hasattr(x, "to_wire") else x)
                          for k, x in v.items()}
            elif hasattr(v, "to_wire"):
                out[n] = v.to_wire()
        return out

    @classmethod
    def from_wire(klass, d: Dict[str, Any]):
        nested, _, plain_dicts = spec.get("s") or _specialize()
        known = klass._wire_names
        if d.keys() == known:
            # exact match (the overwhelmingly common case: our own
            # server's wire dict): one flat C-level copy, then adopt as
            # __dict__ — no filtered comprehension, no 30-kwarg
            # __init__. Listing fan-out decodes N of these per call, so
            # the per-key copy was the client-side hot spot. The copy
            # (not in-place adoption) keeps the CALLER's dict unmutated
            # — callers may retain it (journal payloads, the master's
            # listing cache), and rewriting nested dicts into dataclass
            # objects inside it would corrupt it for re-serialization.
            d = dict(d)
            for n in nested:
                v = d[n]
                if v is None:
                    continue
                sub = _NESTED[(klass.__name__, n)]
                if isinstance(v, list):
                    d[n] = [sub.from_wire(x) if isinstance(x, dict)
                            else x for x in v]
                elif isinstance(v, dict) and n not in plain_dicts:
                    d[n] = sub.from_wire(v)
            obj = object.__new__(klass)
            obj.__dict__ = d
            return obj
        kwargs = {k: v for k, v in d.items() if k in known}
        for n in nested:
            v = kwargs.get(n)
            if v is None:
                continue
            sub = _NESTED[(klass.__name__, n)]
            if isinstance(v, list):
                kwargs[n] = [sub.from_wire(x) if isinstance(x, dict)
                             else x for x in v]
            elif isinstance(v, dict) and n not in plain_dicts:
                kwargs[n] = sub.from_wire(v)
        if len(kwargs) == len(known):
            # complete wire dict (the overwhelmingly common case: our
            # own server sent it): adopt it as __dict__ directly and
            # skip the 30-kwarg __init__ — ~2x faster per entry, which
            # matters at listing fan-out. Partial dicts (forward/back
            # compat) take the kwargs path for defaulting.
            obj = object.__new__(klass)
            obj.__dict__ = kwargs
            return obj
        return klass(**kwargs)

    cls._wire_names = frozenset(_names)
    cls.to_wire = to_wire
    cls.from_wire = from_wire
    return cls


def _is_plain_dict_field(f) -> bool:
    return "Dict" in str(f.type) or "dict" in str(f.type)


_NESTED: Dict[tuple, type] = {}


@_wire_dataclass
@dataclass
class LocalityTier:
    """One (tier-name, value) locality pair, e.g. ("slice", "slice-0")."""

    tier: str = ""
    value: str = ""


#: Ordered tier names, closest first. TPU-native ordering (SURVEY.md 2.11).
LOCALITY_ORDER = ("host", "slice", "pod", "region")


@_wire_dataclass
@dataclass
class TieredIdentity:
    """Ordered locality identity (reference: ``wire/TieredIdentity.java:36``).

    ``closeness`` replaces the reference's nearest-match resolution
    (``TieredIdentity.java:69``): lower is closer; tie broken by tier order.
    """

    tiers: List[LocalityTier] = field(default_factory=list)

    def value(self, tier: str) -> Optional[str]:
        for t in self.tiers:
            if t.tier == tier:
                return t.value
        return None

    def closeness(self, other: "TieredIdentity") -> int:
        """0 = same host; k = first k locality tiers differ; large = remote."""
        for i, name in enumerate(LOCALITY_ORDER):
            mine, theirs = self.value(name), other.value(name)
            if mine is not None and mine == theirs:
                return i
        return len(LOCALITY_ORDER)

    def nearest(self, candidates: List["TieredIdentity"]) -> Optional[int]:
        """Index of the closest candidate, or None if empty."""
        if not candidates:
            return None
        scored = [(self.closeness(c), i) for i, c in enumerate(candidates)]
        return min(scored)[1]

    @staticmethod
    def from_spec(spec: "List[str] | str | None", hostname: str = "") -> "TieredIdentity":
        """Parse ``["host=h","slice=s"]`` / ``"host=h,slice=s"`` specs."""
        tiers: List[LocalityTier] = []
        if spec:
            parts = spec.split(",") if isinstance(spec, str) else spec
            for p in parts:
                if "=" in p:
                    k, _, v = p.partition("=")
                    tiers.append(LocalityTier(k.strip(), v.strip()))
        if hostname and not any(t.tier == "host" for t in tiers):
            tiers.insert(0, LocalityTier("host", hostname))
        return TieredIdentity(tiers)


_NESTED[("TieredIdentity", "tiers")] = LocalityTier


@_wire_dataclass
@dataclass
class WorkerNetAddress:
    host: str = ""
    rpc_port: int = 0
    data_port: int = 0
    web_port: int = 0
    domain_socket_path: str = ""
    #: Same-host shm dir for short-circuit mmap reads (TPU-native analogue of
    #: the reference's short-circuit block paths).
    shm_dir: str = ""
    tiered_identity: TieredIdentity = field(default_factory=TieredIdentity)

    def key(self) -> str:
        return f"{self.host}:{self.rpc_port}"


_NESTED[("WorkerNetAddress", "tiered_identity")] = TieredIdentity


@_wire_dataclass
@dataclass
class BlockLocation:
    worker_id: int = 0
    address: WorkerNetAddress = field(default_factory=WorkerNetAddress)
    tier_alias: str = "MEM"
    medium_type: str = ""


_NESTED[("BlockLocation", "address")] = WorkerNetAddress


@_wire_dataclass
@dataclass
class BlockInfo:
    block_id: int = 0
    length: int = 0
    locations: List[BlockLocation] = field(default_factory=list)
    #: HBM (device-mesh) residency reported by JAX clients — kept
    #: SEPARATE from ``locations``: these are not worker-served replicas
    #: (no data server behind them), so replication counting and the
    #: worker read path must not see them
    device_locations: List[BlockLocation] = field(default_factory=list)


_NESTED[("BlockInfo", "locations")] = BlockLocation
_NESTED[("BlockInfo", "device_locations")] = BlockLocation


@_wire_dataclass
@dataclass
class FileBlockInfo:
    block_info: BlockInfo = field(default_factory=BlockInfo)
    offset: int = 0
    ufs_locations: List[str] = field(default_factory=list)


_NESTED[("FileBlockInfo", "block_info")] = BlockInfo


@_wire_dataclass
@dataclass
class FileInfo:
    file_id: int = 0
    name: str = ""
    path: str = ""
    ufs_path: str = ""
    length: int = 0
    block_size_bytes: int = 0
    creation_time_ms: int = 0
    last_modification_time_ms: int = 0
    last_access_time_ms: int = 0
    completed: bool = False
    folder: bool = False
    pinned: bool = False
    pinned_media: List[str] = field(default_factory=list)
    cacheable: bool = True
    persisted: bool = False
    persistence_state: str = "NOT_PERSISTED"
    block_ids: List[int] = field(default_factory=list)
    in_memory_percentage: int = 0
    ttl: int = -1
    ttl_action: str = "DELETE"
    owner: str = ""
    group: str = ""
    mode: int = 0o644
    mount_point: bool = False
    mount_id: int = 0
    replication_min: int = 0
    replication_max: int = -1
    file_block_infos: List[FileBlockInfo] = field(default_factory=list)
    xattr: Dict[str, str] = field(default_factory=dict)


_NESTED[("FileInfo", "file_block_infos")] = FileBlockInfo


@_wire_dataclass
@dataclass
class WorkerInfo:
    id: int = 0
    address: WorkerNetAddress = field(default_factory=WorkerNetAddress)
    state: str = "LIVE"
    capacity_bytes: int = 0
    used_bytes: int = 0
    start_time_ms: int = 0
    last_contact_ms: int = 0
    capacity_bytes_on_tiers: Dict[str, int] = field(default_factory=dict)
    used_bytes_on_tiers: Dict[str, int] = field(default_factory=dict)
    block_count: int = 0


_NESTED[("WorkerInfo", "address")] = WorkerNetAddress


@_wire_dataclass
@dataclass
class MountPointInfo:
    alluxio_path: str = ""
    ufs_uri: str = ""
    ufs_type: str = ""
    ufs_capacity_bytes: int = -1
    ufs_used_bytes: int = -1
    read_only: bool = False
    shared: bool = False
    mount_id: int = 0
    properties: Dict[str, str] = field(default_factory=dict)


@_wire_dataclass
@dataclass
class MasterInfo:
    leader_master_address: str = ""
    master_addresses: List[str] = field(default_factory=list)
    rpc_port: int = 0
    safe_mode: bool = False
    start_time_ms: int = 0
    up_time_ms: int = 0
    version: str = ""
    cluster_id: str = ""
