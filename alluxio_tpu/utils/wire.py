"""Wire types crossing RPC boundaries.

Re-designs of ``core/common/src/main/java/alluxio/wire/`` (``FileInfo``,
``BlockInfo``, ``BlockLocation``, ``WorkerInfo``, ``WorkerNetAddress``,
``MountPointInfo``) and the locality model ``wire/TieredIdentity.java:36,69``
— re-thought for TPU topology: locality tiers are ``host`` (same TPU VM,
short-circuit shm), ``slice`` (same ICI domain, collective transfers), ``pod``
(same pod, ICI across slices on v4+/DCN otherwise), then DCN.

All types serialize to/from plain dicts (msgpack-friendly) via
``to_wire``/``from_wire``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


def _wire_dataclass(cls):
    """Attach dict (de)serialization to a dataclass, recursing into fields."""

    def to_wire(self) -> Dict[str, Any]:
        out = {}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if hasattr(v, "to_wire"):
                v = v.to_wire()
            elif isinstance(v, list):
                v = [x.to_wire() if hasattr(x, "to_wire") else x for x in v]
            elif isinstance(v, dict):
                v = {k: (x.to_wire() if hasattr(x, "to_wire") else x)
                     for k, x in v.items()}
            out[f.name] = v
        return out

    @classmethod
    def from_wire(klass, d: Dict[str, Any]):
        kwargs = {}
        hints = {f.name: f for f in dataclasses.fields(klass)}
        for name, f in hints.items():
            if name not in d:
                continue
            v = d[name]
            sub = _NESTED.get((klass.__name__, name))
            if sub is not None and v is not None:
                if isinstance(v, list):
                    v = [sub.from_wire(x) if isinstance(x, dict) else x for x in v]
                elif isinstance(v, dict) and not _is_plain_dict_field(f):
                    v = sub.from_wire(v)
            kwargs[name] = v
        return klass(**kwargs)

    cls.to_wire = to_wire
    cls.from_wire = from_wire
    return cls


def _is_plain_dict_field(f) -> bool:
    return "Dict" in str(f.type) or "dict" in str(f.type)


_NESTED: Dict[tuple, type] = {}


@_wire_dataclass
@dataclass
class LocalityTier:
    """One (tier-name, value) locality pair, e.g. ("slice", "slice-0")."""

    tier: str = ""
    value: str = ""


#: Ordered tier names, closest first. TPU-native ordering (SURVEY.md 2.11).
LOCALITY_ORDER = ("host", "slice", "pod", "region")


@_wire_dataclass
@dataclass
class TieredIdentity:
    """Ordered locality identity (reference: ``wire/TieredIdentity.java:36``).

    ``closeness`` replaces the reference's nearest-match resolution
    (``TieredIdentity.java:69``): lower is closer; tie broken by tier order.
    """

    tiers: List[LocalityTier] = field(default_factory=list)

    def value(self, tier: str) -> Optional[str]:
        for t in self.tiers:
            if t.tier == tier:
                return t.value
        return None

    def closeness(self, other: "TieredIdentity") -> int:
        """0 = same host; k = first k locality tiers differ; large = remote."""
        for i, name in enumerate(LOCALITY_ORDER):
            mine, theirs = self.value(name), other.value(name)
            if mine is not None and mine == theirs:
                return i
        return len(LOCALITY_ORDER)

    def nearest(self, candidates: List["TieredIdentity"]) -> Optional[int]:
        """Index of the closest candidate, or None if empty."""
        if not candidates:
            return None
        scored = [(self.closeness(c), i) for i, c in enumerate(candidates)]
        return min(scored)[1]

    @staticmethod
    def from_spec(spec: "List[str] | str | None", hostname: str = "") -> "TieredIdentity":
        """Parse ``["host=h","slice=s"]`` / ``"host=h,slice=s"`` specs."""
        tiers: List[LocalityTier] = []
        if spec:
            parts = spec.split(",") if isinstance(spec, str) else spec
            for p in parts:
                if "=" in p:
                    k, _, v = p.partition("=")
                    tiers.append(LocalityTier(k.strip(), v.strip()))
        if hostname and not any(t.tier == "host" for t in tiers):
            tiers.insert(0, LocalityTier("host", hostname))
        return TieredIdentity(tiers)


_NESTED[("TieredIdentity", "tiers")] = LocalityTier


@_wire_dataclass
@dataclass
class WorkerNetAddress:
    host: str = ""
    rpc_port: int = 0
    data_port: int = 0
    web_port: int = 0
    domain_socket_path: str = ""
    #: Same-host shm dir for short-circuit mmap reads (TPU-native analogue of
    #: the reference's short-circuit block paths).
    shm_dir: str = ""
    tiered_identity: TieredIdentity = field(default_factory=TieredIdentity)

    def key(self) -> str:
        return f"{self.host}:{self.rpc_port}"


_NESTED[("WorkerNetAddress", "tiered_identity")] = TieredIdentity


@_wire_dataclass
@dataclass
class BlockLocation:
    worker_id: int = 0
    address: WorkerNetAddress = field(default_factory=WorkerNetAddress)
    tier_alias: str = "MEM"
    medium_type: str = ""


_NESTED[("BlockLocation", "address")] = WorkerNetAddress


@_wire_dataclass
@dataclass
class BlockInfo:
    block_id: int = 0
    length: int = 0
    locations: List[BlockLocation] = field(default_factory=list)
    #: HBM (device-mesh) residency reported by JAX clients — kept
    #: SEPARATE from ``locations``: these are not worker-served replicas
    #: (no data server behind them), so replication counting and the
    #: worker read path must not see them
    device_locations: List[BlockLocation] = field(default_factory=list)


_NESTED[("BlockInfo", "locations")] = BlockLocation
_NESTED[("BlockInfo", "device_locations")] = BlockLocation


@_wire_dataclass
@dataclass
class FileBlockInfo:
    block_info: BlockInfo = field(default_factory=BlockInfo)
    offset: int = 0
    ufs_locations: List[str] = field(default_factory=list)


_NESTED[("FileBlockInfo", "block_info")] = BlockInfo


@_wire_dataclass
@dataclass
class FileInfo:
    file_id: int = 0
    name: str = ""
    path: str = ""
    ufs_path: str = ""
    length: int = 0
    block_size_bytes: int = 0
    creation_time_ms: int = 0
    last_modification_time_ms: int = 0
    last_access_time_ms: int = 0
    completed: bool = False
    folder: bool = False
    pinned: bool = False
    pinned_media: List[str] = field(default_factory=list)
    cacheable: bool = True
    persisted: bool = False
    persistence_state: str = "NOT_PERSISTED"
    block_ids: List[int] = field(default_factory=list)
    in_memory_percentage: int = 0
    ttl: int = -1
    ttl_action: str = "DELETE"
    owner: str = ""
    group: str = ""
    mode: int = 0o644
    mount_point: bool = False
    mount_id: int = 0
    replication_min: int = 0
    replication_max: int = -1
    file_block_infos: List[FileBlockInfo] = field(default_factory=list)
    xattr: Dict[str, str] = field(default_factory=dict)


_NESTED[("FileInfo", "file_block_infos")] = FileBlockInfo


@_wire_dataclass
@dataclass
class WorkerInfo:
    id: int = 0
    address: WorkerNetAddress = field(default_factory=WorkerNetAddress)
    state: str = "LIVE"
    capacity_bytes: int = 0
    used_bytes: int = 0
    start_time_ms: int = 0
    last_contact_ms: int = 0
    capacity_bytes_on_tiers: Dict[str, int] = field(default_factory=dict)
    used_bytes_on_tiers: Dict[str, int] = field(default_factory=dict)
    block_count: int = 0


_NESTED[("WorkerInfo", "address")] = WorkerNetAddress


@_wire_dataclass
@dataclass
class MountPointInfo:
    alluxio_path: str = ""
    ufs_uri: str = ""
    ufs_type: str = ""
    ufs_capacity_bytes: int = -1
    ufs_used_bytes: int = -1
    read_only: bool = False
    shared: bool = False
    mount_id: int = 0
    properties: Dict[str, str] = field(default_factory=dict)


@_wire_dataclass
@dataclass
class MasterInfo:
    leader_master_address: str = ""
    master_addresses: List[str] = field(default_factory=list)
    rpc_port: int = 0
    safe_mode: bool = False
    start_time_ms: int = 0
    up_time_ms: int = 0
    version: str = ""
    cluster_id: str = ""
