"""In-memory ring buffer of recent log records for the web UIs.

The reference's logs pages tail log4j files; this build logs wherever
the operator pointed ``logging`` (stderr, files, the logserver), so the
dashboards serve a bounded in-process ring instead of guessing at file
paths — same operator value (recent events, one click) with no
filesystem coupling.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Dict, List

_LOCK = threading.Lock()
_HANDLER = None


class RingHandler(logging.Handler):
    def __init__(self, capacity: int = 2000) -> None:
        super().__init__(level=logging.INFO)
        self.records: deque = deque(maxlen=capacity)

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:  # noqa: BLE001 — never break logging
            msg = str(record.msg)
        self.records.append({
            "ts_ms": int(record.created * 1000),
            "level": record.levelname,
            "logger": record.name,
            "message": msg,
        })


def install() -> RingHandler:
    """Attach the ring to the root logger once; returns it."""
    global _HANDLER
    with _LOCK:
        if _HANDLER is None:
            _HANDLER = RingHandler()
            logging.getLogger().addHandler(_HANDLER)
        return _HANDLER


def tail(n: int = 200, level: str = "") -> List[Dict]:
    h = install()
    records = list(h.records)
    if level:
        want = level.upper()
        order = {"DEBUG": 10, "INFO": 20, "WARNING": 30, "ERROR": 40,
                 "CRITICAL": 50}
        floor = order.get(want, 0)
        records = [r for r in records
                   if order.get(r["level"], 0) >= floor]
    return records[-max(1, min(n, 2000)):]


def mark(msg: str) -> None:
    """Convenience for tests: land one record in the ring (warning
    level: the root logger's default level would drop INFO before any
    handler sees it)."""
    logging.getLogger("alluxio_tpu.weblog").warning(msg)
    _ = time  # keep import (record timestamps use logging's clock)
