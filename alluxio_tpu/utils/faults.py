"""Conf-gated fault injection for chaos and self-healing tests.

One process-wide injector (in-process miniclusters deliberately share
it) holds three faults, each scoped by an optional host/source
substring so a multi-worker cluster can break exactly one node:

- **read-latency inflation** — the worker's warm ``read_block`` path
  sleeps per chunk, inflating ``Worker.ReadBlockTime`` so the
  p99-regression health rule (and the remediation engine behind it)
  can be driven end to end;
- **heartbeat freeze** — the worker's metrics reporter silently skips
  its ticks, driving the heartbeat-staleness rule without killing the
  process;
- **UFS error rate** — a deterministic fraction of UFS stripe reads
  fail with an injected ``IOError`` (counter-based, not random: the
  Nth failure lands at the same read in every run);
- **RPC reject rate** — a deterministic fraction of master RPC
  dispatches is shed with the same typed ``ResourceExhausted`` +
  retry-after the admission controller emits, so admission shedding
  and client-side retry-after honoring can be chaos-tested end to end
  without a real flood.  The scope substring matches the RPC's
  ``service.method`` key (e.g. scope ``create_file`` rejects only
  CreateFile);
- **SHM map error rate** — a deterministic fraction of client-side
  SHM segment maps fail with an injected ``OSError``, drilling the
  same-host zero-copy path's transparent fallback to remote reads;
- **SHM lease deny rate** — a deterministic fraction of worker
  ``shm_open`` grants is denied as if the lease table were full,
  drilling lease-denied fallback without actually filling
  ``atpu.worker.shm.max.leases``;
- **native exec error rate** — a deterministic fraction of native
  fastpath batches fails mid-table (one op is poisoned, so earlier
  ops really write), drilling the byte-identical fallback from
  ``plan_exec.cpp`` to the pure-Python read path.

The HA chaos drill (docs/ha.md) adds four programmatic faults — set by
the minicluster / :class:`FaultPlan`, not by conf, since they only make
sense against an orchestrated multi-master cluster:

- **tailer freeze** — a standby's journal tailer (or Raft apply loop)
  stops applying: its advertised ``md_version`` stops advancing, which
  is exactly what the standby-read staleness invariant must survive;
- **election freeze** — a quorum member skips starting elections while
  frozen, making "who wins the next election" deterministic in drills;
- **partition** — Raft peer calls touching a matching node id are
  dropped with a ``ConnectionError`` (responses ride the same call, so
  one-sided dropping cuts the link both ways);
- **fsync errors** — the next N journal fsyncs raise ``OSError`` at the
  ``LocalJournalSystem._fsync`` choke point: the crash-point drill for
  "latch broken, never ack-then-lose".

``FaultPlan`` sequences such faults (plus cluster actions like
kill/restart-primary) into one deterministic, replayable schedule.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class FaultInjector:
    """Mutable fault state; thread-safe (hooks read under no lock —
    torn reads of independent floats are harmless for chaos knobs)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.read_latency_s: float = 0.0
        self.heartbeat_freeze: bool = False
        self.ufs_error_rate: float = 0.0
        self.rpc_reject_rate: float = 0.0
        self.rpc_reject_retry_after_s: float = 0.05
        self.shm_map_error_rate: float = 0.0
        self.shm_lease_deny_rate: float = 0.0
        self.native_exec_error_rate: float = 0.0
        self.scope: str = ""
        #: HA chaos faults (programmatic; see module docstring)
        self.tailer_freeze_scope: str = ""
        self.election_freeze_scope: str = ""
        self.partitioned: "frozenset[str]" = frozenset()
        self.fsync_errors: int = 0
        #: injected-fault tallies, for tests and fsadmin spelunking
        self.injected = {"read_latency": 0, "heartbeat_freeze": 0,
                         "ufs_error": 0, "rpc_reject": 0,
                         "shm_map_error": 0, "shm_lease_deny": 0,
                         "native_exec_error": 0,
                         "tailer_freeze": 0, "election_freeze": 0,
                         "partition_drop": 0, "fsync_error": 0}
        self._ufs_reads = 0
        self._ufs_failed = 0
        self._rpc_calls = 0
        self._rpc_rejected = 0
        self._shm_maps = 0
        self._shm_map_failed = 0
        self._shm_grants = 0
        self._shm_denied = 0
        self._native_execs = 0
        self._native_failed = 0

    # ----------------------------------------------------------- config
    def configure(self, conf) -> None:
        """Arm from ``atpu.debug.fault.*`` (worker boot calls this)."""
        from alluxio_tpu.conf import Keys

        self.set(
            read_latency_s=conf.get_duration_s(
                Keys.DEBUG_FAULT_READ_LATENCY),
            heartbeat_freeze=conf.get_bool(
                Keys.DEBUG_FAULT_HEARTBEAT_FREEZE),
            ufs_error_rate=conf.get_float(Keys.DEBUG_FAULT_UFS_ERROR_RATE),
            rpc_reject_rate=conf.get_float(
                Keys.DEBUG_FAULT_RPC_REJECT_RATE),
            shm_map_error_rate=conf.get_float(
                Keys.DEBUG_FAULT_SHM_MAP_ERROR_RATE),
            shm_lease_deny_rate=conf.get_float(
                Keys.DEBUG_FAULT_SHM_LEASE_DENY_RATE),
            native_exec_error_rate=conf.get_float(
                Keys.DEBUG_FAULT_NATIVE_EXEC_ERROR_RATE),
            scope=str(conf.get(Keys.DEBUG_FAULT_SCOPE) or ""))

    def set(self, *, read_latency_s: Optional[float] = None,
            heartbeat_freeze: Optional[bool] = None,
            ufs_error_rate: Optional[float] = None,
            rpc_reject_rate: Optional[float] = None,
            shm_map_error_rate: Optional[float] = None,
            shm_lease_deny_rate: Optional[float] = None,
            native_exec_error_rate: Optional[float] = None,
            scope: Optional[str] = None,
            tailer_freeze_scope: Optional[str] = None,
            election_freeze_scope: Optional[str] = None,
            partitioned: Optional[Sequence[str]] = None,
            fsync_errors: Optional[int] = None) -> None:
        global _armed
        with self._lock:
            if read_latency_s is not None:
                self.read_latency_s = max(0.0, float(read_latency_s))
            if heartbeat_freeze is not None:
                self.heartbeat_freeze = bool(heartbeat_freeze)
            if ufs_error_rate is not None:
                self.ufs_error_rate = min(1.0, max(
                    0.0, float(ufs_error_rate)))
            if rpc_reject_rate is not None:
                self.rpc_reject_rate = min(1.0, max(
                    0.0, float(rpc_reject_rate)))
            if shm_map_error_rate is not None:
                self.shm_map_error_rate = min(1.0, max(
                    0.0, float(shm_map_error_rate)))
            if shm_lease_deny_rate is not None:
                self.shm_lease_deny_rate = min(1.0, max(
                    0.0, float(shm_lease_deny_rate)))
            if native_exec_error_rate is not None:
                self.native_exec_error_rate = min(1.0, max(
                    0.0, float(native_exec_error_rate)))
            if scope is not None:
                self.scope = str(scope)
            if tailer_freeze_scope is not None:
                self.tailer_freeze_scope = str(tailer_freeze_scope)
            if election_freeze_scope is not None:
                self.election_freeze_scope = str(election_freeze_scope)
            if partitioned is not None:
                self.partitioned = frozenset(
                    str(p) for p in partitioned if str(p))
            if fsync_errors is not None:
                self.fsync_errors = max(0, int(fsync_errors))
            self._rearm_locked()

    def _rearm_locked(self) -> None:
        global _armed
        _armed = bool(self.read_latency_s or self.heartbeat_freeze
                      or self.ufs_error_rate or self.rpc_reject_rate
                      or self.shm_map_error_rate
                      or self.shm_lease_deny_rate
                      or self.native_exec_error_rate
                      or self.tailer_freeze_scope
                      or self.election_freeze_scope
                      or self.partitioned or self.fsync_errors)

    def reset(self) -> None:
        global _armed
        with self._lock:
            self.read_latency_s = 0.0
            self.heartbeat_freeze = False
            self.ufs_error_rate = 0.0
            self.rpc_reject_rate = 0.0
            self.shm_map_error_rate = 0.0
            self.shm_lease_deny_rate = 0.0
            self.native_exec_error_rate = 0.0
            self.scope = ""
            self.tailer_freeze_scope = ""
            self.election_freeze_scope = ""
            self.partitioned = frozenset()
            self.fsync_errors = 0
            self._ufs_reads = 0
            self._ufs_failed = 0
            self._rpc_calls = 0
            self._rpc_rejected = 0
            self._shm_maps = 0
            self._shm_map_failed = 0
            self._shm_grants = 0
            self._shm_denied = 0
            self._native_execs = 0
            self._native_failed = 0
            for k in self.injected:
                self.injected[k] = 0
            _armed = False

    # ------------------------------------------------------------ hooks
    def _in_scope(self, key: str) -> bool:
        return not self.scope or self.scope in key

    def maybe_sleep_read(self, host: str) -> None:
        if self.read_latency_s > 0 and self._in_scope(host):
            self.injected["read_latency"] += 1
            time.sleep(self.read_latency_s)

    def heartbeat_frozen(self, source: str) -> bool:
        if self.heartbeat_freeze and self._in_scope(source):
            self.injected["heartbeat_freeze"] += 1
            return True
        return False

    def take_ufs_error(self, host: str) -> bool:
        """True when this UFS stripe read should fail.  Deterministic:
        fail whenever the failed/total ratio has fallen behind the
        configured rate — rate 0.25 fails exactly reads 1, 5, 9, ..."""
        rate = self.ufs_error_rate
        if rate <= 0 or not self._in_scope(host):
            return False
        with self._lock:
            self._ufs_reads += 1
            if self._ufs_failed < rate * self._ufs_reads:
                self._ufs_failed += 1
                self.injected["ufs_error"] += 1
                return True
        return False

    def tailer_frozen(self, node: str) -> bool:
        """True while ``node`` matches the tailer-freeze scope: the
        standby's tailer (or Raft apply loop) skips applying, so its
        advertised md_version stops advancing — the staleness-contract
        drill."""
        scope = self.tailer_freeze_scope
        if scope and scope in node:
            self.injected["tailer_freeze"] += 1
            return True
        return False

    def election_frozen(self, node: str) -> bool:
        """True while ``node`` matches the election-freeze scope: the
        member sits out elections (still votes), making drill outcomes
        deterministic."""
        scope = self.election_freeze_scope
        if scope and scope in node:
            self.injected["election_freeze"] += 1
            return True
        return False

    def link_blocked(self, a: str, b: str) -> bool:
        """True when either endpoint of a peer call matches a
        partitioned node id.  Checked on the SENDING side only —
        responses ride the same call, so dropping outbound traffic at
        both members cuts the link bidirectionally."""
        part = self.partitioned
        if not part:
            return False
        for p in part:
            if p in a or p in b:
                self.injected["partition_drop"] += 1
                return True
        return False

    def take_fsync_error(self) -> bool:
        """True when this journal fsync should fail (countdown armed by
        ``fsync_errors=N``): the crash-point drill for the journal's
        latch-broken-never-ack-then-lose contract."""
        if self.fsync_errors <= 0:
            return False
        with self._lock:
            if self.fsync_errors <= 0:
                return False
            self.fsync_errors -= 1
            self.injected["fsync_error"] += 1
            self._rearm_locked()
            return True

    def take_shm_map_error(self, host: str) -> bool:
        """True when this client SHM segment map should fail with an
        injected ``OSError`` — same deterministic failed/total pacing
        as the UFS hook, so the Nth map fails at the same read in
        every run."""
        rate = self.shm_map_error_rate
        if rate <= 0 or not self._in_scope(host):
            return False
        with self._lock:
            self._shm_maps += 1
            if self._shm_map_failed < rate * self._shm_maps:
                self._shm_map_failed += 1
                self.injected["shm_map_error"] += 1
                return True
        return False

    def take_shm_lease_deny(self, host: str) -> bool:
        """True when this worker ``shm_open`` grant should be denied as
        if the lease table were full (deterministic failed/total
        pacing)."""
        rate = self.shm_lease_deny_rate
        if rate <= 0 or not self._in_scope(host):
            return False
        with self._lock:
            self._shm_grants += 1
            if self._shm_denied < rate * self._shm_grants:
                self._shm_denied += 1
                self.injected["shm_lease_deny"] += 1
                return True
        return False

    def take_native_exec_error(self, host: str) -> bool:
        """True when this native fastpath batch should fail mid-table
        (one op poisoned before the call, so earlier ops genuinely
        write before the executor rejects). Same deterministic
        failed/total pacing as the UFS hook — rate 0.5 fails exactly
        batches 1, 3, 5, ..."""
        rate = self.native_exec_error_rate
        if rate <= 0 or not self._in_scope(host):
            return False
        with self._lock:
            self._native_execs += 1
            if self._native_failed < rate * self._native_execs:
                self._native_failed += 1
                self.injected["native_exec_error"] += 1
                return True
        return False

    def take_rpc_reject(self, method_key: str) -> float:
        """Retry-after seconds when this RPC dispatch should be shed
        with an injected ``ResourceExhausted``; 0.0 = admit.  Same
        deterministic failed/total pacing as the UFS hook.  The scope
        substring matches ``method_key`` (``service.method``)."""
        rate = self.rpc_reject_rate
        if rate <= 0 or not self._in_scope(method_key):
            return 0.0
        with self._lock:
            self._rpc_calls += 1
            if self._rpc_rejected < rate * self._rpc_calls:
                self._rpc_rejected += 1
                self.injected["rpc_reject"] += 1
                return self.rpc_reject_retry_after_s
        return 0.0


#: fast-path gate the hook sites check before touching the injector
_armed = False
_injector = FaultInjector()


def injector() -> FaultInjector:
    return _injector


def armed() -> bool:
    return _armed


class InjectedFaultError(IOError):
    """Raised by the UFS hook; a distinct type so tests can tell an
    injected failure from a real one."""


class FaultStep:
    """One scheduled chaos action: at ``at_s`` seconds into the plan,
    call the action named ``action`` with ``kwargs``."""

    __slots__ = ("at_s", "action", "kwargs")

    def __init__(self, at_s: float, action: str, **kwargs) -> None:
        self.at_s = float(at_s)
        self.action = str(action)
        self.kwargs = kwargs

    def __repr__(self) -> str:
        return f"FaultStep({self.at_s}, {self.action!r}, {self.kwargs})"


class FaultPlan:
    """A deterministic, replayable chaos schedule.

    The plan is data (ordered :class:`FaultStep`\\ s); the cluster under
    test supplies the ``actions`` catalog (kill_primary, restart_master,
    freeze_tailer, partition, fail_fsync, delay_elections, ...) — the
    HA minicluster exposes exactly that (``HaCluster.chaos_actions``).
    ``run`` executes steps strictly in schedule order, records an
    execution log (step, wall offset, result/error), and never lets one
    failing step silently skip the rest: errors are logged per step and
    re-raised at the end unless ``continue_on_error``.

    Determinism contract: step ORDER and each action's semantics are
    deterministic; wall-clock offsets are best-effort (the driver
    sleeps to each step's ``at_s``).  Invariant checkers run BETWEEN
    steps via the optional ``between`` callback, so every interleaving
    the plan creates is also observed."""

    def __init__(self, steps: Sequence[FaultStep]) -> None:
        self.steps: List[FaultStep] = sorted(
            steps, key=lambda s: s.at_s)

    def run(self, actions: Dict[str, Callable], *,
            between: Optional[Callable[[FaultStep], None]] = None,
            continue_on_error: bool = False,
            sleep: Callable[[float], None] = time.sleep,
            clock: Callable[[], float] = time.monotonic) -> List[dict]:
        unknown = [s.action for s in self.steps if s.action not in actions]
        if unknown:
            raise KeyError(f"fault plan names unknown actions {unknown}; "
                           f"available: {sorted(actions)}")
        t0 = clock()
        log: List[dict] = []
        first_error: Optional[BaseException] = None
        for step in self.steps:
            wait = t0 + step.at_s - clock()
            if wait > 0:
                sleep(wait)
            entry = {"at_s": step.at_s, "action": step.action,
                     "kwargs": dict(step.kwargs),
                     "ran_at_s": clock() - t0}
            try:
                entry["result"] = actions[step.action](**step.kwargs)
                entry["ok"] = True
            except Exception as e:  # noqa: BLE001 - logged + surfaced below
                entry["ok"] = False
                entry["error"] = f"{type(e).__name__}: {e}"
                if first_error is None:
                    first_error = e
                if not continue_on_error:
                    log.append(entry)
                    raise
            log.append(entry)
            if between is not None:
                between(step)
        if first_error is not None and continue_on_error:
            raise first_error
        return log
