"""Conf-gated fault injection for chaos and self-healing tests.

One process-wide injector (in-process miniclusters deliberately share
it) holds three faults, each scoped by an optional host/source
substring so a multi-worker cluster can break exactly one node:

- **read-latency inflation** — the worker's warm ``read_block`` path
  sleeps per chunk, inflating ``Worker.ReadBlockTime`` so the
  p99-regression health rule (and the remediation engine behind it)
  can be driven end to end;
- **heartbeat freeze** — the worker's metrics reporter silently skips
  its ticks, driving the heartbeat-staleness rule without killing the
  process;
- **UFS error rate** — a deterministic fraction of UFS stripe reads
  fail with an injected ``IOError`` (counter-based, not random: the
  Nth failure lands at the same read in every run);
- **RPC reject rate** — a deterministic fraction of master RPC
  dispatches is shed with the same typed ``ResourceExhausted`` +
  retry-after the admission controller emits, so admission shedding
  and client-side retry-after honoring can be chaos-tested end to end
  without a real flood.  The scope substring matches the RPC's
  ``service.method`` key (e.g. scope ``create_file`` rejects only
  CreateFile).

The hooks are gated on a single module flag, so a production cluster
that never sets ``atpu.debug.fault.*`` pays one attribute read per
hook site.  Everything here is test/chaos machinery: see
``docs/self_healing.md`` for how the remediation tests use it.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class FaultInjector:
    """Mutable fault state; thread-safe (hooks read under no lock —
    torn reads of independent floats are harmless for chaos knobs)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.read_latency_s: float = 0.0
        self.heartbeat_freeze: bool = False
        self.ufs_error_rate: float = 0.0
        self.rpc_reject_rate: float = 0.0
        self.rpc_reject_retry_after_s: float = 0.05
        self.scope: str = ""
        #: injected-fault tallies, for tests and fsadmin spelunking
        self.injected = {"read_latency": 0, "heartbeat_freeze": 0,
                         "ufs_error": 0, "rpc_reject": 0}
        self._ufs_reads = 0
        self._ufs_failed = 0
        self._rpc_calls = 0
        self._rpc_rejected = 0

    # ----------------------------------------------------------- config
    def configure(self, conf) -> None:
        """Arm from ``atpu.debug.fault.*`` (worker boot calls this)."""
        from alluxio_tpu.conf import Keys

        self.set(
            read_latency_s=conf.get_duration_s(
                Keys.DEBUG_FAULT_READ_LATENCY),
            heartbeat_freeze=conf.get_bool(
                Keys.DEBUG_FAULT_HEARTBEAT_FREEZE),
            ufs_error_rate=conf.get_float(Keys.DEBUG_FAULT_UFS_ERROR_RATE),
            rpc_reject_rate=conf.get_float(
                Keys.DEBUG_FAULT_RPC_REJECT_RATE),
            scope=str(conf.get(Keys.DEBUG_FAULT_SCOPE) or ""))

    def set(self, *, read_latency_s: Optional[float] = None,
            heartbeat_freeze: Optional[bool] = None,
            ufs_error_rate: Optional[float] = None,
            rpc_reject_rate: Optional[float] = None,
            scope: Optional[str] = None) -> None:
        global _armed
        with self._lock:
            if read_latency_s is not None:
                self.read_latency_s = max(0.0, float(read_latency_s))
            if heartbeat_freeze is not None:
                self.heartbeat_freeze = bool(heartbeat_freeze)
            if ufs_error_rate is not None:
                self.ufs_error_rate = min(1.0, max(
                    0.0, float(ufs_error_rate)))
            if rpc_reject_rate is not None:
                self.rpc_reject_rate = min(1.0, max(
                    0.0, float(rpc_reject_rate)))
            if scope is not None:
                self.scope = str(scope)
            _armed = bool(self.read_latency_s or self.heartbeat_freeze
                          or self.ufs_error_rate or self.rpc_reject_rate)

    def reset(self) -> None:
        global _armed
        with self._lock:
            self.read_latency_s = 0.0
            self.heartbeat_freeze = False
            self.ufs_error_rate = 0.0
            self.rpc_reject_rate = 0.0
            self.scope = ""
            self._ufs_reads = 0
            self._ufs_failed = 0
            self._rpc_calls = 0
            self._rpc_rejected = 0
            for k in self.injected:
                self.injected[k] = 0
            _armed = False

    # ------------------------------------------------------------ hooks
    def _in_scope(self, key: str) -> bool:
        return not self.scope or self.scope in key

    def maybe_sleep_read(self, host: str) -> None:
        if self.read_latency_s > 0 and self._in_scope(host):
            self.injected["read_latency"] += 1
            time.sleep(self.read_latency_s)

    def heartbeat_frozen(self, source: str) -> bool:
        if self.heartbeat_freeze and self._in_scope(source):
            self.injected["heartbeat_freeze"] += 1
            return True
        return False

    def take_ufs_error(self, host: str) -> bool:
        """True when this UFS stripe read should fail.  Deterministic:
        fail whenever the failed/total ratio has fallen behind the
        configured rate — rate 0.25 fails exactly reads 1, 5, 9, ..."""
        rate = self.ufs_error_rate
        if rate <= 0 or not self._in_scope(host):
            return False
        with self._lock:
            self._ufs_reads += 1
            if self._ufs_failed < rate * self._ufs_reads:
                self._ufs_failed += 1
                self.injected["ufs_error"] += 1
                return True
        return False

    def take_rpc_reject(self, method_key: str) -> float:
        """Retry-after seconds when this RPC dispatch should be shed
        with an injected ``ResourceExhausted``; 0.0 = admit.  Same
        deterministic failed/total pacing as the UFS hook.  The scope
        substring matches ``method_key`` (``service.method``)."""
        rate = self.rpc_reject_rate
        if rate <= 0 or not self._in_scope(method_key):
            return 0.0
        with self._lock:
            self._rpc_calls += 1
            if self._rpc_rejected < rate * self._rpc_calls:
                self._rpc_rejected += 1
                self.injected["rpc_reject"] += 1
                return self.rpc_reject_retry_after_s
        return 0.0


#: fast-path gate the hook sites check before touching the injector
_armed = False
_injector = FaultInjector()


def injector() -> FaultInjector:
    return _injector


def armed() -> bool:
    return _armed


class InjectedFaultError(IOError):
    """Raised by the UFS hook; a distinct type so tests can tell an
    injected failure from a real one."""
