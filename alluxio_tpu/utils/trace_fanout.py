"""HA trace fan-out.

A trace's spans land on whichever master each node heartbeats to — with
standby metrics reads (PR-11), that is NOT always the primary, so a
single-master ``get_trace`` can show a hole exactly where the
interesting hop ran. These helpers query every configured master
endpoint and merge the stitched views back into one (dedup by
``(trace_id, span_id)``), which is what ``fsadmin trace`` and
``/api/v1/master/trace?fanout=1`` serve on HA deployments.
"""

from __future__ import annotations

from typing import List, Sequence

from alluxio_tpu.conf import Keys
from alluxio_tpu.utils.tracing import summarize_traces


def master_endpoints(conf) -> List[str]:
    """Every configured master RPC endpoint (the HA list when set, else
    the single hostname:port)."""
    addrs = str(conf.get(Keys.MASTER_RPC_ADDRESSES) or "")
    eps = [a.strip() for a in addrs.split(",") if a.strip()]
    if not eps:
        eps = [f"{conf.get(Keys.MASTER_HOSTNAME)}:"
               f"{conf.get_int(Keys.MASTER_RPC_PORT)}"]
    return eps


def peer_traces(conf, *, limit: int = 500, prefix: str = "",
                trace_id: str = "",
                exclude: Sequence[str] = ()) -> List[dict]:
    """``get_trace`` against each master endpoint individually (no HA
    failover — the point is each member's own ring + store). A dead or
    unreachable member is skipped: a partial view beats no view during
    exactly the failovers this exists to debug."""
    from alluxio_tpu.rpc.clients import MetaMasterClient

    results: List[dict] = []
    for ep in master_endpoints(conf):
        if ep in exclude:
            continue
        try:
            c = MetaMasterClient(ep, conf=conf, retry_duration_s=3.0)
            r = c.get_trace(limit=limit, prefix=prefix,
                            trace_id=trace_id)
        except Exception:  # noqa: BLE001 - dead member: skip
            continue
        for s in r.get("spans") or ():
            # disambiguate each member's own ring spans — "master"
            # alone would collapse three members into one source
            if s.get("source") == "master":
                s["source"] = f"master@{ep}"
        results.append(r)
    return results


def merge_stitched(base: dict, peers: Sequence[dict]) -> dict:
    """Merge peer ``get_trace`` responses into a base stitched view:
    union of spans (first occurrence wins), re-sorted most-recent-first,
    with the per-trace summary recomputed over the union."""
    spans: List[dict] = list(base.get("spans") or ())
    seen = {(s.get("trace_id"), s.get("span_id")) for s in spans}
    for r in peers:
        for s in r.get("spans") or ():
            key = (s.get("trace_id"), s.get("span_id"))
            if key in seen:
                continue
            seen.add(key)
            spans.append(s)
    spans.sort(key=lambda s: s.get("start_ms") or 0.0, reverse=True)
    return {"spans": spans, "traces": summarize_traces(spans)}
