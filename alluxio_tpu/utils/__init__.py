"""Foundation utilities (reference: ``core/base`` + ``core/common/util``)."""

from alluxio_tpu.utils.uri import AlluxioURI  # noqa: F401
from alluxio_tpu.utils import exceptions  # noqa: F401
