"""Retry policies.

Re-design of ``core/common/src/main/java/alluxio/retry/``
(``RetryPolicy.java``, ``ExponentialBackoffRetry.java``,
``ExponentialTimeBoundedRetry.java``, ``RetryUtils.java``): iterator-style
policies (`attempt()` returns False when exhausted) plus a functional
``retry()`` helper that understands the typed exception codes.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, TypeVar

from alluxio_tpu.utils.exceptions import AlluxioTpuError, RETRYABLE_CODES

#: jitter source shared by all policies (random.Random methods are
#: atomic in CPython; contention is not a concern for backoff jitter)
_SHARED_RNG = random.Random()

T = TypeVar("T")


class RetryPolicy:
    """Iterator-style policy: call ``attempt()`` before each try."""

    def attempt(self) -> bool:
        raise NotImplementedError

    @property
    def attempt_count(self) -> int:
        raise NotImplementedError


class NoRetryPolicy(RetryPolicy):
    def __init__(self) -> None:
        self._count = 0

    def attempt(self) -> bool:
        self._count += 1
        return self._count <= 1

    @property
    def attempt_count(self) -> int:
        return self._count


class CountingRetry(RetryPolicy):
    """N retries with no sleeping."""

    def __init__(self, max_retries: int) -> None:
        self._max = max_retries
        self._count = 0

    def attempt(self) -> bool:
        if self._count > self._max:
            return False
        self._count += 1
        return self._count <= self._max + 1

    @property
    def attempt_count(self) -> int:
        return self._count


class SleepingRetry(RetryPolicy):
    def __init__(self, max_retries: int, sleep_s: float,
                 sleep_fn: Callable[[float], None] = time.sleep) -> None:
        self._max = max_retries
        self._sleep_s = sleep_s
        self._sleep_fn = sleep_fn
        self._count = 0

    def attempt(self) -> bool:
        if self._count == 0:
            self._count = 1
            return True
        if self._count > self._max:
            return False
        self._sleep_fn(self._sleep_s)
        self._count += 1
        return True

    @property
    def attempt_count(self) -> int:
        return self._count


class ExponentialBackoffRetry(RetryPolicy):
    """Exponential backoff with FULL jitter, bounded by retry count.

    Full jitter (sleep uniform in ``[0, backoff]``, AWS-style) rather
    than the earlier ``[backoff/2, backoff]`` band: clients that all
    started retrying a dead primary at the same instant (a failover)
    must decorrelate, not stampede the new leader in half-synchronized
    waves."""

    def __init__(self, base_sleep_s: float, max_sleep_s: float, max_retries: int,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None) -> None:
        self._base = base_sleep_s
        self._max_sleep = max_sleep_s
        self._max_retries = max_retries
        self._sleep_fn = sleep_fn
        self._rng = rng or random.Random()
        self._count = 0

    def _next_sleep(self) -> float:
        backoff = min(self._max_sleep, self._base * (2 ** (self._count - 1)))
        return backoff * self._rng.random()

    def attempt(self) -> bool:
        if self._count == 0:
            self._count = 1
            return True
        if self._count > self._max_retries:
            return False
        self._sleep_fn(self._next_sleep())
        self._count += 1
        return True

    @property
    def attempt_count(self) -> int:
        return self._count


class ExponentialTimeBoundedRetry(RetryPolicy):
    """Exponential backoff bounded by wall-clock duration
    (reference: ``ExponentialTimeBoundedRetry.java``)."""

    def __init__(self, max_duration_s: float, base_sleep_s: float,
                 max_sleep_s: float,
                 time_fn: Callable[[], float] = time.monotonic,
                 sleep_fn: Callable[[float], None] = time.sleep,
                 rng: Optional[random.Random] = None) -> None:
        self._deadline = time_fn() + max_duration_s
        self._base = base_sleep_s
        self._max_sleep = max_sleep_s
        self._time_fn = time_fn
        self._sleep_fn = sleep_fn
        # shared module RNG by default: policies are built per-RPC-call
        # and seeding a fresh Mersenne twister each time showed up in
        # master-bench profiles (~16us/call for jitter nobody needs
        # to be independent)
        self._rng = rng or _SHARED_RNG
        self._count = 0
        self._retry_after_s = 0.0
        self._redirect = False
        self._free_redirects = 3

    def note_retry_after(self, hint_s: float) -> None:
        """Server-supplied backoff hint (admission-control shedding):
        the NEXT sleep is at least this long, so a shed client stops
        hammering at exactly the rate the master asked it to."""
        self._retry_after_s = max(0.0, float(hint_s))

    def note_redirect(self) -> None:
        """HA leader-hint redirect: the failed attempt told us exactly
        where to go (NotPrimaryError.leader), so the NEXT attempt runs
        immediately and does not consume a retry attempt — no sleep, no
        backoff growth.  Bounded per policy instance (a redirect chain
        during failover is a few hops at most): after the budget, a
        redirect loop between two confused masters — each hinting the
        other — degrades to normal backoff instead of a zero-sleep RPC
        spin for the whole retry window."""
        if self._free_redirects > 0:
            self._free_redirects -= 1
            self._redirect = True

    def attempt(self) -> bool:
        now = self._time_fn()
        if self._count == 0:
            self._count = 1
            return True
        if now >= self._deadline:
            return False
        if self._redirect:
            self._redirect = False
            return True
        # FULL jitter (uniform in [0, backoff]): failover makes every
        # client of the dead primary retry in sync — a half-jitter band
        # would stampede the new leader in correlated waves
        backoff = min(self._max_sleep, self._base * (2 ** (self._count - 1)))
        hint, self._retry_after_s = self._retry_after_s, 0.0
        sleep = min(max(hint, backoff * self._rng.random()),
                    max(0.0, self._deadline - now))
        self._sleep_fn(sleep)
        self._count += 1
        return True

    @property
    def attempt_count(self) -> int:
        return self._count


def is_retryable(exc: BaseException) -> bool:
    if isinstance(exc, AlluxioTpuError):
        if exc.code in RETRYABLE_CODES:
            return True
        # an admission-shed RPC (RESOURCE_EXHAUSTED + retry-after hint)
        # is transient overload, not a terminal answer: retry AT the
        # hinted pace.  A hint-less RESOURCE_EXHAUSTED (worker out of
        # space...) stays non-retryable, as before.
        return exc.retry_after_s is not None
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


def retry(fn: Callable[[], T], policy: RetryPolicy,
          retry_on: Callable[[BaseException], bool] = is_retryable) -> T:
    """Run ``fn`` under ``policy``; re-raise the last error when exhausted.
    A typed error carrying ``retry_after_s`` (master admission shedding)
    feeds the hint to policies that can honor it.

    Reference: ``retry/RetryUtils.java``.
    """
    last: Optional[BaseException] = None
    note = getattr(policy, "note_retry_after", None)
    note_redirect = getattr(policy, "note_redirect", None)
    while policy.attempt():
        try:
            return fn()
        except BaseException as e:  # noqa: BLE001 - filtered by retry_on
            if not retry_on(e):
                raise
            last = e
            hint = getattr(e, "retry_after_s", None)
            if hint and note is not None:
                note(hint)
            # a leader-hint redirect (NotPrimaryError.leader) names the
            # exact master to try next: go there NOW, free of charge
            if getattr(e, "leader", None) and note_redirect is not None:
                note_redirect()
    assert last is not None
    raise last
