"""Safe reading of HTTP error bodies.

``HTTPError.read()`` performs real socket IO and can itself raise
(connection reset, timed-out file object) — an exception thrown inside
an ``except HTTPError`` handler escapes the caller's error translation
entirely, turning a well-typed connector error into a raw ``OSError``
(observed: Glue 403 under load surfacing as ``ConnectionResetError``).
Every connector's handler reads bodies through this helper instead.
"""

from __future__ import annotations

import urllib.error


def error_body(e: urllib.error.HTTPError, *, limit: int = 400) -> str:
    """Best-effort decode of an HTTP error response body; never
    raises."""
    try:
        return e.read().decode(errors="replace")[:limit]
    except Exception:  # noqa: BLE001 — body is diagnostic only
        return f"(body unreadable; status {e.code})"


def drain(e: urllib.error.HTTPError) -> None:
    """Consume an error body for connection reuse; never raises."""
    try:
        e.read()
    except Exception:  # noqa: BLE001
        pass
