"""Path/URI type for the namespace.

Re-design of the reference's ``core/base/src/main/java/alluxio/AlluxioURI.java``:
an immutable URI with scheme/authority/path, path algebra (join, parent,
depth, descendant checks) and normalization. Scheme ``atpu://`` plays the role
of ``alluxio://``.
"""

from __future__ import annotations

import posixpath
import re
from typing import Optional, Tuple

SEPARATOR = "/"
_SCHEME_RE = re.compile(r"^([a-zA-Z][a-zA-Z0-9+.\-]*)://(.*)$")


class AlluxioURI:
    """Immutable URI: ``[scheme://[authority]]/normalized/path``."""

    __slots__ = ("_scheme", "_authority", "_path")

    def __init__(self, uri: "str | AlluxioURI", *, scheme: Optional[str] = None,
                 authority: Optional[str] = None, path: Optional[str] = None):
        if isinstance(uri, AlluxioURI):
            self._scheme, self._authority, self._path = (
                uri._scheme, uri._authority, uri._path)
            return
        if path is not None:
            self._scheme = scheme
            self._authority = authority
            self._path = self._normalize(path)
            return
        s = str(uri)
        m = _SCHEME_RE.match(s)
        if m:
            self._scheme = m.group(1)
            rest = m.group(2)
            if SEPARATOR in rest:
                auth, _, p = rest.partition(SEPARATOR)
            else:
                auth, p = rest, ""
            self._authority = auth or None
            self._path = self._normalize(SEPARATOR + p)
        else:
            self._scheme = None
            self._authority = None
            self._path = self._normalize(s)

    @staticmethod
    def _normalize(path: str) -> str:
        if not path:
            return SEPARATOR
        norm = posixpath.normpath(path)
        if norm == ".":
            return SEPARATOR
        if not norm.startswith(SEPARATOR):
            norm = SEPARATOR + norm
        return norm

    # -- accessors ----------------------------------------------------------
    @property
    def scheme(self) -> Optional[str]:
        return self._scheme

    @property
    def authority(self) -> Optional[str]:
        return self._authority

    @property
    def path(self) -> str:
        return self._path

    @property
    def name(self) -> str:
        return posixpath.basename(self._path)

    def is_root(self) -> bool:
        return self._path == SEPARATOR

    def is_absolute(self) -> bool:
        return self._path.startswith(SEPARATOR)

    def has_scheme(self) -> bool:
        return self._scheme is not None

    def depth(self) -> int:
        if self.is_root():
            return 0
        return self._path.count(SEPARATOR)

    # -- algebra ------------------------------------------------------------
    def parent(self) -> Optional["AlluxioURI"]:
        if self.is_root():
            return None
        parent_path = posixpath.dirname(self._path)
        return AlluxioURI("", scheme=self._scheme, authority=self._authority,
                          path=parent_path)

    def join(self, suffix: str) -> "AlluxioURI":
        suffix = suffix.lstrip(SEPARATOR)
        base = self._path if self._path != SEPARATOR else ""
        return AlluxioURI("", scheme=self._scheme, authority=self._authority,
                          path=f"{base}{SEPARATOR}{suffix}")

    def path_components(self) -> Tuple[str, ...]:
        if self.is_root():
            return ()
        return tuple(self._path.strip(SEPARATOR).split(SEPARATOR))

    def is_ancestor_of(self, other: "AlluxioURI") -> bool:
        """True if ``other`` lives strictly under (or at) this path."""
        if self.is_root():
            return True
        mine = self._path.rstrip(SEPARATOR)
        theirs = other._path
        return theirs == mine or theirs.startswith(mine + SEPARATOR)

    # -- std protocol -------------------------------------------------------
    def __str__(self) -> str:
        if self._scheme:
            return f"{self._scheme}://{self._authority or ''}{self._path}"
        return self._path

    def __repr__(self) -> str:
        return f"AlluxioURI({str(self)!r})"

    def __eq__(self, other) -> bool:
        if isinstance(other, str):
            other = AlluxioURI(other)
        if not isinstance(other, AlluxioURI):
            return NotImplemented
        return (self._scheme, self._authority, self._path) == (
            other._scheme, other._authority, other._path)

    def __hash__(self) -> int:
        return hash((self._scheme, self._authority, self._path))

    def __lt__(self, other: "AlluxioURI") -> bool:
        return str(self) < str(other)
