"""Clock abstraction with a manually steppable test clock.

Reference: ``core/common/src/main/java/alluxio/clock/{Clock,SystemClock,
ManualClock}.java`` — the manual clock is what makes TTL/lost-worker tests
deterministic.
"""

from __future__ import annotations

import threading
import time


class Clock:
    def millis(self) -> int:
        raise NotImplementedError

    def seconds(self) -> float:
        return self.millis() / 1000.0


class SystemClock(Clock):
    def millis(self) -> int:
        return time.time_ns() // 1_000_000


class ManualClock(Clock):
    """A clock tests can step forward."""

    def __init__(self, start_ms: int = 0) -> None:
        self._ms = start_ms
        self._lock = threading.Lock()

    def millis(self) -> int:
        with self._lock:
            return self._ms

    def add_time_ms(self, delta_ms: int) -> None:
        with self._lock:
            self._ms += delta_ms

    def set_time_ms(self, ms: int) -> None:
        with self._lock:
            self._ms = ms
