"""Typed exception hierarchy.

Re-design of the reference's ~40-class exception hierarchy
(``core/base/src/main/java/alluxio/exception/``) plus its gRPC status mapping
(``exception/status/``). Each exception carries a wire-stable ``code`` so RPC
boundaries can round-trip typed errors.
"""

from __future__ import annotations

import logging
from typing import Optional


class AlluxioTpuError(Exception):
    """Base class; ``code`` is the wire-stable status name.

    ``retry_after_s`` (optional, set by admission control when it sheds
    an RPC) survives the wire round trip so the client-side retry
    policy can honor the server's backoff hint instead of hammering."""

    code = "INTERNAL"
    retry_after_s: Optional[float] = None
    #: HA redirect hint: the current primary's client RPC address, set by
    #: a standby master shedding a non-read RPC (NotPrimaryError).  The
    #: multi-endpoint client follows it without consuming a retry attempt.
    leader: Optional[str] = None
    #: set on errors a STANDBY raised while serving a read: the answer
    #: reflects bounded-stale state (e.g. NOT_FOUND for a path the
    #: primary just acked).  A strong multi-endpoint client retries such
    #: errors on the primary instead of trusting them (docs/ha.md).
    standby: bool = False

    def to_wire(self) -> dict:
        d = {"code": self.code, "message": str(self),
             "type": type(self).__name__}
        if self.retry_after_s is not None:
            d["retry_after_s"] = float(self.retry_after_s)
        if self.leader is not None:
            d["leader"] = str(self.leader)
        if self.standby:
            d["standby"] = True
        return d

    @staticmethod
    def from_wire(d: dict) -> "AlluxioTpuError":
        cls = _BY_NAME.get(d.get("type"), None)
        if cls is None:
            cls = _BY_CODE.get(d.get("code"), AlluxioTpuError)
        e = cls(d.get("message", ""))
        ra = d.get("retry_after_s")
        if ra is not None:
            e.retry_after_s = float(ra)
        ld = d.get("leader")
        if ld is not None:
            e.leader = str(ld)
        if d.get("standby"):
            e.standby = True
        return e


class FileDoesNotExistError(AlluxioTpuError):
    code = "NOT_FOUND"


class BlockDoesNotExistError(AlluxioTpuError):
    code = "NOT_FOUND"


class FileAlreadyExistsError(AlluxioTpuError):
    code = "ALREADY_EXISTS"


class FileAlreadyCompletedError(AlluxioTpuError):
    code = "FAILED_PRECONDITION"


class FileIncompleteError(AlluxioTpuError):
    code = "FAILED_PRECONDITION"


class DirectoryNotEmptyError(AlluxioTpuError):
    code = "FAILED_PRECONDITION"


class InvalidPathError(AlluxioTpuError):
    code = "INVALID_ARGUMENT"


class InvalidArgumentError(AlluxioTpuError):
    code = "INVALID_ARGUMENT"


class PermissionDeniedError(AlluxioTpuError):
    code = "PERMISSION_DENIED"


class UnauthenticatedError(AlluxioTpuError):
    code = "UNAUTHENTICATED"


class NotFoundError(AlluxioTpuError):
    code = "NOT_FOUND"


class AlreadyExistsError(AlluxioTpuError):
    code = "ALREADY_EXISTS"


class ResourceExhaustedError(AlluxioTpuError):
    code = "RESOURCE_EXHAUSTED"


class WorkerOutOfSpaceError(ResourceExhaustedError):
    pass


class FailedPreconditionError(AlluxioTpuError):
    code = "FAILED_PRECONDITION"


class UnavailableError(AlluxioTpuError):
    """Transient; retryable (master in safe mode, worker not registered...)."""

    code = "UNAVAILABLE"


class SafeModeError(UnavailableError):
    pass


class DeadlineExceededError(AlluxioTpuError):
    code = "DEADLINE_EXCEEDED"


class CancelledError(AlluxioTpuError):
    code = "CANCELLED"


class AbortedError(AlluxioTpuError):
    code = "ABORTED"


class NotSupportedError(AlluxioTpuError):
    code = "UNIMPLEMENTED"


class UfsError(AlluxioTpuError):
    code = "INTERNAL"


class JournalClosedError(UnavailableError):
    pass


class NotPrimaryError(UnavailableError):
    """A standby master refusing a write/non-idempotent RPC.  Carries
    ``leader`` (the current primary's client address, when known) so the
    multi-endpoint client can redirect instead of blind-rotating; code
    UNAVAILABLE keeps it transparently retryable for idempotent ops."""

    def __init__(self, message: str = "", *,
                 leader: Optional[str] = None) -> None:
        super().__init__(message or "this master is not the primary")
        if leader:
            self.leader = str(leader)


class BackupError(AlluxioTpuError):
    code = "INTERNAL"


class JobDoesNotExistError(NotFoundError):
    pass


class ConnectionFailedError(UnavailableError):
    pass


class RegisterLeaseNotFoundError(UnavailableError):
    pass


_ALL = [v for v in list(globals().values())
        if isinstance(v, type) and issubclass(v, AlluxioTpuError)]
_BY_NAME = {c.__name__: c for c in _ALL}
_BY_CODE = {c.code: c for c in reversed(_ALL)}


def register_wire_error(cls: type) -> type:
    """Register an :class:`AlluxioTpuError` subclass defined OUTSIDE this
    module in the wire-serialization map, so :meth:`AlluxioTpuError.
    from_wire` reconstructs the exact type instead of degrading to the
    nearest base class (which silently breaks client-side
    ``except SpecificError`` across RPC).  Usable as a decorator.
    The ``wire-error-unregistered`` lint rule enforces this."""
    _BY_NAME[cls.__name__] = cls
    return cls


#: Status codes that a retry policy should treat as transient.
RETRYABLE_CODES = frozenset({"UNAVAILABLE", "DEADLINE_EXCEEDED", "ABORTED"})


def best_effort(what: str, fn, *args, log: Optional[logging.Logger] = None,
                **kwargs):
    """Run a cleanup/notification step that must never mask the primary
    error path: failures are logged at DEBUG and swallowed.  Replaces
    bare ``try: ... except Exception: pass`` blocks (which the
    ``except-swallow`` lint rule rejects on server paths) with one
    audited idiom."""
    try:
        return fn(*args, **kwargs)
    except Exception:  # noqa: BLE001 - by contract: log and move on
        (log or logging.getLogger(
            getattr(fn, "__module__", None) or __name__)).debug(
            "best-effort %s failed", what, exc_info=True)
        return None
