"""Process pause monitor — the JvmPauseMonitor analogue.

Re-design of ``core/common/src/main/java/alluxio/util/
JvmPauseMonitor.java:42`` (started at ``AlluxioMasterProcess.java:
265-273``): a daemon thread sleeps a short interval and measures the
overshoot. A large overshoot means the PROCESS stalled — GC pressure,
GIL starvation from a native extension, CFS throttling, a swapping
host — exactly the stalls that make heartbeats miss and elections
fire spuriously. Pauses are logged and counted into the metrics
registry so ``fsadmin report``/Prometheus surface them.
"""

from __future__ import annotations

import logging
import threading
import time

LOG = logging.getLogger(__name__)


class PauseMonitor:
    """Sleep-drift stall detector."""

    def __init__(self, *, interval_s: float = 0.5,
                 warn_s: float = 1.0, error_s: float = 5.0,
                 metrics=None) -> None:
        self._interval = interval_s
        self._warn = warn_s
        self._error = error_s
        if metrics is None:
            from alluxio_tpu.metrics import metrics as _m

            metrics = _m()
        self._m = metrics
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()
        self.total_pause_s = 0.0
        self.max_pause_s = 0.0
        # register up front: dashboards must see 0.0 for "healthy",
        # not a missing series that also means "monitor not running"
        self._m.register_gauge("Process.MaxPauseSeconds",
                               lambda: self.max_pause_s)

    # -- detection core (pure; unit-testable without the thread) -----------
    def observe(self, elapsed_s: float) -> float:
        """Record one sleep of ``elapsed_s`` wall seconds against the
        configured interval; returns the pause length (0 when none)."""
        pause = elapsed_s - self._interval
        if pause < self._warn:
            return 0.0
        self.total_pause_s += pause
        self.max_pause_s = max(self.max_pause_s, pause)
        if pause >= self._error:
            self._m.counter("Process.SeverePauses").inc()
            LOG.error("process paused ~%.2fs (GC/GIL/host stall): "
                      "heartbeats and elections may have missed", pause)
        else:
            self._m.counter("Process.Pauses").inc()
            LOG.warning("process paused ~%.2fs", pause)
        return pause

    def _run(self) -> None:
        while not self._stop.is_set():
            t0 = time.monotonic()
            self._stop.wait(self._interval)
            if self._stop.is_set():
                return
            self.observe(time.monotonic() - t0)

    def start(self) -> "PauseMonitor":
        if self._thread is None:
            self._stop.clear()  # restartable after stop()
            self._thread = threading.Thread(
                target=self._run, name="pause-monitor", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


_process_monitor: "PauseMonitor | None" = None
_process_lock = threading.Lock()


def ensure_process_monitor() -> PauseMonitor:
    """ONE monitor per OS process, shared by every in-process role
    (LocalCluster runs master + N workers in one interpreter; a host
    stall is one event, not N+1 counter bumps racing one gauge)."""
    global _process_monitor
    with _process_lock:
        if _process_monitor is None:
            _process_monitor = PauseMonitor().start()
        return _process_monitor
