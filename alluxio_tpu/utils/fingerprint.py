"""UFS fingerprints for metadata sync.

Re-design of ``core/common/src/main/java/alluxio/underfs/Fingerprint.java``:
a fingerprint captures the identity-bearing attributes of a UFS entry
(type, content hash/etag, length, mtime, owner/group/mode). Metadata sync
compares the stored fingerprint with a fresh one to decide whether the
inode must be re-synced, split into *metadata* changes (owner/mode) vs
*content* changes (hash/length).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

INVALID = "INVALID"


@dataclass(frozen=True)
class Fingerprint:
    kind: str = INVALID  # "FILE" | "DIRECTORY" | INVALID
    content_hash: str = "_"
    length: int = -1
    owner: str = "_"
    group: str = "_"
    mode: int = -1

    @staticmethod
    def invalid() -> "Fingerprint":
        return Fingerprint()

    @staticmethod
    def from_status(status) -> "Fingerprint":
        """Build from a ``UfsStatus`` (see ``alluxio_tpu.underfs.base``)."""
        if status is None:
            return Fingerprint.invalid()
        return Fingerprint(
            kind="DIRECTORY" if status.is_directory else "FILE",
            content_hash=status.content_hash or str(status.last_modified_ms or "_"),
            length=status.length if not status.is_directory else -1,
            owner=status.owner or "_",
            group=status.group or "_",
            mode=status.mode if status.mode is not None else -1,
        )

    def is_valid(self) -> bool:
        return self.kind != INVALID

    def serialize(self) -> str:
        return (f"kind={self.kind}|hash={self.content_hash}|len={self.length}"
                f"|owner={self.owner}|group={self.group}|mode={self.mode}")

    @staticmethod
    def parse(s: Optional[str]) -> "Fingerprint":
        if not s:
            return Fingerprint.invalid()
        parts = dict(p.split("=", 1) for p in s.split("|") if "=" in p)
        try:
            return Fingerprint(
                kind=parts.get("kind", INVALID),
                content_hash=parts.get("hash", "_"),
                length=int(parts.get("len", -1)),
                owner=parts.get("owner", "_"),
                group=parts.get("group", "_"),
                mode=int(parts.get("mode", -1)),
            )
        except ValueError:
            return Fingerprint.invalid()

    def matches_content(self, other: "Fingerprint") -> bool:
        return (self.kind == other.kind
                and self.content_hash == other.content_hash
                and self.length == other.length)

    def matches_metadata(self, other: "Fingerprint") -> bool:
        return (self.owner == other.owner and self.group == other.group
                and self.mode == other.mode)

    def matches(self, other: "Fingerprint") -> bool:
        return self.matches_content(other) and self.matches_metadata(other)
