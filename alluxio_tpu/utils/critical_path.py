"""Critical-path analysis over stitched traces.

The read-path microscope's second half (docs/observability.md): given
the spans of one trace — client, worker and master spans stitched by
``trace_id`` — reconstruct the *blocking chain*: the single walk from
the root span's start to its end where, at every instant, the segment
on the chain is whatever the operation was actually blocked on. The
model follows phase-attributed I/O analysis (arxiv 2301.01494): a
parent is blocked on its **last-finishing overlapping child** (hedged
fan-outs: the winner that gated completion, not the cancelled loser),
and time not covered by any child is the span's own *self-time*.

Self-time is then attributed to the span's typed phase events
(``Span.phase``, names from ``tracing.PHASES``). Phases are measured
wall-time slices and may legitimately overlap a child span (the
client's ``wire`` wait contains the server's whole span), so each
span's phases are scaled down proportionally to fit its critical
self-time — nothing double-counts, and the chain still partitions the
root's wall-clock exactly. Self-time not covered by any phase stays on
the span as ``<name>/self`` and counts as *unattributed*: the
``attributed_pct`` figure (gated ≥90% in ``make bench-obs``) is the
share of root wall-clock landing in **named phases**.

``analyze_trace`` handles one trace (``fsadmin trace --critical-path``)
and ``profile`` aggregates many sampled traces into the ranked
per-phase table behind ``get_trace_profile`` /
``/api/v1/master/trace/profile`` / ``fsadmin report readpath``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

#: float slop for interval arithmetic on wall-clock milliseconds
_EPS = 1e-6


def _end_ms(s: dict) -> float:
    return (s.get("start_ms") or 0.0) + (s.get("duration_ms") or 0.0)


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def analyze_trace(spans: List[dict]) -> Optional[dict]:
    """Blocking-chain breakdown of one trace's spans.

    Returns None when no span carries a usable interval. Spans whose
    parent was never shipped (unsampled hop, ring eviction) become
    extra roots; the root whose interval is longest anchors the walk —
    on the read path that is the client op span — and the other roots'
    time is simply not part of this trace's wall-clock.
    """
    usable = [s for s in spans
              if s.get("start_ms") is not None
              and s.get("duration_ms") is not None
              and s.get("span_id")]
    if not usable:
        return None
    by_id = {s["span_id"]: s for s in usable}
    kids: Dict[str, List[dict]] = {}
    roots: List[dict] = []
    for s in usable:
        p = s.get("parent")
        if p and p in by_id and p != s["span_id"]:
            kids.setdefault(p, []).append(s)
        else:
            roots.append(s)
    root = max(roots, key=lambda s: s.get("duration_ms") or 0.0)

    # span_id -> critical self-time; chain segments (span, start, end)
    self_ms: Dict[str, float] = {}
    segments: List[Tuple[dict, float, float]] = []
    on_path: List[dict] = []

    def walk(s: dict, wstart: float, wend: float) -> None:
        # clip to the parent's window: cross-process clock skew must
        # never let a child inflate the chain past its parent
        ws = max(wstart, s["start_ms"])
        we = min(wend, _end_ms(s))
        if we - ws <= _EPS:
            return
        on_path.append(s)
        cursor = we
        for k in sorted(kids.get(s["span_id"], ()),
                        key=_end_ms, reverse=True):
            ke = min(_end_ms(k), cursor)
            ks = max(k.get("start_ms") or 0.0, ws)
            if ke - ks <= _EPS or ke - ws <= _EPS:
                continue  # outside the still-unexplained window
            if cursor - ke > _EPS:
                # gap after this child closed and before the later
                # blocker began: the parent itself was running
                segments.append((s, ke, cursor))
                self_ms[s["span_id"]] = \
                    self_ms.get(s["span_id"], 0.0) + (cursor - ke)
            walk(k, ks, ke)
            cursor = min(cursor, ks)
            if cursor - ws <= _EPS:
                break
        if cursor - ws > _EPS:
            segments.append((s, ws, cursor))
            self_ms[s["span_id"]] = \
                self_ms.get(s["span_id"], 0.0) + (cursor - ws)

    walk(root, root["start_ms"], _end_ms(root))
    wall_ms = _end_ms(root) - root["start_ms"]

    # distribute each span's critical self-time over its phases,
    # scaled so overlapping phase measurements cannot double-count
    seg_ms: Dict[str, float] = {}
    attributed = 0.0
    span_rows: List[dict] = []
    seen_ids = set()
    for s in on_path:
        sid = s["span_id"]
        if sid in seen_ids:
            continue
        seen_ids.add(sid)
        self_t = self_ms.get(sid, 0.0)
        phases = [(str(n), float(ms)) for n, ms in (s.get("phases") or ())
                  if ms is not None and float(ms) > 0.0]
        total_phase = sum(ms for _, ms in phases)
        scale = min(1.0, self_t / total_phase) if total_phase > 0 else 0.0
        row_phases: Dict[str, float] = {}
        for pname, pms in phases:
            got = pms * scale
            row_phases[pname] = row_phases.get(pname, 0.0) + got
            key = f"{s.get('name')}/{pname}"
            seg_ms[key] = seg_ms.get(key, 0.0) + got
            attributed += got
        rest = self_t - sum(row_phases.values())
        if rest > _EPS:
            key = f"{s.get('name')}/self"
            seg_ms[key] = seg_ms.get(key, 0.0) + rest
        span_rows.append({
            "span": s.get("name"), "span_id": sid,
            "source": s.get("source"),
            "start_off_ms": round(s["start_ms"] - root["start_ms"], 3),
            "self_ms": round(self_t, 3),
            "phases": {k: round(v, 3) for k, v in row_phases.items()},
        })
    span_rows.sort(key=lambda r: r["start_off_ms"])
    segments.sort(key=lambda seg: seg[1])
    return {
        "trace_id": root.get("trace_id"),
        "root": root.get("name"),
        "wall_ms": round(wall_ms, 3),
        "spans_on_path": span_rows,
        "chain": [{"span": s.get("name"),
                   "start_off_ms": round(a - root["start_ms"], 3),
                   "ms": round(b - a, 3)}
                  for s, a, b in segments],
        "segments": {k: round(v, 3) for k, v in seg_ms.items()},
        "attributed_ms": round(attributed, 3),
        "attributed_pct": round(100.0 * attributed / wall_ms, 2)
        if wall_ms > _EPS else 0.0,
    }


def profile(spans: List[dict], *, root_prefix: str = "",
            max_traces: int = 256, top: int = 40) -> dict:
    """Ranked per-phase profile over many traces' blocking chains.

    ``spans`` is a flat stitched span list (any order, many traces
    mixed). Traces are analyzed independently; per ``span/phase`` key
    we report count, total/mean self-ms and p50/p99 of the per-trace
    self-ms samples, ranked by total — the table that answers "what is
    the small-read path actually blocked on". ``root_prefix`` keeps
    only traces whose root span name matches (e.g.
    ``atpu.client.remote_read``)."""
    by_trace: Dict[str, List[dict]] = {}
    for s in spans:
        tid = s.get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(s)
    rows: Dict[str, List[float]] = {}
    wall_total = 0.0
    attributed_total = 0.0
    walls: List[float] = []
    analyzed = 0
    for tid, tspans in by_trace.items():
        if analyzed >= max_traces:
            break
        res = analyze_trace(tspans)
        if res is None:
            continue
        if root_prefix and not str(res.get("root") or "").startswith(
                root_prefix):
            continue
        analyzed += 1
        wall_total += res["wall_ms"]
        walls.append(res["wall_ms"])
        attributed_total += res["attributed_ms"]
        for key, ms in res["segments"].items():
            rows.setdefault(key, []).append(ms)
    out_rows = []
    for key, samples in rows.items():
        samples.sort()
        total = sum(samples)
        out_rows.append({
            "key": key,
            "count": len(samples),
            "total_ms": round(total, 3),
            "mean_ms": round(total / len(samples), 3),
            "p50_ms": round(_quantile(samples, 0.50), 3),
            "p99_ms": round(_quantile(samples, 0.99), 3),
            "pct": round(100.0 * total / wall_total, 2)
            if wall_total > _EPS else 0.0,
        })
    out_rows.sort(key=lambda r: -r["total_ms"])
    walls.sort()
    return {
        "traces_analyzed": analyzed,
        "wall_ms_total": round(wall_total, 3),
        "wall_ms_p50": round(_quantile(walls, 0.50), 3),
        "wall_ms_p99": round(_quantile(walls, 0.99), 3),
        "attributed_pct": round(
            100.0 * attributed_total / wall_total, 2)
        if wall_total > _EPS else 0.0,
        "phases": out_rows[:top],
    }
