"""Shared single-file HTML status-page builder for the master/worker
web endpoints (stand-in for the reference's webui-* SPAs, with no build
step): common CSS + JS helpers, per-process sections and render code.
"""

from __future__ import annotations

from typing import Sequence, Tuple

_CSS = """
 body{font-family:system-ui,sans-serif;margin:2rem;color:#222}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
 table{border-collapse:collapse;margin:.5rem 0}
 td,th{border:1px solid #ccc;padding:.25rem .6rem;font-size:.9rem;
       text-align:left}
 code{background:#f4f4f4;padding:0 .3rem}
 #err{color:#b00}
"""

_HELPERS = """
const gb = n => (n/2**30).toFixed(2)+' GiB';
const row = (t, cells, th) => {
  const tr = document.createElement('tr');
  for (const c of cells) {
    const el = document.createElement(th ? 'th' : 'td');
    el.textContent = c; tr.appendChild(el);
  }
  t.appendChild(tr);
  return tr;
};
async function j(p){ const r = await fetch(API + p);
                     if(!r.ok) throw new Error(p+': '+r.status);
                     return r.json(); }
"""


def render(title: str, api_prefix: str,
           sections: Sequence[Tuple[str, str]],
           raw_routes: Sequence[str], js_body: str) -> bytes:
    """Build the page: ``sections`` are (heading, table-element-id);
    ``js_body`` is an async function body using the shared helpers
    (``j``/``row``/``gb``) and ``API``."""
    section_html = "".join(
        f"<h2>{heading}</h2><table id=\"{tid}\"></table>"
        for heading, tid in sections)
    routes = " ".join(f"<code>{r}</code>" for r in raw_routes)
    return (f"""<!doctype html>
<html><head><meta charset="utf-8"><title>{title}</title>
<style>{_CSS}</style></head><body>
<h1>{title}</h1>
<div id="err"></div>
{section_html}
<p>Raw: {routes} <code>/metrics</code> (Prometheus)</p>
<script>
const API = '{api_prefix}';
{_HELPERS}
(async () => {{
  try {{
{js_body}
  }} catch (e) {{
    document.getElementById('err').textContent = e;
  }}
}})();
</script></body></html>
""").encode()
