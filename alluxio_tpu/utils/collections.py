"""Concurrent-ish collections used by the registries and job DAGs.

Re-designs of ``core/base/src/main/java/alluxio/collections/``:
- ``IndexedSet`` (multi-index set backing the master's worker/block
  registries, ``collections/IndexedSet.java``)
- ``DirectedAcyclicGraph`` (job workflow ordering,
  ``collections/DirectedAcyclicGraph.java``)
- ``PrefixList`` (path prefix matching, ``collections/PrefixList.java``)

Python's GIL plus coarse per-structure locks replace the reference's
lock-striped maps; the master uses a single-writer event loop anyway.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, Hashable, Iterable, Iterator, List, Optional, Set, TypeVar

T = TypeVar("T")
K = TypeVar("K", bound=Hashable)


class FieldIndex(Generic[T, K]):
    """Named index over a field extractor; unique or non-unique."""

    def __init__(self, name: str, extractor: Callable[[T], K],
                 unique: bool = False) -> None:
        self.name = name
        self.extractor = extractor
        self.unique = unique


class IndexedSet(Generic[T]):
    """A set queryable by any registered field index."""

    def __init__(self, *indexes: FieldIndex) -> None:
        if not indexes:
            raise ValueError("at least one index required")
        self._indexes: Dict[str, FieldIndex] = {ix.name: ix for ix in indexes}
        self._maps: Dict[str, Dict[Hashable, Set[T]]] = {
            ix.name: {} for ix in indexes}
        self._items: Set[T] = set()
        self._lock = threading.RLock()

    def add(self, item: T) -> bool:
        with self._lock:
            if item in self._items:
                return False
            # validate every unique constraint BEFORE touching any index so a
            # violation leaves the set untouched
            for name, ix in self._indexes.items():
                if ix.unique:
                    key = ix.extractor(item)
                    if self._maps[name].get(key):
                        raise ValueError(
                            f"unique index {name} already has key {key!r}")
            for name, ix in self._indexes.items():
                key = ix.extractor(item)
                self._maps[name].setdefault(key, set()).add(item)
            self._items.add(item)
            return True

    def remove(self, item: T) -> bool:
        with self._lock:
            if item not in self._items:
                return False
            self._items.discard(item)
            for name, ix in self._indexes.items():
                key = ix.extractor(item)
                bucket = self._maps[name].get(key)
                if bucket is not None:
                    bucket.discard(item)
                    if not bucket:
                        del self._maps[name][key]
            return True

    def get_by(self, index: str, key: Hashable) -> Set[T]:
        with self._lock:
            return set(self._maps[index].get(key, ()))

    def get_first_by(self, index: str, key: Hashable) -> Optional[T]:
        with self._lock:
            bucket = self._maps[index].get(key)
            return next(iter(bucket)) if bucket else None

    def contains_by(self, index: str, key: Hashable) -> bool:
        with self._lock:
            return key in self._maps[index]

    def remove_by(self, index: str, key: Hashable) -> int:
        with self._lock:
            victims = list(self._maps[index].get(key, ()))
            for v in victims:
                self.remove(v)
            return len(victims)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def __iter__(self) -> Iterator[T]:
        with self._lock:
            return iter(list(self._items))

    def __contains__(self, item: T) -> bool:
        with self._lock:
            return item in self._items


class DirectedAcyclicGraph(Generic[T]):
    """DAG with payloads; supports topological iteration of roots/leaves."""

    def __init__(self) -> None:
        self._parents: Dict[T, Set[T]] = {}
        self._children: Dict[T, Set[T]] = {}
        self._lock = threading.RLock()

    def add(self, node: T, parents: Iterable[T] = ()) -> None:
        with self._lock:
            parents = list(parents)
            for p in parents:
                if p not in self._parents:
                    raise ValueError(f"unknown parent {p!r}")
            if node in self._parents:
                raise ValueError(f"node {node!r} already present")
            if any(self._reaches(node, p) for p in parents):
                raise ValueError("cycle detected")
            self._parents[node] = set(parents)
            self._children[node] = set()
            for p in parents:
                self._children[p].add(node)

    def _reaches(self, src: T, dst: T) -> bool:
        if src == dst:
            return True
        stack = [src]
        seen = set()
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            for c in self._children.get(n, ()):
                if c not in seen:
                    seen.add(c)
                    stack.append(c)
        return False

    def remove(self, node: T) -> None:
        with self._lock:
            if self._children.get(node):
                raise ValueError(f"node {node!r} still has children")
            for p in self._parents.pop(node, ()):
                self._children[p].discard(node)
            self._children.pop(node, None)

    def roots(self) -> List[T]:
        with self._lock:
            return [n for n, ps in self._parents.items() if not ps]

    def children(self, node: T) -> Set[T]:
        with self._lock:
            return set(self._children.get(node, ()))

    def parents(self, node: T) -> Set[T]:
        with self._lock:
            return set(self._parents.get(node, ()))

    def topological_order(self) -> List[T]:
        with self._lock:
            indeg = {n: len(ps) for n, ps in self._parents.items()}
            order: List[T] = []
            frontier = [n for n, d in indeg.items() if d == 0]
            while frontier:
                n = frontier.pop()
                order.append(n)
                for c in self._children.get(n, ()):
                    indeg[c] -= 1
                    if indeg[c] == 0:
                        frontier.append(c)
            return order

    def __contains__(self, node: T) -> bool:
        with self._lock:
            return node in self._parents

    def __len__(self) -> int:
        with self._lock:
            return len(self._parents)


class PrefixList:
    """Path-prefix membership test (reference: ``PrefixList.java``)."""

    def __init__(self, prefixes: Iterable[str]) -> None:
        self._prefixes = [p for p in (s.strip() for s in prefixes) if p]

    def in_list(self, path: str) -> bool:
        return any(path.startswith(p) for p in self._prefixes)

    def out_list(self, path: str) -> bool:
        return not self.in_list(path)
