"""Stripe planning shared by the worker's cold-fetch pipeline
(``worker/ufs_fetch.py``) and the client's parallel remote reads
(``client/remote_read.py``) — one implementation so a future change to
the striping math (alignment, rounding) cannot silently diverge
between the two halves of the data plane."""

from __future__ import annotations

from typing import List, Tuple


def plan_stripes(length: int, stripe_size: int) -> List[Tuple[int, int]]:
    """(range-relative offset, length) per stripe; empty for
    ``length <= 0`` — callers that need a completion event for empty
    ranges add their own sentinel."""
    if length <= 0:
        return []
    stripe_size = max(1, stripe_size)
    return [(off, min(stripe_size, length - off))
            for off in range(0, length, stripe_size)]
